"""Benchmark: GPT-2 training throughput on the available chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` = achieved MFU / 0.35 (the BASELINE.json north-star MFU
for ZeRO-3 GPT-2 pretraining).  Extra detail goes to stderr.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def peak_flops_per_chip(backend: str) -> float:
    """bf16 peak. v5e: 197 TFLOP/s. CPU fallback: nominal 1e12 so the
    script still reports a number in dev environments."""
    if backend in ("tpu", "axon"):
        return 197e12
    return 1e12


def main():
    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    backend = jax.default_backend()
    n_dev = jax.device_count()
    on_tpu = backend in ("tpu", "axon")
    log(f"backend={backend} devices={n_dev}")

    import dataclasses

    # 124M fits without activation recompute at this batch — remat would
    # burn 1/3 extra flops for memory we don't need
    cfg = dataclasses.replace(gpt2.GPT2_SMALL, remat=False) if on_tpu else gpt2.GPT2_TINY
    seq = 1024 if on_tpu else 128
    micro_bs = 8 if on_tpu else 2
    gas = 4 if on_tpu else 1  # amortizes per-dispatch host latency
    steps = 8 if on_tpu else 3

    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1 if n_dev > 1 else 0},
        "mesh": {"fsdp": n_dev, "data": 1} if n_dev > 1 else None,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    config = {k: v for k, v in config.items() if v is not None}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )

    dp = engine.mesh_info.dp_world_size
    global_bs = micro_bs * gas * dp
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {"input_ids": rng.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)}

    # warmup / compile (input pipeline = threaded device prefetch,
    # standard practice; batch transfer overlaps the compiled step)
    t0 = time.time()
    for batch in engine.prefetch_loader(batches(2)):
        loss = engine.train_batch(batch)
    log(f"compile+2 steps: {time.time()-t0:.1f}s loss={float(loss):.3f}")

    # best-of-2 timing windows: remote/tunneled TPU paths occasionally
    # hiccup for seconds — one bad window must not poison the record
    dt = float("inf")
    for _ in range(2):
        t0 = time.time()
        for batch in engine.prefetch_loader(batches(steps)):
            loss = engine.train_batch(batch)
        # a true sync: pull the scalar to host (block_until_ready is not
        # a reliable barrier on remote/tunneled backends)
        loss = float(loss)
        dt = min(dt, (time.time() - t0) / steps)

    tokens_per_step = global_bs * seq
    tokens_per_sec = tokens_per_step / dt
    tokens_per_sec_chip = tokens_per_sec / n_dev

    # Training FLOPs/token ≈ 6*N + 12*L*D*seq (attention term)
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq
    achieved = tokens_per_sec_chip * flops_per_token
    mfu = achieved / peak_flops_per_chip(backend)
    log(
        f"step={dt*1000:.1f}ms tokens/s/chip={tokens_per_sec_chip:,.0f} "
        f"model={n_params/1e6:.0f}M seq={seq} MFU={mfu*100:.1f}%"
    )

    print(
        json.dumps(
            {
                "metric": f"gpt2_{n_params//1_000_000}M_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.35, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
