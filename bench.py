"""Benchmark driver: GPT-2/BERT training + inference rungs on the available chip(s).

Prints ONE JSON line to stdout (the driver's record):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` = achieved MFU / 0.35 (the BASELINE.json north-star MFU
for ZeRO-3 GPT-2 pretraining).  Every other rung's record is appended to
BENCH_EXTRA.json the moment it is measured; all detail goes to stderr
with a running-clock timestamp.

Architecture (round 4): the parent process runs NO JAX at all — it
schedules each rung as a child ``python bench.py --rung NAME`` with a
hard per-rung timeout and a global deadline (BENCH_DEADLINE_S, default
1620s < the driver's 1800s window).  A rung that would not fit the
remaining budget is SKIPPED and the skip recorded; a rung that hangs is
killed at its cap and recorded as timed out; the parent always exits 0
with whatever completed.  Child exit also frees that rung's HBM and
host state unconditionally — no cross-rung teardown risk.  Rung order
puts the never-yet-driver-verified inference rungs directly after the
headline, before the long training rungs.

Note on the 1.5B north-star config: full fp32 Adam state for GPT-2 XL
is ~18GB > 16GB HBM, so a single chip needs ZeRO-Offload streaming
(tools/train_xl_onchip.py, BENCH_CAPABILITY.json); GPT-2 Large (774M)
is the largest rung that fits fully on-device.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

START = time.time()
HERE = os.path.dirname(os.path.abspath(__file__))
EXTRA_PATH = os.path.join(HERE, "BENCH_EXTRA.json")
BENCH_JSON_PATH = os.path.join(HERE, "BENCH.json")
HISTORY_PATH = os.path.join(HERE, "bench_history.jsonl")
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", 1620))


def log(msg):
    print(f"[bench +{time.time() - START:6.1f}s] {msg}", file=sys.stderr, flush=True)


def remaining() -> float:
    return DEADLINE_S - (time.time() - START)


def append_capability_record(rec: dict) -> None:
    """Dedup-append one record (by metric name) to BENCH_CAPABILITY.json
    — the shared writer for capability tools (train_xl_onchip,
    bench_neo27_decode); bench.py's own rungs use BENCH_EXTRA.json,
    which every run clears."""
    cap_path = os.path.join(HERE, "BENCH_CAPABILITY.json")
    recs = []
    if os.path.exists(cap_path):
        with open(cap_path) as f:
            recs = [r for r in json.load(f) if r.get("metric") != rec["metric"]]
    recs.append(rec)
    with open(cap_path, "w") as f:
        json.dump(recs, f, indent=1)


def peak_flops_per_chip(backend: str) -> float:
    """bf16 peak per chip — the ONE table in
    profiling.flops_profiler.PEAK_TFLOPS_BY_PLATFORM, so the analytic
    MFU here and the telemetry gauge's share a denominator."""
    from deepspeed_tpu.profiling.flops_profiler import peak_flops

    return peak_flops("tpu" if backend in ("tpu", "axon") else backend)


# ---------------------------------------------------------------------------
# child-side rung implementations
# ---------------------------------------------------------------------------

def _setup_jax_cache():
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # dev knob: the image's sitecustomize registers the TPU-tunnel
        # backend regardless of JAX_PLATFORMS; pin back to CPU here
        jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() in ("tpu", "axon"):
        # Persistent compilation cache (TPU only): the big rungs' graphs
        # (unrolled 124M step, 48-layer XL decode) cost minutes of
        # compile; a warm cache turns repeat runs into pure execution.
        # NOT enabled on CPU — XLA:CPU AOT artifacts are machine-feature
        # sensitive on these VMs (see tests/conftest.py note).
        cache_dir = os.path.join(HERE, ".jax_cache_tpu")
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
            log(f"compilation cache: {cache_dir}")
        except Exception as e:  # noqa: BLE001
            log(f"compilation cache unavailable: {e}")


def _timed_steps(engine, batches, steps, label):
    """Compile+warm, then best-of-2 timing windows with a true host sync
    (one bad window must not poison the record).  Returns ``(dt,
    phases)`` — ``phases`` is the engine StepTimeline's per-step mean
    over the final window (data_wait/compute/ckpt_stall attribution;
    docs/performance.md), emitted into every training record.

    ``DS_BENCH_RUN_API=1`` drives ``engine.train_batches`` (N steps in
    ONE compiled lax.scan; semantics pinned by
    tests/test_engine.py::test_train_batches_matches_per_step_loop)."""
    # default OFF on the tunnel: the scanned multi-step program's carry
    # double-buffer copies of the big state cost MORE than the per-step
    # dispatch they save (774M: 271 vs 234 ms/step, r5 measured; see
    # docs/design-notes.md) — flip on for backends where dispatch
    # dominates
    use_run = hasattr(engine, "train_batches") and not getattr(engine, "_offload", False)
    use_run = use_run and os.environ.get("DS_BENCH_RUN_API", "0") == "1"
    # DS_TB_UNROLL: "full" = fully unrolled (no while loop), an int
    # k >= 2 = partial unroll (k step bodies per while iteration, carry
    # copies amortize 1/k), unset/""/"1" = plain scan.  "1" deliberately
    # means the same as engine.train_batches(unroll=1) — the two
    # surfaces used to give the literal 1 opposite meanings (ADVICE r5)
    _u = os.environ.get("DS_TB_UNROLL", "")
    if _u == "full":
        tb_unroll = True
    elif _u and not _u.isdigit():
        raise SystemExit(f"DS_TB_UNROLL must be an integer or 'full', got {_u!r}")
    else:
        tb_unroll = int(_u) if _u else False  # 1 == plain scan, like the engine
    t0 = time.time()
    if use_run:
        # warm with the SAME n=steps program the windows time — an
        # n=2 warmup would leave window 1 paying the real compile
        losses = engine.train_batches(list(batches(steps)), unroll=tb_unroll)
        loss = float(losses[-1])
    else:
        for batch in engine.prefetch_loader(batches(2)):
            loss = engine.train_batch(batch)
        loss = float(loss)
    log(f"[{label}] compile+2 steps: {time.time()-t0:.1f}s loss={loss:.3f}")
    dt = float("inf")
    for _ in range(2):
        t0 = time.time()
        if use_run:
            losses = engine.train_batches(list(batches(steps)), unroll=tb_unroll)
            loss = float(losses[-1])
        else:
            for batch in engine.prefetch_loader(batches(steps)):
                loss = engine.train_batch(batch)
            loss = float(loss)
        dt = min(dt, (time.time() - t0) / steps)
    phases = engine.timeline.summary(steps)
    log(f"[{label}] timing windows done; {engine.timeline.format_summary(steps)}")
    return dt, phases


def _device_or_host_init(family_mod, cfg, on_tpu):
    """On TPU, generate the random init on-chip (minutes of host→device
    upload become seconds of on-chip generation); on CPU keep the host
    init for dev-environment parity."""
    import jax.numpy as jnp

    if on_tpu:
        t0 = time.time()
        p = family_mod.init_params_device(cfg, dtype=jnp.float32)
        log(f"device init: {time.time()-t0:.1f}s")
        return p
    return family_mod.init_params(cfg)


def bench_model(cfg, micro_bs, gas, seq, steps, zero_stage, label, opt_params=None):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    n_dev = jax.device_count()
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": zero_stage},
        "mesh": {"fsdp": n_dev, "data": 1} if n_dev > 1 else None,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4, **(opt_params or {})}},
        "steps_per_print": 10_000,
    }
    config = {k: v for k, v in config.items() if v is not None}
    params = _device_or_host_init(gpt2, cfg, on_tpu and cfg.n_experts == 0)
    log(f"[{label}] params ready; building engine")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=params, config=config, tp_spec_fn=tp_fn
    )
    log(f"[{label}] engine ready")

    dp = engine.mesh_info.dp_world_size
    global_bs = micro_bs * gas * dp
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {"input_ids": rng.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)}

    dt, phases = _timed_steps(engine, batches, steps, label)

    if engine._sanitizer is not None:
        # ds_san guards/signatures perturb the thing being measured;
        # never let a sanitized number look like a clean record
        log(f"[{label}] WARNING: ds_san is armed — timings include sanitizer overhead")

    comm = engine.comm_summary()
    tel = engine.telemetry.summary() if getattr(engine, "telemetry", None) is not None else {}
    tokens_per_sec_chip = global_bs * seq / dt / n_dev
    # Training FLOPs/token ≈ 6*N + 12*L*D*seq (attention term)
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq
    mfu = tokens_per_sec_chip * flops_per_token / peak_flops_per_chip(backend)
    log(
        f"[{label}] step={dt*1000:.1f}ms tokens/s/chip={tokens_per_sec_chip:,.0f} "
        f"model={n_params/1e6:.0f}M seq={seq} zero={zero_stage} MFU={mfu*100:.1f}% "
        f"(telemetry gauge: {tel.get('mfu')})"
    )
    return {
        "metric": f"gpt2_{n_params//1_000_000}M_zero{zero_stage}_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu_pct": round(mfu * 100, 2),
        "step_ms": round(dt * 1000, 1),
        # per-phase attribution (overlap subsystem; docs/performance.md)
        "steps_per_s": round(1.0 / dt, 3),
        "data_wait_ms": phases.get("data_wait_ms", 0.0),
        "ckpt_stall_ms": phases.get("ckpt_stall_ms", 0.0),
        # comm layer (docs/comm.md): active grad-exchange strategy + the
        # per-step comm-bytes model
        "comm_strategy": comm["strategy"],
        "comm_bytes_per_step": comm["grad_exchange_bytes"],
        # telemetry plane (docs/telemetry.md): the live compiled-cost
        # MFU gauge (NB the scan caveat: truthful when the layer loop is
        # unrolled, as the headline rung's config is), HBM bytes/step
        # from the executable's cost analysis, and the snapshot digest
        "mfu": tel.get("mfu"),
        "hbm_bytes_per_step": tel.get("hbm_bytes_per_step"),
        "telemetry": tel.get("telemetry"),
        "micro_bs": micro_bs,
        "gas": gas,
        "seq": seq,
        **({"ds_san": True} if engine._sanitizer is not None else {}),
        **({"supervision": True} if getattr(engine, "_supervision", None) is not None else {}),
    }


def zero3_comm_record(big_cfg, big_result, gas, fsdp=8):
    """ZeRO allgather bandwidth — the third BASELINE.json metric.

    One tunneled chip has no ICI neighbors, so the rung reports the
    HLO-validated byte model (tests/test_zero_comm.py pins it against
    compiled HLO) divided by the MEASURED single-chip step time: the
    all-gather bandwidth ZeRO-3 demands of each chip's interconnect
    to hold this step time at fsdp=8, vs the v5e ICI roofline
    (1600 Gbps/chip ≈ 200 GB/s).  Reference context: the allgather
    tail is the perf-critical end of every ZeRO step (stage2.py:1489)."""
    from deepspeed_tpu.runtime.zero.stages import zero_step_comm_model

    n_params = big_cfg.num_params()
    comm = zero_step_comm_model(n_params, fsdp=fsdp, stage=3, gas=gas)
    step_s = big_result["step_ms"] / 1e3
    demand_gbps = comm["all-gather"] / step_s / 1e9
    ici_gbps = 200.0  # v5e: 1600 Gbit/s/chip aggregate ICI
    log(
        f"[zero3-comm] allgather {comm['all-gather']/1e9:.2f} GB/step (model, "
        f"fsdp={fsdp}) / {step_s*1e3:.0f} ms -> demand {demand_gbps:.0f} GB/s "
        f"= {100*demand_gbps/ici_gbps:.0f}% of v5e ICI ({ici_gbps:.0f} GB/s)"
    )
    return {
        "metric": "zero3_allgather_gbps",
        "value": round(demand_gbps, 1),
        "unit": "GB/s demanded of ICI at measured step time (fsdp=8)",
        "allgather_bytes_per_step": comm["all-gather"],
        "reduce_scatter_bytes_per_step": comm["reduce-scatter"],
        "ici_roofline_gbps": ici_gbps,
        "ici_share_pct": round(100 * demand_gbps / ici_gbps, 1),
    }


def bench_bert(seq: int, micro_bs: int, gas: int, steps: int):
    """BERT-Large MLM+NSP pretraining samples/s — a BASELINE.json metric
    (reference: 64 TFLOPS / 272 samples/s @seq128, 53 TFLOPS / 52
    samples/s @seq512 on 1x V100-32GB, fastest-bert blog :15-16; those
    reference numbers use their own batch sizes — micro_bs is recorded
    in the emitted record so comparisons stay apples-to-apples)."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import bert

    n_dev = jax.device_count()
    on_tpu = jax.default_backend() in ("tpu", "axon")
    base = bert.BERT_LARGE if on_tpu else bert.BERT_TINY
    seq_req = seq  # metric names key on the REQUESTED seq so CPU-dev
    seq = min(seq, base.max_position_embeddings)  # clamped runs don't collide
    cfg = dataclasses.replace(base, remat=False, scan_unroll=base.num_hidden_layers)
    model_fn, init_fn, tp_fn = bert.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    params = _device_or_host_init(bert, cfg, on_tpu)
    label = f"bert-large-s{seq}"
    log(f"[{label}] params ready; building engine")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=params, config=config, tp_spec_fn=tp_fn
    )
    log(f"[{label}] engine ready")
    global_bs = micro_bs * gas * engine.mesh_info.dp_world_size
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            ids = rng.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)
            yield {
                "input_ids": ids,
                "masked_lm_labels": np.where(rng.random((global_bs, seq)) < 0.15, ids, -100).astype(np.int32),
                "next_sentence_label": rng.integers(0, 2, (global_bs,), dtype=np.int32),
            }

    dt, phases = _timed_steps(engine, batches, steps, label)
    comm = engine.comm_summary()
    tel = engine.telemetry.summary() if getattr(engine, "telemetry", None) is not None else {}
    samples_s = global_bs / dt / n_dev
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    tflops = samples_s * seq * flops_per_token / 1e12
    log(
        f"[{label}] step={dt*1000:.1f}ms samples/s/chip={samples_s:,.1f} "
        f"achieved={tflops:.1f} TFLOP/s (ref V100: {'272 samples/s / 64 TF' if seq == 128 else '52 samples/s / 53 TF'})"
    )
    return {
        "metric": f"bert_large_seq{seq_req}_train_samples_per_sec_per_chip",
        "value": round(samples_s, 1),
        "unit": "samples/s",
        "achieved_tflops": round(tflops, 1),
        "steps_per_s": round(1.0 / dt, 3),
        "data_wait_ms": phases.get("data_wait_ms", 0.0),
        "ckpt_stall_ms": phases.get("ckpt_stall_ms", 0.0),
        "comm_strategy": comm["strategy"],
        "comm_bytes_per_step": comm["grad_exchange_bytes"],
        "mfu": tel.get("mfu"),
        "hbm_bytes_per_step": tel.get("hbm_bytes_per_step"),
        "telemetry": tel.get("telemetry"),
        "micro_bs": micro_bs,
        "gas": gas,
        "seq": seq,
        **({"ds_san": True} if engine._sanitizer is not None else {}),
        **({"supervision": True} if getattr(engine, "_supervision", None) is not None else {}),
    }


def bench_inference(model_name: str, quantize_bits: int, label: str,
                    kv_cache_dtype: str = "model", prompt_len: int = 128):
    """Decode throughput: tokens/s in the steady KV-cache decode loop
    (reference inference kernels claim 2-4x fp16 / 3-5x int8,
    docs/_posts/2021-05-05-inference-kernel-optimization.md:55)."""
    import jax

    import deepspeed_tpu

    on_tpu = jax.default_backend() in ("tpu", "axon")
    t0 = time.time()
    engine = deepspeed_tpu.init_inference(
        model=model_name, quantize_bits=quantize_bits, max_out_tokens=512,
        kv_cache_dtype=kv_cache_dtype, init_on_device=on_tpu,
    )
    log(f"[{label}] engine ready in {time.time()-t0:.1f}s")
    # dev (CPU/tiny) runs shrink the windows to fit the model's n_positions
    B, T, short, long_ = (8, prompt_len, 16, 128) if on_tpu else (4, 32, 8, 64)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, engine.model_config.vocab_size, (B, T), dtype=np.int32)

    def run(new):
        t0 = time.time()
        out = engine.generate(prompt, max_new_tokens=new, do_sample=False)
        _ = int(np.asarray(out)[0, -1])  # true sync
        return time.time() - t0

    run(short)  # compile short
    log(f"[{label}] short generate compiled")
    run(long_)  # compile long
    log(f"[{label}] long generate compiled")
    t_s = min(run(short) for _ in range(3))
    t_l = min(run(long_) for _ in range(3))
    # marginal decode rate: the (t_l - t_s) window is pure decode.
    # Tunnel/dispatch noise can exceed the window on a bad run and
    # produce a negative or absurd rate — fail the rung rather than
    # record garbage (the parent then marks it skipped with rc=1).
    delta = t_l - t_s
    if delta <= max(0.05 * t_l, 1e-3):
        raise RuntimeError(
            f"decode timing windows not separable: t_short={t_s:.2f}s "
            f"t_long={t_l:.2f}s (noise >= decode delta)"
        )
    tok_s = B * (long_ - short) / delta
    log(f"[{label}] decode tokens/s={tok_s:,.0f} (B={B}, prompt={T}; t_short={t_s:.2f}s t_long={t_l:.2f}s)")
    return {
        "metric": f"{model_name.replace('-', '_')}_{label}_decode_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "batch": B,
        "prompt_len": T,
    }


def run_rung(name: str):
    """Child-process entry: run one rung, print its record(s) as JSON
    lines on stdout."""
    import jax

    from deepspeed_tpu.models import gpt2

    _setup_jax_cache()
    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    log(f"rung={name} backend={backend} devices={jax.device_count()}")

    def emit(rec):
        """Print the record the moment it is measured — the parent's
        timeout salvage reads partial child stdout, so buffering until
        rung end would lose completed measurements on a cap kill."""
        rec.setdefault("backend", backend)
        print(json.dumps(rec), flush=True)

    if name == "headline":
        if on_tpu:
            # 124M fits without activation recompute at this batch — remat
            # would burn 1/3 extra flops for memory we don't need; full
            # layer-loop unroll kills the scan's dynamic-slice/copy
            # bookkeeping (~50ms/step) at the cost of a longer compile
            # steps=32: the timing window's final host sync (~100ms RTT on
            # the tunnel) amortizes over the window — 6-8-step windows were
            # charging ~10ms/step of measurement artifact to the record
            cfg = dataclasses.replace(gpt2.GPT2_SMALL, remat=False, scan_unroll=gpt2.GPT2_SMALL.n_layer)
            emit(bench_model(cfg, micro_bs=8, gas=4, seq=1024, steps=32, zero_stage=0, label="124M"))
        else:
            emit(bench_model(gpt2.GPT2_TINY, micro_bs=2, gas=1, seq=128, steps=3, zero_stage=0, label="tiny"))
    elif name == "decode-bf16":
        emit(bench_inference("gpt2-xl" if on_tpu else "tiny", 0, "bf16"))
    elif name == "decode-int8":
        emit(bench_inference("gpt2-xl" if on_tpu else "tiny", 8, "int8"))
    elif name == "neo-bf16":
        emit(bench_inference("gpt-neo-2.7b" if on_tpu else "tiny", 0, "bf16"))
    elif name == "neo-int8":
        emit(bench_inference("gpt-neo-2.7b" if on_tpu else "tiny", 8, "int8"))
    elif name == "decode-longctx":
        # long-context decode, SAME-harness quantization ratio: at
        # prompt 384 the KV-cache read rivals the weight read, so int8
        # weights + int8 KV attack both roofline terms at once
        m = "gpt2-xl" if on_tpu else "tiny"
        pl = 384 if on_tpu else 32
        r_bf = bench_inference(m, 0, "longctx-bf16", prompt_len=pl)
        emit(r_bf)
        r_q = bench_inference(m, 8, "longctx-int8w-int8kv", kv_cache_dtype="int8", prompt_len=pl)
        r_q["speedup_vs_bf16_same_harness"] = round(r_q["value"] / max(r_bf["value"], 1e-9), 3)
        emit(r_q)
    elif name == "774M-zero3":
        # Big-model rung: 774M with full on-device fp32 Adam state
        # (params 3.1G + m/v 6.2G ≈ 9.3G at gas==1), round-4 MFU
        # configuration — see tools/sweep_774m.py for the measured ladder.
        big = dataclasses.replace(
            gpt2.GPT2_LARGE if on_tpu else gpt2.GPT2_TINY, remat=True, xent_chunk_size=512,
            remat_save_names=("qkv", "ffn_pre", "attn_o", "attn_lse"),
        )
        # steps=32: see the headline rung's window-length note
        mb, sq, st = (4, 1024, 32) if on_tpu else (2, 128, 3)
        r = bench_model(big, micro_bs=mb, gas=1, seq=sq, steps=st, zero_stage=3, label="774M-zero3")
        emit(r)
        try:
            # derived metric must never cost the measured primary rung
            emit(zero3_comm_record(big, r, gas=1))
        except Exception as e:  # noqa: BLE001
            log(f"[zero3-comm] FAILED: {str(e)[:200]}")
    elif name == "bert-s128":
        emit(bench_bert(seq=128, micro_bs=64 if on_tpu else 2, gas=1, steps=24 if on_tpu else 3))
    elif name == "bert-s512":
        emit(bench_bert(seq=512, micro_bs=16 if on_tpu else 2, gas=1, steps=24 if on_tpu else 3))
    elif name == "longctx-train":
        # long-context TRAINING: sparse (BigBird splash) vs dense flash
        # inside the full train step at 16k — the reference's headline
        # long-seq claim is "up to 6.3x" (sparse-attention blog :32);
        # same harness as tools/bench_long_context.py, driver-captured
        from tools.bench_long_context import make_record, run_mode

        seq, n_layer = (16384, 8) if on_tpu else (512, 2)
        steps = 4 if on_tpu else 2
        dt_f, tok_f = run_mode("flash", seq, n_layer, steps)
        dt_s, tok_s = run_mode("sparse", seq, n_layer, steps)
        rec = make_record(seq, n_layer, dt_f, tok_f, dt_s, tok_s)
        # baseline = the reference's 6.3x sparse-over-dense claim.  NB
        # the denominator is OUR dense path, which r5.1 made 2.19x
        # faster at 16k (splash-dense routing) — the reference ratio was
        # against its own unimproved dense; vs the r5.0 dense path the
        # same sparse step measures ~11.9x (see the record note)
        rec["vs_baseline"] = round(rec["sparse_over_dense"] / 6.3, 3)
        emit(rec)
    elif name == "serving":
        # request-level SLO rung (docs/serving.md): seeded Poisson
        # arrivals against the continuous-batching engine — p50/p99
        # TTFT, per-token latency and tokens/s at several offered loads,
        # bf16-KV and int8-KV slot pools.  Grandchild process like
        # comm-strategies (its own engine builds + HBM lifetime).
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_serving.py")]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "serving", "skipped": True,
                  "reason": f"bench_serving child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "fleet":
        # fleet failover rung (docs/serving.md §Fleet): 3-replica
        # FleetRouter under seeded Poisson load, one replica killed
        # mid-run and supervised back in the background — the emitted
        # failover_over_steady_p99 ratio is the fleet proof bound
        # (admitted p99 TTFT <= 2x steady-state).  Grandchild like the
        # serving rung (its own engine builds + HBM lifetime).
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_serving.py"),
               "--fleet"]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "fleet", "skipped": True,
                  "reason": f"bench_serving --fleet child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "kvcache":
        # paged-KV rung (docs/serving.md §Paged KV & prefix caching):
        # an 80%-shared system-prompt batch plus 3-turn sessions run
        # with the cache on vs off under the same schedule — the
        # emitted x_prefill_flops reduction is the dedup proof bound
        # (>= 2x at bit-identical greedy outputs, lower TTFT p50).
        # Grandchild like the serving rung.
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_serving.py"),
               "--kvcache"]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "kvcache", "skipped": True,
                  "reason": f"bench_serving --kvcache child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "elastic":
        # elastic-fleet rung (docs/serving.md §Elastic fleet): an
        # autoscaled fleet under ~10x one replica's offered load with a
        # forced mid-surge scale-down + live KV migration — the emitted
        # record carries aggregate tokens/s, admitted-p99 TTFT over
        # steady state, shed rate, and scale reaction times.
        # Grandchild like the serving rung.
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_serving.py"),
               "--elastic"]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "elastic", "skipped": True,
                  "reason": f"bench_serving --elastic child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "kvtiers":
        # KV-tiering rung (docs/serving.md §KV tiering): a session fleet
        # whose parked KV working set is ~4x the device page pool vs an
        # all-HBM reference under the same schedule — the emitted record
        # carries tokens/s at 4x oversubscription, the T0-resident
        # overhead ratio, and the swap-hide ratio at bit-identical
        # greedy outputs.  Grandchild like the serving rung.
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_serving.py"),
               "--kvtiers"]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "kvtiers", "skipped": True,
                  "reason": f"bench_serving --kvtiers child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "tenants":
        # mixed-tenant isolation rung (docs/serving.md §Front-door): a
        # quiet tenant's seeded stream run solo vs next to a noisy
        # tenant offered 10x its token-bucket quota — the emitted
        # record gates the quiet tenant's admitted p99 TTFT in the
        # mixed run (isolation breaking = the number inflates past the
        # noise band).  Grandchild like the serving rung.
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_serving.py"),
               "--tenants"]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "tenants", "skipped": True,
                  "reason": f"bench_serving --tenants child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "sharding":
        # weight-update-sharding sweep (docs/sharding.md): replicated vs
        # cross-replica ZeRO-1 (vs the composed data x fsdp grid) —
        # update-phase FLOPs/bytes per replica from compiled cost
        # analysis, opt-state bytes, the one params-sized all-gather,
        # loss parity.  Grandchild like comm-strategies (the CPU case
        # forces the 8-device dryrun mesh before ITS jax import).
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_sharding.py")]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "sharding", "skipped": True,
                  "reason": f"bench_sharding child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "kernels":
        # Pallas kernel microbench (docs/kernels.md): lax reference vs
        # fused flash-decode (bf16 + int8 KV, 2k/16k context) and the
        # one-pass fused optimizer update — speedup, parity error, and
        # compiled-cost HBM bytes per cell.  Grandchild like serving
        # (its own engine-free jax lifetime; --dryrun shapes on CPU).
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_kernels.py")]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            emit({"metric": "kernels", "skipped": True,
                  "reason": f"bench_kernels child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    elif name == "comm-strategies":
        # dense vs int8 vs 1-bit grad exchange + 1-bit LAMB, on the 124M
        # and bert-s512 configs (docs/comm.md).  Runs in a grandchild so
        # the CPU case can force the 8-device dryrun mesh (XLA_FLAGS must
        # be set before ITS jax import; this child's jax is already up).
        import subprocess as sp

        cmd = [sys.executable, os.path.join(HERE, "tools", "bench_comm.py")]
        if not on_tpu:
            cmd.append("--dryrun")
        proc = sp.run(cmd, stdout=sp.PIPE, cwd=HERE)
        recs = _parse_records(proc.stdout.decode(errors="replace"))
        if proc.returncode != 0 and not recs:
            # same contract as the parent's _run_child: a dead sweep must
            # leave a failure record, not a silently empty rung
            emit({"metric": "comm-strategies", "skipped": True,
                  "reason": f"bench_comm child rc={proc.returncode}"})
        for rec in recs:
            emit(rec)
    else:
        raise SystemExit(f"unknown rung '{name}'")


# ---------------------------------------------------------------------------
# parent-side scheduler
# ---------------------------------------------------------------------------

# (name, est_s, cap_s): skipped when remaining budget < est_s; child is
# killed at cap_s.  Estimates assume a warm compile cache; caps bound
# the cold-cache case so one slow rung cannot eat the rungs behind it.
RUNGS = [
    ("headline", 240, 600),
    ("decode-bf16", 210, 420),
    ("decode-int8", 210, 420),
    ("774M-zero3", 300, 540),
    ("bert-s128", 180, 360),
    ("bert-s512", 240, 420),
    # 2.7B-class serving (BASELINE ladder's final rung) — runs last so
    # the core rungs can never be starved by it; warm-cache cost ~100s
    # each (measured r4: full 7-rung suite finished in 338s of 1620)
    ("neo-bf16", 150, 360),
    ("neo-int8", 150, 360),
    # same-harness long-context quantization ratio (bf16 vs int8w+int8kv
    # in ONE child); measured r5 warm ~200s
    ("decode-longctx", 260, 480),
    # 16k sparse-vs-dense TRAINING (two engine builds; dense 16k steps
    # are ~2.2s each, so the measurement itself is ~30s warm)
    ("longctx-train", 240, 480),
    # Pallas kernel microbench: fused flash-decode + fused optimizer
    # update vs their lax/XLA references (docs/kernels.md); standalone
    # jits only, no engine builds — cheap
    ("kernels", 120, 300),
    # weight-update-sharding sweep: replicated vs cross-replica ZeRO-1
    # update-phase FLOPs/bytes per strategy (docs/sharding.md); 3
    # engine builds in one grandchild
    ("sharding", 180, 420),
    # comm-strategy sweep: dense vs int8 vs 1-bit grad exchange + 1-bit
    # LAMB on the 124M / bert-s512 pair (docs/comm.md); ~7 engine builds
    # in one grandchild, so it runs last
    ("comm-strategies", 240, 480),
    # request-level serving SLO sweep (docs/serving.md): one gpt2-xl
    # int8-weight engine reused across 2 kv dtypes x 3 offered loads in
    # a grandchild; measured dryrun ~60s, TPU budget dominated by the
    # engine build + one prefill/decode compile pair per pool
    ("serving", 240, 480),
    # fleet failover proof (docs/serving.md §Fleet): 3 replica engines +
    # 1 capacity anchor + 1 supervised rebuild in a grandchild; the
    # record carries failover_over_steady_p99 for the <=2x bound
    ("fleet", 240, 480),
    # paged-KV dedup proof (docs/serving.md §Paged KV & prefix caching):
    # the same shared-prefix + session schedule with the cache on vs
    # off in a grandchild; the record carries x_prefill_flops for the
    # >=2x bound at bit-identical greedy outputs
    ("kvcache", 240, 480),
    # elastic-fleet proof (docs/serving.md §Elastic fleet): autoscaled
    # fleet at ~10x one replica's offered load + forced mid-surge
    # scale-down with live KV migration in a grandchild; the record
    # carries elastic_over_steady_p99 and scale reaction times
    ("elastic", 240, 480),
    # KV-tiering proof (docs/serving.md §KV tiering): a ~4x-oversubscribed
    # session working set over HBM -> host -> disk tiers vs an all-HBM
    # reference in a grandchild; the record carries tokens/s at 4x, the
    # T0-resident overhead ratio, and swap_hidden_ratio at bit-identical
    # greedy outputs with zero queue-full rejections
    ("kvtiers", 240, 480),
    # mixed-tenant isolation proof (docs/serving.md §Front-door): one
    # noisy tenant offered 10x its token-bucket quota next to a quiet
    # tenant's fixed seeded stream; the record gates the quiet tenant's
    # admitted p99 TTFT under contention (plus the noisy throttle rate)
    ("tenants", 240, 480),
]

# Plausibility floors for each rung's PRIMARY record on REAL TPU —
# 2-5x below the measured r4 values, so they only trip on catastrophic
# stalls (the shared dev tunnel was observed delivering a ~20x-slow
# rung while neighboring rungs ran at full speed).  A sub-floor rung
# is retried ONCE if the budget allows and the better run is kept.
# CPU dev runs (BENCH_FORCE_CPU=1) skip the floors.
RUNG_FLOORS = {
    "headline": 40_000,      # tokens/s/chip (normal ~120k)
    "decode-bf16": 200,      # tokens/s (normal ~1000)
    "decode-int8": 200,      # tokens/s (normal ~1400)
    "774M-zero3": 6_000,     # tokens/s/chip (normal ~17.7k)
    "bert-s128": 100,        # samples/s (normal ~390)
    "bert-s512": 20,         # samples/s (normal ~78)
    "neo-bf16": 200,         # tokens/s (normal ~930)
    "neo-int8": 200,         # tokens/s (normal ~1450)
    "decode-longctx": 150,   # tokens/s, first (bf16) record (normal ~770)
    "longctx-train": 15_000,  # sparse tokens/s at 16k (normal ~91k)
}


def _parse_records(out: str):
    recs = []
    for line in out.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return recs


def _apply_injection(rec: dict) -> dict:
    """CI perf-sentinel knob: ``DS_BENCH_INJECT=pattern:scale[,...]``
    scales matching metrics' values (e.g. ``decode:0.9`` = a synthetic
    10% decode-tokens/s regression).  The record is marked ``injected``
    so a doctored number can never pass as a measurement."""
    spec = os.environ.get("DS_BENCH_INJECT", "")
    if not spec or not isinstance(rec.get("value"), (int, float)):
        return rec
    for part in spec.split(","):
        pat, _, scale = part.partition(":")
        if pat and scale and pat in rec.get("metric", ""):
            rec = dict(
                rec,
                value=round(rec["value"] * float(scale), 4),
                injected={"pattern": pat, "scale": float(scale)},
            )
            log(f"INJECTED {pat}:{scale} -> {rec['metric']} = {rec['value']}")
    return rec


def _run_child(name: str, budget: float):
    """Run one rung child; returns (records, failure_reason|None)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--rung", name],
            stdout=subprocess.PIPE, timeout=budget, cwd=HERE,
            # children (and the grandchild sweeps they spawn) must not
            # append bench history themselves — the parent is the one
            # writer for a driver run (regression.history_append gates)
            env={**os.environ, "DS_BENCH_CHILD": "1"},
        )
    except subprocess.TimeoutExpired as e:
        log(f"[{name}] TIMED OUT at {budget:.0f}s — killed")
        # salvage complete records the child printed before the cap
        recs = _parse_records((e.stdout or b"").decode(errors="replace"))
        return recs, None if recs else f"timed out at {budget:.0f}s"
    out = proc.stdout.decode(errors="replace")
    recs = _parse_records(out)
    if proc.returncode != 0:
        log(f"[{name}] FAILED rc={proc.returncode}")
        return recs, None if recs else f"child rc={proc.returncode}"
    return recs, None


def _load_regression():
    """Import telemetry/regression.py by FILE PATH: the parent process
    runs no jax at all (children own the chip), and going through the
    ``deepspeed_tpu`` package __init__ would initialize a backend.  The
    module is deliberately stdlib-only, so this is safe."""
    import importlib.util

    path = os.path.join(HERE, "deepspeed_tpu", "telemetry", "regression.py")
    spec = importlib.util.spec_from_file_location("_ds_bench_regression", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main():
    _regression = _load_regression()
    git_sha, history_append, new_run_id = (
        _regression.git_sha, _regression.history_append, _regression.new_run_id
    )

    extra = []
    if os.path.exists(EXTRA_PATH):
        os.remove(EXTRA_PATH)  # never let a stale record outlive this run

    def flush_extra():
        with open(EXTRA_PATH, "w") as f:
            json.dump(extra, f, indent=1)

    # consolidated machine-readable summary (rung -> headline metrics):
    # rewritten after every rung so the trajectory survives a cap kill,
    # finalized at the end — no more parsing log tails to recover a run
    run_id = new_run_id()
    sha = git_sha(HERE)
    rung_summary = {}

    def flush_bench_json(done=False):
        doc = {
            "schema": 1,
            "ts": time.time(),
            "run_id": run_id,
            "git_sha": sha,
            "complete": done,
            "wall_s": round(time.time() - START, 1),
            "rungs": rung_summary,
        }
        tmp = BENCH_JSON_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, BENCH_JSON_PATH)

    headline_printed = False
    skip_big = os.environ.get("BENCH_SKIP_BIG") == "1"
    retries_used = 0

    active = [r for r in RUNGS if not (skip_big and r[0] != "headline")]
    only = [s for s in os.environ.get("BENCH_RUNGS", "").split(",") if s]
    if only:
        # CI perf-sentinel subset (and a dev convenience): run only the
        # named rungs, in ladder order
        active = [r for r in active if r[0] in only]
    for i, (name, est, cap) in enumerate(active):
        rest_est = sum(e for _, e, _ in active[i + 1:])
        # the rung must fit inside its own kill cap: launching when
        # remaining()-45 < est would start a rung predicted to be
        # killed, burning the budget of every rung behind it
        if remaining() - 45 < est:
            log(f"[{name}] SKIPPED: {remaining():.0f}s left < {est}s estimate + 45s teardown")
            extra.append({"metric": name, "skipped": True,
                          "reason": f"{remaining():.0f}s budget left < {est}s estimate + 45s teardown"})
            rung_summary[name] = {"skipped": True, "reason": "budget"}
            flush_extra()
            flush_bench_json()
            continue
        budget = min(cap, remaining() - 45)
        log(f"[{name}] launching (cap {budget:.0f}s, {remaining():.0f}s left)")
        records, fail_reason = _run_child(name, budget)

        # floors apply only to REAL TPU measurements — the child stamps
        # every record with the backend it actually ran on (a dev box
        # without the tunnel falls back to tiny CPU models whose values
        # sit far below the TPU floors)
        on_real_tpu = bool(records) and records[0].get("backend") in ("tpu", "axon")
        floor = RUNG_FLOORS.get(name) if on_real_tpu else None
        primary = records[0].get("value") if records else None
        # retry-worthy: an implausibly slow TPU measurement (sub-floor),
        # OR a cap-kill that salvaged nothing — the most violent form of
        # the same shared-tunnel stall (mild stalls finish under the cap
        # with a sub-floor value; hard ones never reach a record at all)
        suspect = (floor is not None and primary is not None and primary < floor) or (
            fail_reason is not None and "timed out" in fail_reason and not records
        )
        if (
            suspect
            and retries_used < 2  # a persistent stall must not turn every rung into two
            and remaining() - 45 - est >= rest_est  # never starve the ladder behind
        ):
            retries_used += 1
            reason = fail_reason or f"value {primary} < floor {floor}"
            log(f"[{name}] suspect result ({reason}) — retrying once")
            records2, fail2 = _run_child(name, min(cap, remaining() - 45 - rest_est))
            kept_retry = bool(records2) and (
                primary is None or records2[0].get("value", 0) > primary
            )
            if kept_retry:
                records, fail_reason = records2, fail2
            # the selection is asymmetric (only sub-floor runs retry, and
            # max wins) — record BOTH attempts so the bias is visible in
            # BENCH_EXTRA.json rather than silently folded into the value
            if records:
                records[0] = dict(
                    records[0],
                    retry={
                        "reason": reason,
                        "kept": "retry" if kept_retry else "first",
                        "first_value": primary,
                        "retry_value": records2[0].get("value") if records2 else None,
                    },
                )

        if fail_reason is not None and not records:
            extra.append({"metric": name, "skipped": True, "reason": fail_reason})
            rung_summary[name] = {"skipped": True, "reason": fail_reason}
            flush_extra()
            flush_bench_json()
        records = [_apply_injection(rec) for rec in records]
        for rec in records:
            if name == "headline" and not headline_printed and "vs_baseline" in rec:
                # the driver records this line — print it the moment the
                # headline rung lands so nothing later can lose it
                print(json.dumps({k: rec[k] for k in ("metric", "value", "unit", "vs_baseline")}), flush=True)
                headline_printed = True
            extra.append(rec)
            flush_extra()
            log(f"[{name}] recorded: {rec.get('metric')} = {rec.get('value')}")
        if records:
            keep = ("metric", "value", "unit", "vs_baseline", "mfu_pct",
                    "step_ms", "backend", "injected")
            rung_summary[name] = {
                "records": [
                    {k: r[k] for k in keep if k in r} for r in records
                    if not r.get("skipped")
                ],
            }
            flush_bench_json()
            # persistent bench history (docs/performance.md §Regression
            # workflow): one schema'd line per measured record, keyed by
            # (rung, metric, config fingerprint, git sha, backend)
            try:
                n = history_append(records, rung=name, path=HISTORY_PATH,
                                   run_id=run_id, sha=sha)
                if n:
                    log(f"[{name}] bench_history += {n} line(s)")
            except Exception as e:  # noqa: BLE001 — history must not kill a bench
                log(f"[{name}] bench_history append FAILED: {e}")

    if not headline_printed:
        # honest failure record — still parseable by the driver
        print(json.dumps({
            "metric": "gpt2_124M_zero0_train_tokens_per_sec_per_chip",
            "value": 0, "unit": "tokens/s/chip", "vs_baseline": 0,
            "error": "headline rung did not complete",
        }), flush=True)
    flush_bench_json(done=True)
    log(f"done in {time.time()-START:.0f}s; {sum(1 for r in extra if not r.get('skipped'))} records, "
        f"{sum(1 for r in extra if r.get('skipped'))} skips; summary -> {BENCH_JSON_PATH}")


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--rung":
        run_rung(sys.argv[2])
    else:
        main()
