"""Benchmark: GPT-2 training throughput on the available chip(s).

Prints ONE JSON line (the driver's record):
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` = achieved MFU / 0.35 (the BASELINE.json north-star MFU
for ZeRO-3 GPT-2 pretraining).  Extra detail goes to stderr, and the
big-model point (the largest GPT-2 whose full fp32 Adam state fits one
chip's HBM) is appended to BENCH_EXTRA.json.

Note on the 1.5B north-star config: full fp32 Adam state for GPT-2 XL
is ~18GB > 16GB HBM, so a single chip needs ZeRO-Offload — which works
(tests/test_offload.py) but is not benchable through a tunneled TPU
whose host<->device link measures ~10MB/s (one grad fetch would take
minutes).  GPT-2 Large (774M) is the largest rung that fits fully
on-device; the XL point becomes meaningful at fsdp>=2.
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def peak_flops_per_chip(backend: str) -> float:
    """bf16 peak. v5e: 197 TFLOP/s. CPU fallback: nominal 1e12 so the
    script still reports a number in dev environments."""
    if backend in ("tpu", "axon"):
        return 197e12
    return 1e12


def _timed_steps(engine, batches, steps, label):
    """Compile+warm, then best-of-2 timing windows with a true host sync
    (block_until_ready is not a reliable barrier on tunneled backends;
    one bad window must not poison the record)."""
    t0 = time.time()
    for batch in engine.prefetch_loader(batches(2)):
        loss = engine.train_batch(batch)
    log(f"[{label}] compile+2 steps: {time.time()-t0:.1f}s loss={float(loss):.3f}")
    dt = float("inf")
    for _ in range(2):
        t0 = time.time()
        for batch in engine.prefetch_loader(batches(steps)):
            loss = engine.train_batch(batch)
        loss = float(loss)
        dt = min(dt, (time.time() - t0) / steps)
    return dt


def bench_model(cfg, micro_bs, gas, seq, steps, zero_stage, label):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    backend = jax.default_backend()
    n_dev = jax.device_count()
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": zero_stage},
        "mesh": {"fsdp": n_dev, "data": 1} if n_dev > 1 else None,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    config = {k: v for k, v in config.items() if v is not None}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )

    dp = engine.mesh_info.dp_world_size
    global_bs = micro_bs * gas * dp
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            yield {"input_ids": rng.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)}

    dt = _timed_steps(engine, batches, steps, label)

    tokens_per_sec_chip = global_bs * seq / dt / n_dev
    # Training FLOPs/token ≈ 6*N + 12*L*D*seq (attention term)
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.n_embd * seq
    mfu = tokens_per_sec_chip * flops_per_token / peak_flops_per_chip(backend)
    log(
        f"[{label}] step={dt*1000:.1f}ms tokens/s/chip={tokens_per_sec_chip:,.0f} "
        f"model={n_params/1e6:.0f}M seq={seq} zero={zero_stage} MFU={mfu*100:.1f}%"
    )
    return {
        "metric": f"gpt2_{n_params//1_000_000}M_zero{zero_stage}_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu_pct": round(mfu * 100, 2),
        "step_ms": round(dt * 1000, 1),
    }


def bench_bert(seq: int, micro_bs: int, gas: int, steps: int):
    """BERT-Large MLM+NSP pretraining samples/s — a BASELINE.json metric
    (reference: 64 TFLOPS / 272 samples/s @seq128, 53 TFLOPS / 52
    samples/s @seq512 on 1x V100-32GB, fastest-bert blog :15-16)."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import bert

    n_dev = jax.device_count()
    cfg = dataclasses.replace(
        bert.BERT_LARGE, remat=False, scan_unroll=bert.BERT_LARGE.num_hidden_layers
    )
    model_fn, init_fn, tp_fn = bert.make_model(cfg)
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(), config=config, tp_spec_fn=tp_fn
    )
    global_bs = micro_bs * gas * engine.mesh_info.dp_world_size
    rng = np.random.default_rng(0)

    def batches(n):
        for _ in range(n):
            ids = rng.integers(0, cfg.vocab_size, (global_bs, seq), dtype=np.int32)
            yield {
                "input_ids": ids,
                "masked_lm_labels": np.where(rng.random((global_bs, seq)) < 0.15, ids, -100).astype(np.int32),
                "next_sentence_label": rng.integers(0, 2, (global_bs,), dtype=np.int32),
            }

    dt = _timed_steps(engine, batches, steps, f"bert-large-s{seq}")
    samples_s = global_bs / dt / n_dev
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq
    tflops = samples_s * seq * flops_per_token / 1e12
    log(
        f"[bert-large-s{seq}] step={dt*1000:.1f}ms samples/s/chip={samples_s:,.1f} "
        f"achieved={tflops:.1f} TFLOP/s (ref V100: {'272 samples/s / 64 TF' if seq == 128 else '52 samples/s / 53 TF'})"
    )
    return {
        "metric": f"bert_large_seq{seq}_train_samples_per_sec_per_chip",
        "value": round(samples_s, 1),
        "unit": "samples/s",
        "achieved_tflops": round(tflops, 1),
    }


def bench_inference(model_name: str, quantize_bits: int, label: str):
    """Decode throughput: tokens/s in the steady KV-cache decode loop
    (reference inference kernels claim 2-4x fp16 / 3-5x int8,
    docs/_posts/2021-05-05-inference-kernel-optimization.md:55)."""
    import deepspeed_tpu

    engine = deepspeed_tpu.init_inference(
        model=model_name, quantize_bits=quantize_bits, max_out_tokens=512
    )
    B, T = 8, 128
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, engine.model_config.vocab_size, (B, T), dtype=np.int32)

    def run(new):
        t0 = time.time()
        out = engine.generate(prompt, max_new_tokens=new, do_sample=False)
        _ = int(np.asarray(out)[0, -1])  # true sync
        return time.time() - t0

    run(16)  # compile short
    run(128)  # compile long
    t16 = min(run(16) for _ in range(2))
    t128 = min(run(128) for _ in range(2))
    # marginal decode rate: the (t128 - t16) window is pure decode
    tok_s = B * (128 - 16) / (t128 - t16)
    log(f"[{label}] decode tokens/s={tok_s:,.0f} (B={B}, prompt={T}; t16={t16:.2f}s t128={t128:.2f}s)")
    return {
        "metric": f"{model_name.replace('-', '_')}_{label}_decode_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
    }


def main():
    import jax

    from deepspeed_tpu.models import gpt2

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    log(f"backend={backend} devices={jax.device_count()}")

    if on_tpu:
        # Persistent compilation cache (TPU only): the big rungs' graphs
        # (unrolled 124M step, 48-layer XL decode) cost minutes of
        # compile; a warm cache turns repeat runs into pure execution.
        # NOT enabled on CPU — XLA:CPU AOT artifacts are machine-feature
        # sensitive on these VMs (see tests/conftest.py note).
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache_tpu")
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
            log(f"compilation cache: {cache_dir}")
        except Exception as e:  # noqa: BLE001
            log(f"compilation cache unavailable: {e}")

    # Headline: 124M fits without activation recompute at this batch —
    # remat would burn 1/3 extra flops for memory we don't need
    if on_tpu:
        # full layer-loop unroll: kills the scan's dynamic-slice/copy
        # bookkeeping (~50ms/step) at the cost of a ~2x longer compile
        cfg = dataclasses.replace(gpt2.GPT2_SMALL, remat=False, scan_unroll=gpt2.GPT2_SMALL.n_layer)
        headline = bench_model(cfg, micro_bs=8, gas=4, seq=1024, steps=8, zero_stage=0, label="124M")
    else:
        headline = bench_model(gpt2.GPT2_TINY, micro_bs=2, gas=1, seq=128, steps=3, zero_stage=0, label="tiny")

    # the driver records this line — print it BEFORE the long extras so
    # a timeout can't lose the headline
    print(json.dumps({k: headline[k] for k in ("metric", "value", "unit", "vs_baseline")}), flush=True)

    extra = []
    extra_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_EXTRA.json")
    if os.path.exists(extra_path):
        os.remove(extra_path)  # never let a stale record outlive this run

    def try_point(fn, label):
        import gc

        try:
            extra.append(fn())
            with open(extra_path, "w") as f:
                json.dump(extra, f, indent=1)
        except Exception as e:  # noqa: BLE001 — later points must still run
            log(f"[{label}] FAILED: {str(e)[:300]}")
        finally:
            # free the previous rung's HBM (a 774M training engine holds
            # ~12GB of state) before the next engine initializes
            gc.collect()

    def zero3_comm_rung(big_cfg, big_result, gas, fsdp=8):
        """ZeRO allgather bandwidth — the third BASELINE.json metric.

        One tunneled chip has no ICI neighbors, so the rung reports the
        HLO-validated byte model (tests/test_zero_comm.py pins it against
        compiled HLO) divided by the MEASURED single-chip step time: the
        all-gather bandwidth ZeRO-3 demands of each chip's interconnect
        to hold this step time at fsdp=8, vs the v5e ICI roofline
        (1600 Gbps/chip ≈ 200 GB/s).  Reference context: the allgather
        tail is the perf-critical end of every ZeRO step
        (stage2.py:1489)."""
        from deepspeed_tpu.runtime.zero.stages import zero_step_comm_model

        n_params = big_cfg.num_params()
        comm = zero_step_comm_model(n_params, fsdp=fsdp, stage=3, gas=gas)
        step_s = big_result["step_ms"] / 1e3
        demand_gbps = comm["all-gather"] / step_s / 1e9
        ici_gbps = 200.0  # v5e: 1600 Gbit/s/chip aggregate ICI
        log(
            f"[zero3-comm] allgather {comm['all-gather']/1e9:.2f} GB/step (model, "
            f"fsdp={fsdp}) / {step_s*1e3:.0f} ms -> demand {demand_gbps:.0f} GB/s "
            f"= {100*demand_gbps/ici_gbps:.0f}% of v5e ICI ({ici_gbps:.0f} GB/s)"
        )
        return {
            "metric": "zero3_allgather_gbps",
            "value": round(demand_gbps, 1),
            "unit": "GB/s demanded of ICI at measured step time (fsdp=8)",
            "allgather_bytes_per_step": comm["all-gather"],
            "reduce_scatter_bytes_per_step": comm["reduce-scatter"],
            "ici_roofline_gbps": ici_gbps,
            "ici_share_pct": round(100 * demand_gbps / ici_gbps, 1),
        }

    if on_tpu and os.environ.get("BENCH_SKIP_BIG") != "1":
        # Big-model rung: 774M with full on-device fp32 Adam state
        # (params 3.1G + m/v 6.2G + fp32 grad-accum 3.1G ≈ 12.4G),
        # Round-3 MFU configuration (sweep record in tools/sweep_774m.py,
        # measured on-chip): selective remat saving qkv/ffn_pre + the
        # flash kernels' own residuals (attn_o/attn_lse — backward never
        # re-runs the forward kernel), the gas==1 fused step (no
        # persistent fp32 accumulator: 3.1GB freed for the saved
        # activations), and (512,512) flash blocks.
        # Ladder: r2 policy 35.4% -> gas1 38.1% -> +selective remat
        # 39.4% -> +tuned blocks 41.7% -> +flash residuals 42.6% MFU.
        big = dataclasses.replace(
            gpt2.GPT2_LARGE, remat=True, xent_chunk_size=512,
            remat_save_names=("qkv", "ffn_pre", "attn_o", "attn_lse"),
        )
        big_mb, big_gas = 4, 1

        def big_rung():
            r = bench_model(big, micro_bs=big_mb, gas=big_gas, seq=1024, steps=6, zero_stage=3, label="774M-zero3")
            try:
                # derived metric must never cost the measured primary rung
                extra.append(zero3_comm_rung(big, r, big_gas))
            except Exception as e:  # noqa: BLE001
                log(f"[zero3-comm] FAILED: {str(e)[:200]}")
            return r

        try_point(big_rung, "774M-zero3")
        # BERT-Large samples/s (BASELINE.json metric; ref V100 numbers in
        # the fastest-bert blog)
        # micro-batches from the r3 sweep: seq128 mb64 (390.6 samples/s
        # with the short-seq dense attention path), seq512 mb16 (76.7)
        try_point(lambda: bench_bert(seq=128, micro_bs=64, gas=1, steps=6), "bert-large-s128")
        try_point(lambda: bench_bert(seq=512, micro_bs=16, gas=1, steps=6), "bert-large-s512")
        # Inference rungs: GPT-2 XL-class KV-cache decode, bf16 and int8
        try_point(lambda: bench_inference("gpt2-xl", 0, "bf16"), "infer-bf16")
        try_point(lambda: bench_inference("gpt2-xl", 8, "int8"), "infer-int8")


if __name__ == "__main__":
    main()
