"""Multi-node fan-out runners.

Reference: ``deepspeed/launcher/multinode_runner.py`` — ``PDSHRunner``
(:35), ``OpenMPIRunner`` (:78), ``MVAPICHRunner`` (:118): each turns the
resource pool + user command into a pdsh/mpirun command line.  Same
shapes here, emitting commands that invoke the per-node launcher
(``launcher/launch.py``) with the TPU env bootstrap; an ``SSHRunner``
covers bare TPU-VM pods (the common case — gcloud/ssh fan-out, one
process per host).
"""
from __future__ import annotations

import base64
import json
import os
import shlex
import shutil
from typing import Dict, List


class MultiNodeRunner:
    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(getattr(args, "user_args", []) or [])
        self.user_script = args.user_script
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, var: str) -> None:
        self.exports[key.strip()] = var.strip()

    @property
    def name(self) -> str:
        raise NotImplementedError

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment: Dict[str, str], active_resources: Dict[str, List[int]]) -> List[str]:
        raise NotImplementedError

    def _launch_cmd(self, node_rank, active_resources: Dict[str, List[int]]) -> List[str]:
        # per-node proc counts ride inside world_info (launch.py derives
        # rank offsets from it, so heterogeneous slot counts work)
        return [
            "python",
            "-u",
            "-m",
            "deepspeed_tpu.launcher.launch",
            f"--node_rank={node_rank}",
            f"--master_addr={self.args.master_addr}",
            f"--master_port={self.args.master_port}",
            f"--world_info={self.world_info_base64}",
            self.user_script,
            *self.user_arguments,
        ]


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference :35): one ssh-parallel command across the
    host list; %n expands to the node index via a small shell shim."""

    @property
    def name(self):
        return "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        environment = dict(environment)
        environment["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(active_resources.keys())
        exports = " ".join(f"export {k}={shlex.quote(v)};" for k, v in self.exports.items())
        # pdsh expands %n to the relative node index — exactly the node
        # rank (works for IPs/aliases, unlike hostname matching)
        launch = " ".join(
            "--node_rank=%n" if c.startswith("--node_rank=") else shlex.quote(c)
            for c in self._launch_cmd(0, active_resources)
        )
        return ["pdsh", "-f", "1024", "-w", hosts, f"{exports} cd {os.path.abspath('.')}; {launch}"]


class SSHRunner(MultiNodeRunner):
    """Plain ssh loop — the TPU-VM default (gcloud compute tpus tpu-vm ssh
    fan-out follows the same shape)."""

    @property
    def name(self):
        return "ssh"

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources):
        cmds = []
        exports = " ".join(f"export {k}={shlex.quote(v)};" for k, v in self.exports.items())
        for rank, host in enumerate(active_resources):
            launch = " ".join(shlex.quote(c) for c in self._launch_cmd(rank, active_resources))
            cmds.append(["ssh", host, f"{exports} cd {os.path.abspath('.')} && {launch}"])
        return cmds


class OpenMPIRunner(MultiNodeRunner):
    """mpirun fan-out (reference :78): one proc per host, ranks from MPI;
    the user script relies on mpi_discovery (comm/distributed.py)."""

    @property
    def name(self):
        return "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        hosts = ",".join(f"{h}:1" for h in active_resources)
        export_flags = []
        for k, v in self.exports.items():
            export_flags += ["-x", f"{k}={v}"]
        return [
            "mpirun",
            "-n", str(total),
            "-host", hosts,
            "--mca", "btl", "^openib",
            "--mca", "btl_tcp_if_include", "eth0",
            *export_flags,
            "python", "-u", self.user_script, *self.user_arguments,
        ]


class MVAPICHRunner(OpenMPIRunner):
    """MVAPICH flavor (reference :118); same command shape with a
    hostfile instead of -host."""

    @property
    def name(self):
        return "mvapich"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun_rsh") is not None
