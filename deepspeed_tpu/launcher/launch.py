"""Per-node launcher.

Reference: ``deepspeed/launcher/launch.py`` (``main`` :67) — decode the
world info, set ``MASTER_*``/rank env vars, spawn one process per local
accelerator, kill the pack if any child dies (:129-167).

TPU difference: JAX runs **one process per host** that owns all local
chips (SURVEY §3.1 TPU note), so the per-rank fan-out collapses to a
single child per node — but the contract stays: env-var bootstrap
(MASTER_ADDR/PORT, RANK, WORLD_SIZE consumed by
``comm/distributed.init_distributed``), signal propagation, non-zero
exit on child failure.  ``--procs_per_node`` > 1 is supported for
CPU-cluster/debug runs (each child gets a distinct RANK and a
``JAX_LOCAL_DEVICE`` hint).

Supervision (docs/resilience.md): children get ``DS_SUPERVISION_PORT``
(derived from ``master_port``) so the heartbeat side channel needs no
config edit.  The kill-on-failure contract becomes failure-domain
aware: a child dying to a SIGNAL (the hardware-loss signature —
SIGKILL, SIGSEGV, ...) opens a ``--peer_grace`` window in which the
surviving ranks may detect the death themselves, commit their verified
emergency tags, and exit ``43``/``44`` — only then is the pack killed.
A plain non-zero ``sys.exit`` still kills the pack immediately (a bug
is not a failure domain).  The final exit code prefers ``44`` ("a
survivor saved") over ``43`` over the crash code, and the per-rank exit
codes land in ``$DS_SUPERVISION_DIR/node<r>_status.json`` for the
runner's elastic restart to re-derive the surviving world from.
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List

from deepspeed_tpu.utils.logging import logger

EXIT_PREEMPTED_SAVED = 43
EXIT_PEER_FAILED_SAVED = 44
_SAVED_CODES = (EXIT_PREEMPTED_SAVED, EXIT_PEER_FAILED_SAVED)


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="deepspeed_tpu per-node launcher")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", default="127.0.0.1", type=str)
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument("--world_info", default="e30=", type=str, help="base64 json {host: [slots]}")
    parser.add_argument("--procs_per_node", type=int, default=1)
    parser.add_argument(
        "--peer_grace", type=float, default=float(os.environ.get("DS_PEER_GRACE", "30")),
        help="seconds survivors get to emergency-save (exit 43/44) after a sibling "
             "dies to a signal, before the pack is killed",
    )
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded: str) -> dict:
    return json.loads(base64.urlsafe_b64decode(encoded).decode())


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    if hosts:
        # ranks come from the world info itself (supports heterogeneous
        # slot counts: rank = slots of earlier hosts + local_rank)
        slots = [len(v) for v in world_info.values()]
        world_size = sum(slots)
        procs_per_node = slots[args.node_rank]
        rank_offset = sum(slots[: args.node_rank])
    else:
        procs_per_node = max(1, args.procs_per_node)
        world_size = procs_per_node
        rank_offset = args.node_rank * procs_per_node

    children: List[subprocess.Popen] = []

    def kill_all(signum=None, frame=None):
        for p in children:
            if p.poll() is None:
                p.terminate()
        for p in children:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if signum is not None:
            sys.exit(128 + signum)

    signal.signal(signal.SIGINT, kill_all)
    signal.signal(signal.SIGTERM, kill_all)

    # supervision side channel: every rank derives the same endpoint
    # from the launch args — no per-job config edit needed
    sup_port = os.environ.get("DS_SUPERVISION_PORT") or str(args.master_port + 17)
    sup_addr = os.environ.get("DS_SUPERVISION_ADDR") or args.master_addr

    for local_rank in range(procs_per_node):
        rank = rank_offset + local_rank
        env = os.environ.copy()
        env.update(
            MASTER_ADDR=args.master_addr,
            MASTER_PORT=str(args.master_port),
            RANK=str(rank),
            LOCAL_RANK=str(local_rank),
            WORLD_SIZE=str(world_size),
            DS_SUPERVISION_PORT=sup_port,
            DS_SUPERVISION_ADDR=sup_addr,
        )
        cmd = [sys.executable, "-u", args.training_script, *args.training_script_args]
        logger.info(f"launch: rank {rank}/{world_size} -> {' '.join(cmd)}")
        children.append(subprocess.Popen(cmd, env=env))

    # Reference behavior: the first plain non-zero exit kills every
    # sibling and propagates the code (launch.py:129-167).  Supervision
    # refinement: a SIGNAL death (rc < 0) instead opens a peer-grace
    # window so survivors can emergency-save and exit 43/44 themselves;
    # children exiting 43/44 never trigger the pack-kill at all (they
    # saved — their siblings are about to notice the departure and do
    # the same).
    codes: Dict[int, int] = {}
    crash_code = 0
    grace_deadline = None
    alive = set(range(len(children)))
    while alive:
        for i in list(alive):
            code = children[i].poll()
            if code is None:
                continue
            alive.discard(i)
            codes[i] = code
            if code == 0 or code in _SAVED_CODES:
                if code in _SAVED_CODES:
                    logger.warning(f"launch: rank process {i} exited {code} (saved-and-exited)")
                    # a saved-and-exited rank means its siblings are
                    # (or are about to be) wedged on the missing peer:
                    # arm the same bounded grace a signal death gets, so
                    # supervision-off packs cannot hang forever
                    if alive and grace_deadline is None:
                        grace_deadline = time.monotonic() + max(0.0, args.peer_grace)
                continue
            if code < 0:  # died to a signal: the hardware-loss signature
                sig = -code
                codes[i] = 128 + sig
                crash_code = crash_code or 128 + sig
                if grace_deadline is None:
                    grace_deadline = time.monotonic() + max(0.0, args.peer_grace)
                    logger.error(
                        f"launch: rank process {i} killed by signal {sig}; giving "
                        f"survivors {args.peer_grace:g}s to emergency-save before the pack-kill"
                    )
            else:
                logger.error(f"launch: rank process {i} exited with {code}; terminating job")
                crash_code = crash_code or code
                if grace_deadline is None:
                    # immediate pack-kill — but never SHORTEN a grace
                    # window a signal death already opened (exit 1 after
                    # a peer loss is the documented "save failed" code;
                    # other survivors may still be mid-emergency-save)
                    grace_deadline = time.monotonic()
        if alive and grace_deadline is not None and time.monotonic() >= grace_deadline:
            logger.error(f"launch: terminating {len(alive)} remaining rank process(es)")
            break
        if alive:
            # poll() above already reaps; a waitpid(-1) here would steal
            # exit statuses from Popen and break code propagation
            time.sleep(0.2)
    # survivors terminated at grace expiry were on HEALTHY hardware that
    # simply ran out of time — record them separately so the runner's
    # shrink does not drop their slots alongside the genuinely dead
    pack_killed = sorted(alive)
    if alive:
        kill_all()
        for i in alive:
            # kill_all waited: prefer the REAL exit code it reaped — a
            # survivor whose watchdog turned our SIGTERM into a saved
            # exit 43 must not be recorded as killed
            rc = children[i].returncode
            if rc is None:
                rc = 128 + signal.SIGTERM
            elif rc < 0:
                rc = 128 - rc
            codes.setdefault(i, rc)

    # exit-code aggregation (docs/resilience.md): a survivor that
    # certified a save outranks the crash that caused it — the runner's
    # --restarts keys off 43/44
    all_codes = list(codes.values())
    if any(c == EXIT_PEER_FAILED_SAVED for c in all_codes):
        exit_code = EXIT_PEER_FAILED_SAVED
    elif any(c == EXIT_PREEMPTED_SAVED for c in all_codes):
        exit_code = EXIT_PREEMPTED_SAVED
    else:
        exit_code = crash_code

    status_dir = os.environ.get("DS_SUPERVISION_DIR")
    if status_dir:
        try:
            os.makedirs(status_dir, exist_ok=True)
            status = {
                "node_rank": args.node_rank,
                "rank_offset": rank_offset,
                "codes": {str(rank_offset + i): codes.get(i, 0) for i in range(len(children))},
                "pack_killed": [rank_offset + i for i in pack_killed],
                "exit_code": exit_code,
            }
            tmp = os.path.join(status_dir, f".node{args.node_rank}_status.tmp")
            with open(tmp, "w") as f:
                json.dump(status, f)
            os.replace(tmp, os.path.join(status_dir, f"node{args.node_rank}_status.json"))
        except OSError as e:
            logger.warning(f"launch: could not write supervision status: {e}")
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
