"""Per-node launcher.

Reference: ``deepspeed/launcher/launch.py`` (``main`` :67) — decode the
world info, set ``MASTER_*``/rank env vars, spawn one process per local
accelerator, kill the pack if any child dies (:129-167).

TPU difference: JAX runs **one process per host** that owns all local
chips (SURVEY §3.1 TPU note), so the per-rank fan-out collapses to a
single child per node — but the contract stays: env-var bootstrap
(MASTER_ADDR/PORT, RANK, WORLD_SIZE consumed by
``comm/distributed.init_distributed``), signal propagation, non-zero
exit on child failure.  ``--procs_per_node`` > 1 is supported for
CPU-cluster/debug runs (each child gets a distinct RANK and a
``JAX_LOCAL_DEVICE`` hint).
"""
from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys
from typing import List

from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(description="deepspeed_tpu per-node launcher")
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master_addr", default="127.0.0.1", type=str)
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument("--world_info", default="e30=", type=str, help="base64 json {host: [slots]}")
    parser.add_argument("--procs_per_node", type=int, default=1)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(encoded: str) -> dict:
    return json.loads(base64.urlsafe_b64decode(encoded).decode())


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    hosts = list(world_info.keys())
    if hosts:
        # ranks come from the world info itself (supports heterogeneous
        # slot counts: rank = slots of earlier hosts + local_rank)
        slots = [len(v) for v in world_info.values()]
        world_size = sum(slots)
        procs_per_node = slots[args.node_rank]
        rank_offset = sum(slots[: args.node_rank])
    else:
        procs_per_node = max(1, args.procs_per_node)
        world_size = procs_per_node
        rank_offset = args.node_rank * procs_per_node

    children: List[subprocess.Popen] = []

    def kill_all(signum=None, frame=None):
        for p in children:
            if p.poll() is None:
                p.terminate()
        for p in children:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if signum is not None:
            sys.exit(128 + signum)

    signal.signal(signal.SIGINT, kill_all)
    signal.signal(signal.SIGTERM, kill_all)

    for local_rank in range(procs_per_node):
        rank = rank_offset + local_rank
        env = os.environ.copy()
        env.update(
            MASTER_ADDR=args.master_addr,
            MASTER_PORT=str(args.master_port),
            RANK=str(rank),
            LOCAL_RANK=str(local_rank),
            WORLD_SIZE=str(world_size),
        )
        cmd = [sys.executable, "-u", args.training_script, *args.training_script_args]
        logger.info(f"launch: rank {rank}/{world_size} -> {' '.join(cmd)}")
        children.append(subprocess.Popen(cmd, env=env))

    # reference behavior: first non-zero exit kills every sibling and
    # propagates the code (launch.py:129-167)
    exit_code = 0
    alive = set(range(len(children)))
    while alive and exit_code == 0:
        for i in list(alive):
            code = children[i].poll()
            if code is not None:
                alive.discard(i)
                if code != 0:
                    logger.error(f"launch: rank process {i} exited with {code}; terminating job")
                    exit_code = code
        if alive and exit_code == 0:
            # poll() above already reaps; a waitpid(-1) here would steal
            # exit statuses from Popen and break code propagation
            import time

            time.sleep(0.2)
    if exit_code != 0:
        kill_all()
    else:
        for p in children:
            p.wait()
    sys.exit(exit_code)


if __name__ == "__main__":
    main()
