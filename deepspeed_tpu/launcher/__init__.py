from deepspeed_tpu.launcher.runner import (
    encode_world_info,
    fetch_hostfile,
    main,
    parse_resource_filter,
)
