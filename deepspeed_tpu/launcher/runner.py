"""Job launcher front-end (the ``deepspeed`` CLI).

Reference: ``deepspeed/launcher/runner.py`` — ``main`` (:259): parse the
hostfile (:120), apply ``--include/--exclude`` filters (:151), base64 the
world info (:253), then either exec the local per-node launcher or fan
out through a multi-node runner.  Behavior preserved; the per-node story
changes to one-JAX-process-per-host (SURVEY §3.1 TPU note).
"""
from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import re
import subprocess
import sys
from typing import Dict, List

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher", formatter_class=argparse.ArgumentDefaultsHelpFormatter
    )
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='e.g. "host1,host2" or "host1:0,2@host2:1"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="inverse of --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_hosts_procs", dest="num_gpus", type=int, default=-1,
                        help="processes per node (reference flag name kept)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh", help="pdsh|ssh|openmpi|mvapich")
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Dict[str, int]:
    """Parse 'hostname slots=N' lines (reference :120); returns an
    ordered {host: slot_count}."""
    if not os.path.isfile(hostfile_path):
        return {}
    resource_pool = collections.OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)\s*$", line)
            if m is None:
                raise ValueError(f"hostfile line malformed: '{line}' (want 'host slots=N')")
            host, slots = m.group(1), int(m.group(2))
            if host in resource_pool:
                raise ValueError(f"hostfile contains duplicate host '{host}'")
            resource_pool[host] = slots
    return resource_pool


def _parse_filter(spec: str) -> Dict[str, List[int]]:
    """'h1:0,2@h2' → {'h1': [0, 2], 'h2': []} (reference inclusion/
    exclusion grammar, runner.py:151)."""
    out: Dict[str, List[int]] = collections.OrderedDict()
    if not spec:
        return out
    for part in spec.split("@"):
        if ":" in part:
            host, slots = part.split(":", 1)
            out[host] = [int(s) for s in slots.split(",") if s != ""]
        else:
            out[part] = []
    return out


def parse_resource_filter(
    resource_pool: Dict[str, int], include_str: str = "", exclude_str: str = ""
) -> Dict[str, List[int]]:
    """Apply --include/--exclude to the pool (reference :151-240).
    Returns {host: [slot ids]}."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    full = collections.OrderedDict((h, list(range(n))) for h, n in resource_pool.items())
    if not include_str and not exclude_str:
        return full
    if include_str:
        spec = _parse_filter(include_str)
        out = collections.OrderedDict()
        for host, slots in spec.items():
            if host not in full:
                raise ValueError(f"--include host '{host}' not in hostfile")
            bad = [s for s in slots if s not in full[host]]
            if bad:
                raise ValueError(f"--include slots {bad} invalid for host '{host}'")
            out[host] = slots or full[host]
        return out
    spec = _parse_filter(exclude_str)
    out = collections.OrderedDict()
    for host, slots in full.items():
        if host in spec:
            drop = spec[host] or slots
            bad = [s for s in spec[host] if s not in slots]
            if bad:
                raise ValueError(f"--exclude slots {bad} invalid for host '{host}'")
            keep = [s for s in slots if s not in drop]
            if keep:
                out[host] = keep
        else:
            out[host] = slots
    return out


def encode_world_info(active_resources: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(json.dumps(active_resources).encode()).decode()


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool:
        # single-node path (reference :314-324): localhost, all local chips
        procs = args.num_gpus if args.num_gpus > 0 else 1
        active = {"localhost": list(range(procs))}
        cmd = [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            "--node_rank=0",
            f"--master_addr={args.master_addr or '127.0.0.1'}",
            f"--master_port={args.master_port}",
            f"--world_info={encode_world_info(active)}",
            f"--procs_per_node={procs}",
            args.user_script, *args.user_args,
        ]
        logger.info(f"runner: single-node cmd: {' '.join(cmd)}")
        result = subprocess.Popen(cmd)
        result.wait()
        sys.exit(result.returncode)

    active = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = collections.OrderedDict(list(active.items())[: args.num_nodes])
    world_info = encode_world_info(active)
    args.master_addr = args.master_addr or next(iter(active))

    from deepspeed_tpu.launcher.multinode_runner import (
        MVAPICHRunner, OpenMPIRunner, PDSHRunner, SSHRunner,
    )

    runners = {"pdsh": PDSHRunner, "ssh": SSHRunner, "openmpi": OpenMPIRunner, "mvapich": MVAPICHRunner}
    if args.launcher not in runners:
        raise ValueError(f"unknown launcher {args.launcher} (choose from {sorted(runners)})")
    runner = runners[args.launcher](args, world_info)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{runner.name}' not found on PATH")
    env = os.environ.copy()
    cmd = runner.get_cmd(env, active)
    if isinstance(cmd[0], list):  # ssh runner: one command per host
        import time

        procs = [subprocess.Popen(c, env=env) for c in cmd]
        code = 0
        alive = set(range(len(procs)))
        # cross-node pack-kill (mirrors launch.py's per-node contract):
        # first non-zero exit terminates the remaining hosts
        while alive and code == 0:
            for i in list(alive):
                rc = procs[i].poll()
                if rc is not None:
                    alive.discard(i)
                    if rc != 0:
                        logger.error(f"runner: node {i} exited with {rc}; terminating remaining hosts")
                        code = rc
            if alive and code == 0:
                time.sleep(0.5)
        for i in alive:
            procs[i].terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
        sys.exit(code)
    logger.info(f"runner: {' '.join(map(str, cmd))}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
