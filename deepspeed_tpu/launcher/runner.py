"""Job launcher front-end (the ``deepspeed`` CLI).

Reference: ``deepspeed/launcher/runner.py`` — ``main`` (:259): parse the
hostfile (:120), apply ``--include/--exclude`` filters (:151), base64 the
world info (:253), then either exec the local per-node launcher or fan
out through a multi-node runner.  Behavior preserved; the per-node story
changes to one-JAX-process-per-host (SURVEY §3.1 TPU note).
"""
from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import re
import subprocess
import sys
from typing import Dict, List

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher", formatter_class=argparse.ArgumentDefaultsHelpFormatter
    )
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="hostfile of 'hostname slots=N' lines")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='e.g. "host1,host2" or "host1:0,2@host2:1"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="inverse of --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_hosts_procs", dest="num_gpus", type=int, default=-1,
                        help="processes per node (reference flag name kept)")
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh", help="pdsh|ssh|openmpi|mvapich")
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument(
        "--restarts", type=int, default=0,
        help="elastic restarts: when the job exits 43/44 (saved-and-exited, "
             "docs/resilience.md), relaunch up to N times on the surviving "
             "hosts/slots (shrunk world via elasticity.shrink_world_info); the "
             "engine resumes from the newest verified tag",
    )
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Dict[str, int]:
    """Parse 'hostname slots=N' lines (reference :120); returns an
    ordered {host: slot_count}."""
    if not os.path.isfile(hostfile_path):
        return {}
    resource_pool = collections.OrderedDict()
    with open(hostfile_path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"^(\S+)\s+slots=(\d+)\s*$", line)
            if m is None:
                raise ValueError(f"hostfile line malformed: '{line}' (want 'host slots=N')")
            host, slots = m.group(1), int(m.group(2))
            if host in resource_pool:
                raise ValueError(f"hostfile contains duplicate host '{host}'")
            resource_pool[host] = slots
    return resource_pool


def _parse_filter(spec: str) -> Dict[str, List[int]]:
    """'h1:0,2@h2' → {'h1': [0, 2], 'h2': []} (reference inclusion/
    exclusion grammar, runner.py:151)."""
    out: Dict[str, List[int]] = collections.OrderedDict()
    if not spec:
        return out
    for part in spec.split("@"):
        if ":" in part:
            host, slots = part.split(":", 1)
            out[host] = [int(s) for s in slots.split(",") if s != ""]
        else:
            out[part] = []
    return out


def parse_resource_filter(
    resource_pool: Dict[str, int], include_str: str = "", exclude_str: str = ""
) -> Dict[str, List[int]]:
    """Apply --include/--exclude to the pool (reference :151-240).
    Returns {host: [slot ids]}."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    full = collections.OrderedDict((h, list(range(n))) for h, n in resource_pool.items())
    if not include_str and not exclude_str:
        return full
    if include_str:
        spec = _parse_filter(include_str)
        out = collections.OrderedDict()
        for host, slots in spec.items():
            if host not in full:
                raise ValueError(f"--include host '{host}' not in hostfile")
            bad = [s for s in slots if s not in full[host]]
            if bad:
                raise ValueError(f"--include slots {bad} invalid for host '{host}'")
            out[host] = slots or full[host]
        return out
    spec = _parse_filter(exclude_str)
    out = collections.OrderedDict()
    for host, slots in full.items():
        if host in spec:
            drop = spec[host] or slots
            bad = [s for s in spec[host] if s not in slots]
            if bad:
                raise ValueError(f"--exclude slots {bad} invalid for host '{host}'")
            keep = [s for s in slots if s not in drop]
            if keep:
                out[host] = keep
        else:
            out[host] = slots
    return out


def encode_world_info(active_resources: Dict[str, List[int]]) -> str:
    return base64.urlsafe_b64encode(json.dumps(active_resources).encode()).decode()


_SAVED_CODES = (43, 44)  # preempted-and-saved / peer-failed-and-saved


def _read_failed_ranks(status_dir: str) -> List[int]:
    """Global ranks whose exit codes in the per-node status files mark a
    crash (anything but 0/43/44) — what the shrunk relaunch drops.
    Ranks the launcher itself pack-killed at grace expiry sat on healthy
    hardware and are NOT failures."""
    failed: List[int] = []
    try:
        for name in os.listdir(status_dir):
            if not (name.startswith("node") and name.endswith("_status.json")):
                continue
            with open(os.path.join(status_dir, name)) as f:
                status = json.load(f)
            pack_killed = {int(r) for r in status.get("pack_killed", [])}
            for rank, code in status.get("codes", {}).items():
                if int(code) not in (0,) + _SAVED_CODES and int(rank) not in pack_killed:
                    failed.append(int(rank))
    except (OSError, ValueError) as e:
        logger.warning(f"runner: could not read supervision status from {status_dir}: {e}")
    return sorted(set(failed))


def _default_shrink(active: Dict[str, List[int]], status_dir: str) -> Dict[str, List[int]]:
    """Rank-level shrink from the per-node status files."""
    from deepspeed_tpu.elasticity.elasticity import shrink_world_info

    failed = _read_failed_ranks(status_dir)
    if not failed:
        return active
    try:
        return shrink_world_info(active, failed)
    except ValueError as e:
        logger.warning(f"runner: rank-level shrink failed ({e}); restarting at the same world")
        return active


def _elastic_loop(args, active: Dict[str, List[int]], launch_once, shrink_fn=_default_shrink) -> int:
    """Run ``launch_once(active, attempt)`` -> exit code, relaunching on
    43/44 at the shrunk world up to ``--restarts`` times (the elastic
    restart driver; docs/resilience.md).  ``shrink_fn(active,
    status_dir)`` derives the surviving resources for the relaunch."""
    import shutil
    import tempfile

    if args.restarts <= 0:
        # plain run: no status plumbing, no env mutation, nothing leaked
        return launch_once(active, 0)

    attempt = 0
    while True:
        status_dir = tempfile.mkdtemp(prefix="ds_supervision_")
        os.environ["DS_SUPERVISION_DIR"] = status_dir
        os.environ["DS_RESTART_COUNT"] = str(attempt)
        os.environ["DS_RESTARTS"] = str(args.restarts)
        try:
            code = launch_once(active, attempt)
            if code not in _SAVED_CODES or attempt >= args.restarts:
                if code in _SAVED_CODES:
                    logger.error(
                        f"runner: restart budget ({args.restarts}) exhausted; exiting {code}"
                    )
                return code
            survivors = shrink_fn(active, status_dir)
        finally:
            # status files were consumed (or the run is over): clean up
            shutil.rmtree(status_dir, ignore_errors=True)
        if not survivors:
            logger.error("runner: no surviving slots to restart on")
            return code
        attempt += 1
        logger.warning(
            f"runner: job exited {code} (saved); elastic restart {attempt}/{args.restarts} on "
            f"{sum(len(v) for v in survivors.values())} slot(s) across "
            f"{len(survivors)} host(s)"
        )
        active = survivors


def main(args=None):
    args = parse_args(args)
    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool:
        # single-node path (reference :314-324): localhost, all local chips
        procs = args.num_gpus if args.num_gpus > 0 else 1
        active = {"localhost": list(range(procs))}

        def launch_once(active_now, attempt):
            procs_now = sum(len(v) for v in active_now.values())
            cmd = [
                sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                "--node_rank=0",
                f"--master_addr={args.master_addr or '127.0.0.1'}",
                f"--master_port={args.master_port}",
                f"--world_info={encode_world_info(active_now)}",
                f"--procs_per_node={procs_now}",
                args.user_script, *args.user_args,
            ]
            logger.info(f"runner: single-node cmd: {' '.join(cmd)}")
            result = subprocess.Popen(cmd)
            result.wait()
            return result.returncode

        sys.exit(_elastic_loop(args, active, launch_once))

    active = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = collections.OrderedDict(list(active.items())[: args.num_nodes])
    args.master_addr = args.master_addr or next(iter(active))

    from deepspeed_tpu.launcher.multinode_runner import (
        MVAPICHRunner, OpenMPIRunner, PDSHRunner, SSHRunner,
    )

    runners = {"pdsh": PDSHRunner, "ssh": SSHRunner, "openmpi": OpenMPIRunner, "mvapich": MVAPICHRunner}
    if args.launcher not in runners:
        raise ValueError(f"unknown launcher {args.launcher} (choose from {sorted(runners)})")
    if args.restarts and args.launcher not in ("ssh",):
        logger.warning(
            f"runner: --restarts with the '{args.launcher}' launcher relaunches at the SAME "
            "world (the single fan-out process hides which host died); use the ssh launcher "
            "for per-host shrink"
        )

    def launch_once(active_now, attempt):
        launch_once.failed_hosts = []
        world_info = encode_world_info(active_now)
        runner = runners[args.launcher](args, world_info)
        if not runner.backend_exists():
            raise RuntimeError(f"launcher backend '{runner.name}' not found on PATH")
        # supervision state must reach the REMOTE nodes too (ssh does
        # not forward env): DS_SUPERVISION_DIR enables the rank-level
        # shrink on shared filesystems, the rest keep restart counters
        # and fault plans consistent across the pod
        for key in ("DS_SUPERVISION_DIR", "DS_RESTART_COUNT", "DS_RESTARTS",
                    "DS_PEER_GRACE", "DS_FAULT_PLAN"):
            if os.environ.get(key):
                runner.add_export(key, os.environ[key])
        env = os.environ.copy()
        cmd = runner.get_cmd(env, active_now)
        if not isinstance(cmd[0], list):
            logger.info(f"runner: {' '.join(map(str, cmd))}")
            result = subprocess.Popen(cmd, env=env)
            result.wait()
            return result.returncode

        # ssh runner: one command per host.  Cross-node pack-kill
        # mirrors launch.py's per-node contract, refined for the
        # supervision exit codes: a node exiting 43/44 saved and left
        # (no pack-kill); any other non-zero code opens a peer-grace
        # window for the remaining hosts to emergency-save first.
        import time

        procs = [subprocess.Popen(c, env=env) for c in cmd]
        hosts = list(active_now)
        codes = {}
        crash = 0
        grace_deadline = None
        peer_grace = float(os.environ.get("DS_PEER_GRACE", "30"))
        alive = set(range(len(procs)))
        while alive:
            for i in list(alive):
                rc = procs[i].poll()
                if rc is None:
                    continue
                alive.discard(i)
                codes[i] = rc
                if rc == 0:
                    continue
                if rc in _SAVED_CODES:
                    # a saved-and-exited node means the others are (or
                    # are about to be) wedged on the missing peer: bound
                    # the wait like launch.py's per-node loop does
                    logger.warning(f"runner: node {i} ({hosts[i]}) exited {rc} (saved)")
                    if alive and grace_deadline is None:
                        grace_deadline = time.monotonic() + peer_grace
                    continue
                logger.error(f"runner: node {i} ({hosts[i]}) exited with {rc}")
                crash = crash or rc
                if grace_deadline is None:
                    grace_deadline = time.monotonic() + peer_grace
            if alive and grace_deadline is not None and time.monotonic() >= grace_deadline:
                logger.error(f"runner: terminating {len(alive)} remaining host(s)")
                break
            if alive:
                time.sleep(0.5)
        for i in alive:
            procs[i].terminate()
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    p.kill()
        # a crashed node's surviving slots cannot be re-derived from
        # here (its status file is on its local disk): drop the WHOLE
        # crashed host on restart
        bad_hosts = [hosts[i] for i, rc in codes.items() if rc not in (0,) + _SAVED_CODES]
        launch_once.failed_hosts = bad_hosts
        all_codes = list(codes.values())
        if any(c == 44 for c in all_codes):
            return 44
        if any(c == 43 for c in all_codes):
            return 43
        return crash

    def shrink_multinode(active_now, status_dir):
        # rank-level shrink from status files (reachable on a shared
        # filesystem — DS_SUPERVISION_DIR is exported to the nodes),
        # then drop WHOLE crashed hosts (a node whose launcher died has
        # no readable status; tracked on launch_once by exit code)
        survivors = _default_shrink(active_now, status_dir)
        failed_hosts = set(getattr(launch_once, "failed_hosts", []))
        survivors = collections.OrderedDict(
            (h, s) for h, s in survivors.items() if h not in failed_hosts
        )
        if survivors:
            args.master_addr = next(iter(survivors))
        return survivors

    sys.exit(_elastic_loop(args, active, launch_once, shrink_fn=shrink_multinode))


if __name__ == "__main__":
    main()
