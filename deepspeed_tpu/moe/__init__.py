"""Mixture-of-experts (expert parallelism over the ``expert`` mesh axis).

Upstream DeepSpeed grew ``deepspeed.moe`` in v0.5 (after the reference
snapshot); here it is first-class from round 1 because expert
parallelism shapes the mesh design (SURVEY.md §2.5 notes EP as absent
in the reference)."""
from deepspeed_tpu.moe.layer import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
    moe_param_specs,
    top_k_gating,
)

__all__ = ["MoEConfig", "init_moe_params", "moe_ffn", "moe_param_specs", "top_k_gating"]
