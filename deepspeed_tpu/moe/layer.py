"""Mixture-of-experts with expert parallelism over the ``expert`` axis.

The reference snapshot (v0.4.5) predates DeepSpeed-MoE (landed v0.5,
``deepspeed/moe/layer.py`` upstream); this framework ships MoE
TPU-first from the start:

* **Static-shape capacity dispatch** (GShard-style): top-k gating
  produces dense ``(tokens, experts, capacity)`` dispatch/combine
  tensors; dispatch and combine are einsums that XLA lowers onto the
  MXU, and token→expert movement over the ``expert`` mesh axis becomes
  an XLA all-to-all inserted by GSPMD from the sharding constraints —
  no Python-side routing, no dynamic shapes.
* **Experts stacked on a leading dim** ``(E, ...)`` sharded
  ``P("expert", ...)`` so each expert-parallel rank owns ``E/ep``
  experts; compute is a single batched matmul over the local experts.
* **Load-balancing aux loss** (Switch/GShard): ``E * Σ_e mean_prob_e *
  frac_tokens_e``, returned to the caller to add to the task loss.

Functional API (params are plain pytrees, like the rest of the
framework): ``init_moe_params`` → ``moe_ffn(params, x)``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.registry import register_op

EXPERT_AXIS = "expert"


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int
    d_model: int
    d_ff: int
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    # router jitter noise (training only), as in Switch Transformer.
    # NB: the aux-loss *weight* is applied by the caller (moe_ffn returns
    # the unweighted load-balancing loss).
    router_jitter: float = 0.0


def init_moe_params(cfg: MoEConfig, rng: np.random.Generator, std: float = 0.02, proj_std: Optional[float] = None) -> Dict[str, Any]:
    """Expert FFN + router weights, experts stacked on dim 0."""
    if proj_std is None:
        proj_std = std
    E, D, F = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "gate_w": (rng.standard_normal((D, E)) * std).astype(np.float32),
        "w1": (rng.standard_normal((E, D, F)) * std).astype(np.float32),
        "b1": np.zeros((E, F), np.float32),
        "w2": (rng.standard_normal((E, F, D)) * proj_std).astype(np.float32),
        "b2": np.zeros((E, D), np.float32),
    }


def moe_param_specs(layer_dim: bool = False, tp_axis: Optional[str] = None) -> Dict[str, P]:
    """PartitionSpecs for MoE weights: experts over ``expert``, and
    (optionally) the expert-FFN hidden dim over ``tp_axis`` (EP × TP).

    Back-compat re-export: the layout now lives in the partition-rule
    engine (:func:`deepspeed_tpu.sharding.rules.moe_param_specs`), which
    every engine resolves through."""
    from deepspeed_tpu.sharding.rules import moe_param_specs as _specs

    return _specs(layer_dim=layer_dim, tp_axis=tp_axis)


def _capacity(tokens: int, num_experts: int, factor: float, min_capacity: int) -> int:
    cap = int(np.ceil(tokens / num_experts * factor))
    return max(cap, min_capacity)


def top_k_gating(
    logits: jnp.ndarray,
    top_k: int,
    capacity: int,
    rng: Optional[jax.Array] = None,
    jitter: float = 0.0,
    token_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """GShard-style top-k gating with static capacity.

    ``logits``: (T, E) router scores for T tokens.  ``token_mask`` (T,)
    in {0,1} excludes padding tokens from dispatch, capacity, and the
    aux loss.
    Returns ``(dispatch (T,E,C) bool-ish, combine (T,E,C) float, aux_loss)``.
    """
    T, E = logits.shape
    if rng is not None and jitter > 0.0:
        logits = logits * jax.random.uniform(rng, logits.shape, minval=1.0 - jitter, maxval=1.0 + jitter)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T, E)
    if token_mask is None:
        tmask = jnp.ones((T,), jnp.float32)
        n_real = float(T)
    else:
        tmask = token_mask.astype(jnp.float32)
        n_real = jnp.maximum(jnp.sum(tmask), 1.0)

    # Iteratively pick top-k choices per token, masking previous picks.
    masked = probs
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    # Track per-expert fill across the k rounds so capacity is shared.
    fill = jnp.zeros((E,), jnp.int32)
    frac_tokens = jnp.zeros((E,), jnp.float32)  # for aux loss (top-1 only per Switch)

    for r in range(top_k):
        idx = jnp.argmax(masked, axis=-1)  # (T,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32) * tmask[:, None]  # (T, E); pads route nowhere
        gate = jnp.sum(probs * onehot, axis=-1)  # (T,)
        # position of each token within its chosen expert's buffer
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # (T, E)
        pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32) + jnp.sum(onehot * fill[None, :], axis=-1).astype(jnp.int32)
        keep = pos < capacity
        gate = gate * keep
        pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity, dtype=jnp.float32)  # (T, C)
        sel = onehot * keep[:, None]  # (T, E)
        dispatch = dispatch + sel[:, :, None] * pos_oh[:, None, :]
        combine = combine + (gate[:, None] * sel)[:, :, None] * pos_oh[:, None, :]
        fill = fill + jnp.sum(sel, axis=0).astype(jnp.int32)
        if r == 0:
            frac_tokens = jnp.sum(onehot, axis=0) / n_real
        masked = masked * (1.0 - onehot)  # mask picked expert for next round

    mean_prob = jnp.sum(probs * tmask[:, None], axis=0) / n_real  # (E,)
    aux_loss = E * jnp.sum(mean_prob * frac_tokens)
    return dispatch, combine, aux_loss


def _expert_sharding(spec: P):
    """Best-effort NamedSharding from the engine's global mesh (None if
    no engine/mesh yet — then GSPMD is unconstrained, still correct)."""
    from deepspeed_tpu.parallel.sequence import get_global_mesh

    mesh = get_global_mesh()
    if mesh is None or EXPERT_AXIS not in mesh.axis_names:
        return None
    return NamedSharding(mesh, spec)


def moe_ffn(
    params: Dict[str, Any],
    x: jnp.ndarray,
    cfg: MoEConfig,
    rng: Optional[jax.Array] = None,
    training: bool = False,
    token_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE feed-forward over ``x (B, T, D)`` → ``(out (B, T, D), aux_loss)``.

    Expert weights ``params['w1'] (E, D, F)`` etc. may be sharded over
    the ``expert`` axis; dispatch/combine einsums trigger GSPMD
    all-to-alls between the token sharding (batch axes) and the expert
    sharding.  ``training`` selects capacity_factor (vs the laxer
    eval_capacity_factor) and enables router jitter; ``token_mask``
    (B, T) excludes padding from routing/capacity/aux.
    """
    B, T, D = x.shape
    tokens = B * T
    E = cfg.num_experts
    factor = cfg.capacity_factor if training else cfg.eval_capacity_factor
    C = _capacity(tokens, E, factor, cfg.min_capacity)

    xt = x.reshape(tokens, D)
    logits = xt.astype(jnp.float32) @ params["gate_w"].astype(jnp.float32)
    dispatch, combine, aux = top_k_gating(
        logits,
        cfg.top_k,
        C,
        rng=rng,
        jitter=cfg.router_jitter if training else 0.0,
        token_mask=token_mask.reshape(tokens) if token_mask is not None else None,
    )
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(jnp.float32)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt)  # (E, C, D)
    sh = _expert_sharding(P(EXPERT_AXIS, None, None))
    if sh is not None:
        expert_in = jax.lax.with_sharding_constraint(expert_in, sh)
    h = jnp.einsum("ecd,edf->ecf", expert_in, params["w1"].astype(x.dtype)) + params["b1"][:, None, :].astype(x.dtype)
    h = jax.nn.gelu(h, approximate=True)
    out = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(x.dtype)) + params["b2"][:, None, :].astype(x.dtype)
    if sh is not None:
        out = jax.lax.with_sharding_constraint(out, sh)
    y = jnp.einsum("tec,ecd->td", combine, out.astype(jnp.float32))
    return y.reshape(B, T, D).astype(x.dtype), aux.astype(jnp.float32)


@register_op("moe", "xla", "GShard-style top-k MoE dispatch/combine (GSPMD all-to-all over expert axis)")
def _load_moe():
    return moe_ffn


MOE_PARAM_KEYS = ("gate_w", "w1", "b1", "w2", "b2")


def moe_ffn_from_block(lp: Dict[str, Any], h: jnp.ndarray, *, top_k: int = 2,
                       capacity_factor: float = 1.25, eval_capacity_factor: float = 2.0,
                       rng: Optional[jax.Array] = None, training: bool = False,
                       token_mask: Optional[jnp.ndarray] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Apply a block's MoE FFN from its stacked layer params ``lp``
    (shapes determine num_experts/d_ff) — the ONE place the train block
    (models/gpt2.py) and the inference block (ops/transformer/inference)
    build their MoEConfig, so capacity semantics can't drift."""
    cfg = MoEConfig(
        num_experts=lp["gate_w"].shape[-1],
        d_model=h.shape[-1],
        d_ff=lp["w1"].shape[-1],
        top_k=top_k,
        capacity_factor=capacity_factor,
        eval_capacity_factor=eval_capacity_factor,
    )
    params = {k: lp[k] for k in MOE_PARAM_KEYS}
    return moe_ffn(params, h, cfg, rng=rng, training=training, token_mask=token_mask)
