"""Deterministic fault injection for the resilience test harness.

The resilience code calls :func:`check`/:func:`check_flag` at named
sites (``"ckpt.commit"``, ``"ckpt.latest"``, ``"engine.force_overflow"``,
...).  In production no injector is installed and both are near-free
attribute checks.  Under test, a seeded :class:`FaultInjector` is
installed as a context manager and fires exactly the failures its plan
describes — I/O errors, kill-mid-save, forced overflow steps — so every
recovery path is provable, repeatably.

Two failure shapes:

* :class:`InjectedFault` (an ``OSError``) — a transient I/O error; the
  retry policy is expected to absorb it.
* :class:`InjectedKill` (a ``BaseException``) — models the process dying
  at that instruction.  Deliberately NOT an ``Exception`` so no
  ``except Exception`` cleanup handler in the code under test can "survive"
  a death the real process would not.
"""
from __future__ import annotations

import os
import random
from typing import Dict, List, Optional, Tuple


class InjectedFault(OSError):
    """A planned transient I/O failure."""


class InjectedKill(BaseException):
    """A planned process death (uncatchable by ``except Exception``)."""


_ACTIVE: Optional["FaultInjector"] = None


def check(site: str, path: Optional[str] = None) -> None:
    """Raise if the active injector has a raising plan armed for ``site``."""
    if _ACTIVE is not None:
        _ACTIVE.fire(site, path)


def check_flag(site: str) -> bool:
    """True if the active injector has a non-raising flag armed for
    ``site`` (e.g. "pretend this step overflowed")."""
    if _ACTIVE is None:
        return False
    return _ACTIVE.fire_flag(site)


class FaultInjector:
    """Seeded, per-site fault plans.  Use as a context manager::

        inj = FaultInjector(seed=0)
        inj.fail("ckpt.save.state", times=2)      # first two calls raise
        inj.kill("ckpt.commit")                   # then die at commit
        with inj:
            engine.save_checkpoint(d)
    """

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._plans: Dict[str, dict] = {}
        self.log: List[Tuple[str, str]] = []  # (site, event)

    # -- plan registration ------------------------------------------------
    def _plan(self, site: str, exc, times: int, after: int, probability: Optional[float]) -> None:
        self._plans[site] = {
            "exc": exc, "times": times, "after": after,
            "probability": probability, "calls": 0, "fired": 0,
        }

    def fail(self, site: str, times: int = 1, after: int = 0, exc=InjectedFault,
             probability: Optional[float] = None) -> "FaultInjector":
        """Arm ``site`` to raise ``exc`` for its next ``times`` triggers
        (skipping the first ``after`` calls)."""
        self._plan(site, exc, times, after, probability)
        return self

    def kill(self, site: str, after: int = 0) -> "FaultInjector":
        """Arm ``site`` to simulate process death (InjectedKill)."""
        self._plan(site, InjectedKill, 1, after, None)
        return self

    def flag(self, site: str, times: int = 1, after: int = 0) -> "FaultInjector":
        """Arm a non-raising flag at ``site`` (check_flag returns True)."""
        self._plan(site, None, times, after, None)
        return self

    # -- firing -----------------------------------------------------------
    def _triggers(self, plan: dict) -> bool:
        plan["calls"] += 1
        if plan["fired"] >= plan["times"] or plan["calls"] <= plan["after"]:
            return False
        if plan["probability"] is not None and self.rng.random() >= plan["probability"]:
            return False
        plan["fired"] += 1
        return True

    def fire(self, site: str, path: Optional[str] = None) -> None:
        plan = self._plans.get(site)
        if plan is None or plan["exc"] is None:
            return
        if self._triggers(plan):
            self.log.append((site, plan["exc"].__name__))
            raise plan["exc"](f"injected fault at site '{site}'" + (f" ({path})" if path else ""))

    def fire_flag(self, site: str) -> bool:
        plan = self._plans.get(site)
        if plan is None or plan["exc"] is not None:
            return False
        if self._triggers(plan):
            self.log.append((site, "flag"))
            return True
        return False

    def calls(self, site: str) -> int:
        plan = self._plans.get(site)
        return plan["calls"] if plan else 0

    # -- direct corruption helpers (for committed tags) -------------------
    @staticmethod
    def truncate_file(path: str, keep_bytes: int = 0) -> None:
        with open(path, "r+b") as f:
            f.truncate(keep_bytes)

    def corrupt_file(self, path: str) -> None:
        """Flip one byte in the middle of the file (seeded position)."""
        size = os.path.getsize(path)
        if size == 0:
            return
        pos = self.rng.randrange(size)
        with open(path, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]))

    # -- installation -----------------------------------------------------
    def __enter__(self) -> "FaultInjector":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a FaultInjector is already active")
        _ACTIVE = self
        return self

    def __exit__(self, *exc_info) -> None:
        global _ACTIVE
        _ACTIVE = None
