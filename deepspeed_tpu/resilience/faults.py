"""Deterministic fault injection for the resilience test harness.

The resilience code calls :func:`check`/:func:`check_flag` at named
sites (``"ckpt.commit"``, ``"ckpt.latest"``, ``"engine.force_overflow"``,
...).  In production no injector is installed and both are near-free
attribute checks.  Under test, a seeded :class:`FaultInjector` is
installed as a context manager and fires exactly the failures its plan
describes — I/O errors, kill-mid-save, forced overflow steps — so every
recovery path is provable, repeatably.

Two failure shapes:

* :class:`InjectedFault` (an ``OSError``) — a transient I/O error; the
  retry policy is expected to absorb it.
* :class:`InjectedKill` (a ``BaseException``) — models the process dying
  at that instruction.  Deliberately NOT an ``Exception`` so no
  ``except Exception`` cleanup handler in the code under test can "survive"
  a death the real process would not.

Multi-process plans (the supervision harness): a plan serializes to
JSON, rides to launcher-spawned children in the ``DS_FAULT_PLAN`` env
var, and installs itself at engine init (:func:`install_from_env`).
Each plan entry may carry a ``rank`` filter, so a single env var arms
"SIGKILL rank 1 at its 4th step boundary" across a real 2-process job.
Two plan kinds exist only for real processes:

* ``sigkill`` — ``os.kill(getpid(), SIGKILL)``: the real thing, no
  Python unwinding, no atexit — exactly what a hardware loss looks like
  to the surviving ranks;
* ``stall`` — :func:`check_stall` sleeps ``seconds`` inside a blocking
  sync (site ``collective.stall``), modelling a wedged-but-alive peer
  for the hung-collective watchdog.

Serving sites (``serving.submit``, ``serving.prefill``,
``serving.decode``, ``serving.journal.commit``; docs/resilience.md) use
the same machinery plus the ``latency`` action — a *repeating* sleep
(:func:`check_latency`, default every call) that models a slow decode
step so the overload tests can build real queue pressure without a big
model.  ``stall`` fires ``times`` then disarms; ``latency`` keeps
firing — a degraded chip, not a single wedge.

Fleet sites (``router.route`` — fail + recurring latency on the route
path, ``router.hedge`` — fail at hedge launch, ``replica.death`` — a
``flag`` plan the router polls each step to kill a live replica;
docs/resilience.md §Fleet) drive the front-door chaos matrix in
``tests/test_fleet.py`` and ``tools/fleet_chaos.py``.

Race sites (``race.*``; docs/ds_race.md §Stress mode): the ds_race
schedule-perturbation harness wraps instrumented lock acquire/release
sites with :func:`check_race`, and two recurring, probabilistic plan
kinds widen the interleaving space a seeded run explores:

* ``race.yield`` — ``time.sleep(0)``: drop the GIL so another runnable
  thread is scheduled at this instruction;
* ``race.stall`` — a sub-millisecond sleep: hold a lock (or a gap
  between a read and its write-back) open long enough for a conflicting
  thread to land inside it.

``fire_race`` consults the exact site first, then the ``race.*``
catch-all, so a plan can jitter every instrumented lock while pinning a
heavier stall on one suspect site.
"""
from __future__ import annotations

import json
import os
import random
import signal
import time
from typing import Dict, List, Optional, Tuple

DS_FAULT_PLAN_ENV = "DS_FAULT_PLAN"


class InjectedFault(OSError):
    """A planned transient I/O failure."""


class InjectedKill(BaseException):
    """A planned process death (uncatchable by ``except Exception``)."""


_ACTIVE: Optional["FaultInjector"] = None


def check(site: str, path: Optional[str] = None) -> None:
    """Raise if the active injector has a raising plan armed for ``site``."""
    if _ACTIVE is not None:
        _ACTIVE.fire(site, path)


def check_flag(site: str) -> bool:
    """True if the active injector has a non-raising flag armed for
    ``site`` (e.g. "pretend this step overflowed")."""
    if _ACTIVE is None:
        return False
    return _ACTIVE.fire_flag(site)


def check_stall(site: str) -> float:
    """Sleep for the planned stall duration at ``site`` (0 when no stall
    is armed).  Returns the seconds slept — the hung-collective tests
    assert attribution against it."""
    if _ACTIVE is None:
        return 0.0
    seconds = _ACTIVE.fire_stall(site)
    if seconds > 0:
        time.sleep(seconds)
    return seconds


def check_latency(site: str) -> float:
    """Sleep for the planned *recurring* latency at ``site`` (0 when no
    latency plan is armed).  Unlike :func:`check_stall` this fires on
    every call (up to the plan's ``times``, default unbounded) — the
    slow-decode injection the serving overload tests drive queue
    pressure with."""
    if _ACTIVE is None:
        return 0.0
    seconds = _ACTIVE.fire_latency(site)
    if seconds > 0:
        time.sleep(seconds)
    return seconds


def check_race(site: str) -> None:
    """Schedule-perturbation point for the ds_race stress harness
    (docs/ds_race.md §Stress mode).  Instrumented lock wrappers call
    this before and after acquiring; an armed ``race.yield`` plan drops
    the GIL (``sleep(0)``), a ``race.stall`` plan holds the site open
    for a sub-millisecond beat.  Free when no injector is active — one
    global ``None`` check, same cost model as :func:`check`."""
    if _ACTIVE is not None:
        seconds = _ACTIVE.fire_race(site)
        if seconds >= 0:
            time.sleep(seconds)


class FaultInjector:
    """Seeded, per-site fault plans.  Use as a context manager::

        inj = FaultInjector(seed=0)
        inj.fail("ckpt.save.state", times=2)      # first two calls raise
        inj.kill("ckpt.commit")                   # then die at commit
        with inj:
            engine.save_checkpoint(d)
    """

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self._plans: Dict[str, dict] = {}
        self.log: List[Tuple[str, str]] = []  # (site, event)

    # -- plan registration ------------------------------------------------
    def _plan(self, site: str, exc, times: int, after: int, probability: Optional[float],
              kind: Optional[str] = None, seconds: float = 0.0) -> None:
        self._plans[site] = {
            "exc": exc, "times": times, "after": after,
            "probability": probability, "calls": 0, "fired": 0,
            "kind": kind or ("flag" if exc is None else "raise"),
            "seconds": float(seconds),
        }

    def fail(self, site: str, times: int = 1, after: int = 0, exc=InjectedFault,
             probability: Optional[float] = None) -> "FaultInjector":
        """Arm ``site`` to raise ``exc`` for its next ``times`` triggers
        (skipping the first ``after`` calls)."""
        self._plan(site, exc, times, after, probability)
        return self

    def kill(self, site: str, after: int = 0) -> "FaultInjector":
        """Arm ``site`` to simulate process death (InjectedKill)."""
        self._plan(site, InjectedKill, 1, after, None)
        return self

    def flag(self, site: str, times: int = 1, after: int = 0) -> "FaultInjector":
        """Arm a non-raising flag at ``site`` (check_flag returns True)."""
        self._plan(site, None, times, after, None)
        return self

    def sigkill(self, site: str, after: int = 0) -> "FaultInjector":
        """Arm a REAL ``SIGKILL`` of this process at ``site`` — no Python
        unwinding, no atexit.  Only meaningful in subprocess tests; the
        in-process analog is :meth:`kill`."""
        self._plan(site, None, 1, after, None, kind="sigkill")
        return self

    def stall(self, site: str, seconds: float, times: int = 1, after: int = 0) -> "FaultInjector":
        """Arm a ``seconds``-long sleep at ``site`` (``check_stall``) —
        a wedged-but-alive collective."""
        self._plan(site, None, times, after, None, kind="stall", seconds=seconds)
        return self

    def latency(self, site: str, seconds: float, times: int = 0, after: int = 0) -> "FaultInjector":
        """Arm a *recurring* ``seconds``-long sleep at ``site``
        (``check_latency``): every call sleeps, up to ``times`` fires
        (``0`` = unbounded) — a persistently slow decode step for the
        serving overload harness, not a one-shot wedge."""
        self._plan(site, None, times if times > 0 else 1 << 30, after, None,
                   kind="latency", seconds=seconds)
        return self

    def race_yield(self, site: str, probability: float = 0.5, times: int = 0,
                   after: int = 0) -> "FaultInjector":
        """Arm a *recurring, probabilistic* GIL yield (``sleep(0)``) at
        ``site`` (``check_race``).  ``site`` may be the ``race.*``
        catch-all, which matches every race site without an exact plan
        of its own.  ``times=0`` = unbounded."""
        self._plan(site, None, times if times > 0 else 1 << 30, after,
                   probability, kind="race.yield", seconds=0.0)
        return self

    def race_stall(self, site: str, seconds: float = 0.0002,
                   probability: float = 0.1, times: int = 0,
                   after: int = 0) -> "FaultInjector":
        """Arm a recurring, probabilistic sub-millisecond stall at a
        race site — long enough for a conflicting thread to land inside
        the window the stall holds open."""
        self._plan(site, None, times if times > 0 else 1 << 30, after,
                   probability, kind="race.stall", seconds=seconds)
        return self

    # -- firing -----------------------------------------------------------
    def _triggers(self, plan: dict) -> bool:
        plan["calls"] += 1
        if plan["fired"] >= plan["times"] or plan["calls"] <= plan["after"]:
            return False
        if plan["probability"] is not None and self.rng.random() >= plan["probability"]:
            return False
        plan["fired"] += 1
        return True

    def fire(self, site: str, path: Optional[str] = None) -> None:
        plan = self._plans.get(site)
        if plan is None:
            return
        if plan["kind"] == "sigkill":
            if self._triggers(plan):
                self.log.append((site, "sigkill"))
                os.kill(os.getpid(), signal.SIGKILL)
            return
        if plan["exc"] is None:
            return
        if self._triggers(plan):
            self.log.append((site, plan["exc"].__name__))
            raise plan["exc"](f"injected fault at site '{site}'" + (f" ({path})" if path else ""))

    def fire_flag(self, site: str) -> bool:
        plan = self._plans.get(site)
        if plan is None or plan["kind"] != "flag":
            return False
        if self._triggers(plan):
            self.log.append((site, "flag"))
            return True
        return False

    def fire_stall(self, site: str) -> float:
        plan = self._plans.get(site)
        if plan is None or plan["kind"] != "stall":
            return 0.0
        if self._triggers(plan):
            self.log.append((site, "stall"))
            return plan["seconds"]
        return 0.0

    def fire_latency(self, site: str) -> float:
        plan = self._plans.get(site)
        if plan is None or plan["kind"] != "latency":
            return 0.0
        if self._triggers(plan):
            # one log line per site, not per fire: latency plans fire on
            # every decode step and would otherwise flood the log
            if plan["fired"] == 1:
                self.log.append((site, "latency"))
            return plan["seconds"]
        return 0.0

    def fire_race(self, site: str) -> float:
        """Seconds to sleep at a race site, or ``-1.0`` when nothing
        fires (``check_race`` treats ``>= 0`` as "sleep", so a yield
        plan returns ``0.0`` and still drops the GIL).  The exact site
        is consulted first; sites without their own plan fall through to
        the ``race.*`` catch-all."""
        plan = self._plans.get(site)
        if plan is None or not plan["kind"].startswith("race."):
            plan = self._plans.get("race.*")
        if plan is None or not plan["kind"].startswith("race."):
            return -1.0
        if self._triggers(plan):
            if plan["fired"] == 1:  # one log line per site (see latency)
                self.log.append((site, plan["kind"]))
            return plan["seconds"] if plan["kind"] == "race.stall" else 0.0
        return -1.0

    def calls(self, site: str) -> int:
        plan = self._plans.get(site)
        return plan["calls"] if plan else 0

    # -- direct corruption helpers (for committed tags) -------------------
    @staticmethod
    def truncate_file(path: str, keep_bytes: int = 0) -> None:
        with open(path, "r+b") as f:
            f.truncate(keep_bytes)

    def corrupt_file(self, path: str) -> None:
        """Flip one byte in the middle of the file (seeded position)."""
        size = os.path.getsize(path)
        if size == 0:
            return
        pos = self.rng.randrange(size)
        with open(path, "r+b") as f:
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]))

    # -- multi-process plan propagation (DS_FAULT_PLAN) -------------------
    _EXC_NAMES = {"InjectedFault": InjectedFault, "InjectedKill": InjectedKill,
                  "OSError": OSError, "RuntimeError": RuntimeError}

    def to_plan(self) -> str:
        """Serialize the armed plans to the ``DS_FAULT_PLAN`` JSON form
        (rank filters are added by the caller — see :func:`plan_json`)."""
        entries = []
        for site, p in self._plans.items():
            entries.append({
                "site": site,
                "action": {"raise": "fail", "flag": "flag", "sigkill": "sigkill",
                           "stall": "stall", "latency": "latency",
                           "race.yield": "race.yield",
                           "race.stall": "race.stall"}[p["kind"]],
                "times": p["times"], "after": p["after"], "seconds": p["seconds"],
                **({"exc": p["exc"].__name__} if p["exc"] is not None and p["kind"] == "raise" else {}),
                **({"probability": p["probability"]} if p["probability"] is not None else {}),
            })
        return json.dumps({"seed": 0, "plans": entries})

    @classmethod
    def from_plan(cls, spec: str, rank: Optional[int] = None) -> "FaultInjector":
        """Build an injector from the JSON plan, keeping only entries
        whose ``rank`` filter matches (absent filter = every rank)."""
        d = json.loads(spec)
        inj = cls(seed=int(d.get("seed", 0)))
        for e in d.get("plans", []):
            r = e.get("rank")
            if r is not None and rank is not None:
                ranks = r if isinstance(r, list) else [r]
                if rank not in [int(x) for x in ranks]:
                    continue
            site = e["site"]
            action = e.get("action", "fail")
            times = int(e.get("times", 1))
            after = int(e.get("after", 0))
            if action == "fail":
                exc = cls._EXC_NAMES.get(e.get("exc", "InjectedFault"), InjectedFault)
                inj.fail(site, times=times, after=after, exc=exc,
                         probability=e.get("probability"))
            elif action == "kill":
                inj.kill(site, after=after)
            elif action == "sigkill":
                inj.sigkill(site, after=after)
            elif action == "flag":
                inj.flag(site, times=times, after=after)
            elif action == "stall":
                inj.stall(site, float(e.get("seconds", 1.0)), times=times, after=after)
            elif action == "latency":
                # times defaults to 1 via the shared parse above, but a
                # latency plan's natural default is "every call"
                inj.latency(site, float(e.get("seconds", 0.01)),
                            times=int(e.get("times", 0)), after=after)
            elif action == "race.yield":
                inj.race_yield(site, probability=float(e.get("probability", 0.5)),
                               times=int(e.get("times", 0)), after=after)
            elif action == "race.stall":
                inj.race_stall(site, seconds=float(e.get("seconds", 0.0002)),
                               probability=float(e.get("probability", 0.1)),
                               times=int(e.get("times", 0)), after=after)
            else:
                raise ValueError(f"unknown fault action '{action}' for site '{site}'")
        return inj

    # -- installation -----------------------------------------------------
    def __enter__(self) -> "FaultInjector":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a FaultInjector is already active")
        _ACTIVE = self
        return self

    def __exit__(self, *exc_info) -> None:
        global _ACTIVE
        _ACTIVE = None


def plan_json(plans: List[dict], seed: int = 0) -> str:
    """Compose a ``DS_FAULT_PLAN`` value from raw entries, e.g.::

        plan_json([{"site": "step.boundary", "action": "sigkill",
                    "rank": 1, "after": 3}])
    """
    return json.dumps({"seed": seed, "plans": plans})


def install_from_env(rank: Optional[int] = None) -> Optional[FaultInjector]:
    """Install the injector described by ``DS_FAULT_PLAN`` for the rest
    of this process's life (no context manager: launcher-spawned
    children die with their plan).  ``rank`` defaults to the launcher's
    ``RANK`` env.  No-op (returns None) without the env var, with an
    empty filtered plan, or when an injector is already active."""
    global _ACTIVE
    spec = os.environ.get(DS_FAULT_PLAN_ENV)
    if not spec or _ACTIVE is not None:
        return None
    if rank is None:
        rank = int(os.environ.get("RANK", "0"))
    inj = FaultInjector.from_plan(spec, rank=rank)
    if not inj._plans:
        return None
    _ACTIVE = inj
    return inj
