"""The per-rank supervisor: heartbeat publishing, peer-death detection,
armed deadlines around blocking syncs, and rescue orchestration.

Every rank runs one :class:`Supervisor` (engine-owned when
``resilience.supervision.enabled``).  Two background threads:

* the **publisher** beats the side channel every ``beat_interval``
  (suppressible via the ``hb.drop`` fault site, for tests);
* the **monitor** polls the channel for peer events, checks armed-region
  deadlines, and on a peer death / deadline expiry runs the rescue
  protocol.

Rescue protocol (the "survivor commits and exits 44" contract):

1. a peer-death notice or an armed deadline expiry sets
   :attr:`peer_failure` / records the stuck site;
2. the main thread gets ``rescue_grace`` seconds to handle it itself —
   either its blocking sync errors out (the armed region's ``__exit__``
   converts that into the engine's peer-failure handler) or it reaches
   the next step boundary (which polls :attr:`peer_failure`);
3. if the main thread never surfaces (truly wedged in a dead
   collective), the monitor thread commits the emergency tag ITSELF
   from the last step-boundary host snapshot
   (:func:`~.rescue.emergency_local_save` — pure host I/O, no JAX) and
   hard-exits ``44``; with no usable snapshot/save-dir it exits ``1``
   ("crashed — resume from the previous tag").

Armed regions are how blocking syncs become supervisable::

    with supervisor.armed("ckpt_stage_barrier"):
        multihost_utils.sync_global_devices(...)

``supervised_sync`` wraps the common case (and carries the
``collective.stall`` fault-injection site so hung-collective handling
is provable in-process).
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.supervision.rescue import SnapshotBox, emergency_local_save
from deepspeed_tpu.utils.logging import logger

EXIT_PEER_FAILED_SAVED = 44


@dataclass
class PeerFailure:
    rank: int
    reason: str
    detected_at: float = field(default_factory=time.monotonic)


@dataclass
class _ArmedRegion:
    site: str
    deadline: float  # monotonic
    armed_at: float


class Supervisor:
    """One per rank.  ``exit_fn``/``clock`` are injectable for tests;
    ``on_rescue`` replaces the default save-and-exit (tests again)."""

    def __init__(
        self,
        rank: int,
        world_size: int,
        channel,
        beat_interval: float = 1.0,
        sync_timeout: float = 300.0,
        rescue_grace: float = 5.0,
        exit_code: int = EXIT_PEER_FAILED_SAVED,
        save_dir_fn: Optional[Callable[[], Optional[str]]] = None,
        checksum: str = "sha256",
        on_rescue: Optional[Callable[[str, str], None]] = None,
        exit_fn: Callable[[int], None] = os._exit,
        clock: Callable[[], float] = time.monotonic,
        metrics_fn: Optional[Callable[[], Optional[Dict[str, float]]]] = None,
        aggregator=None,
    ):
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.channel = channel
        self.beat_interval = float(beat_interval)
        self.sync_timeout = float(sync_timeout)
        self.rescue_grace = float(rescue_grace)
        self.exit_code = int(exit_code)
        self.save_dir_fn = save_dir_fn or (lambda: None)
        self.checksum = checksum
        self.on_rescue = on_rescue
        self.exit_fn = exit_fn
        self._clock = clock
        # telemetry piggyback (docs/telemetry.md): metrics_fn supplies
        # this rank's compact snapshot per beat; the rank-0 supervisor
        # feeds peer snapshots + death marks to the aggregator
        self.metrics_fn = metrics_fn
        self.aggregator = aggregator

        self.snapshot = SnapshotBox()
        self.peer_failure: Optional[PeerFailure] = None
        self.last_stuck_site: Optional[str] = None
        self.main_handling = False  # main thread took over the rescue
        self.rescued = False
        self._rescue_owner: Optional[str] = None  # CAS'd; one saver only
        self._regions: Dict[int, _ArmedRegion] = {}  # thread id -> region
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._failure_evt = threading.Event()
        self._threads: list = []
        self._started = False
        self._beat_seq = 0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Supervisor":
        if self._started:
            return self
        self.channel.start()
        for name, fn in (("ds-sup-beat", self._beat_loop), ("ds-sup-monitor", self._monitor_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self._started = True
        import atexit

        atexit.register(self.stop)
        return self

    def stop(self) -> None:
        """Clean shutdown: publish a goodbye (departing is not dying)
        and stop the threads.  Idempotent."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self.channel.goodbye()
        except Exception:  # noqa: BLE001 — shutdown must not raise
            pass
        self.channel.stop()

    # -- background loops -------------------------------------------------
    def _beat_loop(self) -> None:
        while not self._stop.wait(self.beat_interval):
            self._beat_seq += 1
            if faults.check_flag("hb.drop"):
                continue  # injected heartbeat suppression (tests)
            try:
                metrics = None
                if self.metrics_fn is not None:
                    try:
                        metrics = self.metrics_fn()
                    except Exception as e:  # noqa: BLE001 — beats must not die with metrics
                        logger.warning(f"supervision: metrics snapshot failed: {e!r}")
                if metrics:
                    self.channel.beat(self._beat_seq, metrics=metrics)
                else:
                    self.channel.beat(self._beat_seq)
            except Exception as e:  # noqa: BLE001
                logger.warning(f"supervision: beat publish failed: {e!r}")

    def _feed_aggregator(self) -> None:
        """Pump piggybacked peer snapshots into the rank-0 aggregator
        and export when anything changed (JSONL stream + cluster/*
        gauges; docs/telemetry.md)."""
        agg = self.aggregator
        if agg is None:
            return
        # peer_metrics() already includes this rank's own snapshot (both
        # channels record it in beat()), so the channel table is the one
        # feed; equal-seq re-feeds are deduped by the aggregator
        peer_metrics = getattr(self.channel, "peer_metrics", None)
        if peer_metrics is not None:
            for r, (seq, m) in peer_metrics().items():
                agg.update(r, seq, m)
        agg.export_line()

    def _monitor_loop(self) -> None:
        period = max(0.05, min(0.5, self.beat_interval / 2.0))
        while not self._stop.wait(period):
            try:
                self._feed_aggregator()
                for ev in self.channel.events():
                    if self.aggregator is not None and ev.kind == "bye":
                        self.aggregator.mark_bye(ev.rank)
                        self.aggregator.export_line()
                    if ev.kind == "dead" and self.peer_failure is None:
                        self.peer_failure = PeerFailure(ev.rank, ev.reason)
                        self._failure_evt.set()
                        logger.error(
                            f"supervision: rank {ev.rank} declared dead ({ev.reason})"
                        )
                        if self.aggregator is not None:
                            # the dead rank must appear in the exported
                            # aggregate stream BEFORE any rescue exit
                            self.aggregator.mark_dead(ev.rank, ev.reason)
                            self.aggregator.export_line(force=True)
                        self._run_rescue(
                            site=self._current_site() or "idle",
                            reason=f"peer rank {ev.rank} failed: {ev.reason}",
                        )
                        return
                expired = self._expired_region()
                if expired is not None:
                    self.last_stuck_site = expired.site
                    # the REGION's own timeout, not the global default —
                    # per-site overrides must be attributed correctly
                    timeout = expired.deadline - expired.armed_at
                    logger.error(
                        f"supervision: blocking sync '{expired.site}' exceeded its "
                        f"{timeout:g}s deadline (armed "
                        f"{self._clock() - expired.armed_at:.1f}s ago) — treating as hung collective"
                    )
                    self._run_rescue(
                        site=expired.site,
                        reason=f"collective '{expired.site}' hung past its {timeout:g}s deadline",
                    )
                    return
            except Exception as e:  # noqa: BLE001 — the monitor must survive
                logger.warning(f"supervision monitor error: {e!r}")

    # -- armed regions ----------------------------------------------------
    def armed(self, site: str, timeout: Optional[float] = None):
        """Context manager: a deadline around one blocking sync.  On an
        exception inside the region, a pending peer failure is allowed a
        moment to confirm (the collective usually errors *before* the
        beat timeout) so callers can attribute the error to the death."""
        return _Armed(self, site, self.sync_timeout if timeout is None else float(timeout))

    def _current_site(self) -> Optional[str]:
        with self._lock:
            for region in self._regions.values():
                return region.site
        return None

    def _expired_region(self) -> Optional[_ArmedRegion]:
        now = self._clock()
        with self._lock:
            for region in self._regions.values():
                if now >= region.deadline:
                    return region
        return None

    # -- failure handling -------------------------------------------------
    def confirm_peer_failure(self, wait: float = 0.0) -> Optional[PeerFailure]:
        """The current peer failure, optionally waiting up to ``wait``
        seconds for detection to land (a collective often errors out
        milliseconds after the peer dies, before the channel notices)."""
        if self.peer_failure is None and wait > 0:
            self._failure_evt.wait(wait)
        return self.peer_failure

    def snapshot_due(self, step: int, interval: int) -> bool:
        return interval > 0 and step > self.snapshot.step and step % max(1, interval) == 0

    def claim_rescue(self, owner: str) -> bool:
        """Exactly ONE thread commits the emergency tag (both staging
        the same tag would make the loser report exit 1 over a
        committed, verified save).  Idempotent for the winner."""
        with self._lock:
            if self._rescue_owner is None:
                self._rescue_owner = owner
            return self._rescue_owner == owner

    def _run_rescue(self, site: str, reason: str) -> None:
        self.last_stuck_site = site
        if self.on_rescue is not None:
            self.on_rescue(site, reason)
            return
        # grace: let the main thread surface (error out of the armed
        # region, or hit the next step boundary) and run the clean
        # handler itself — its state may be fresher than the snapshot
        deadline = self._clock() + self.rescue_grace
        while self._clock() < deadline:
            if self.main_handling:
                return  # main thread owns the exit now
            time.sleep(0.05)
        if self.main_handling or not self.claim_rescue("monitor"):
            return  # the main thread owns (or just claimed) the rescue
        logger.error(
            f"supervision: main thread did not surface within {self.rescue_grace:g}s "
            f"(stuck at '{site}'); committing emergency tag from the supervisor thread"
        )
        code = self.rescue_save(reason=reason)
        self.stop()
        self.exit_fn(code)

    def rescue_save(self, reason: str = "") -> int:
        """Commit the last step-boundary snapshot as a verified
        ``local_npz`` tag.  Returns the exit code the caller must use:
        ``exit_code`` (44) on a committed tag, 1 otherwise."""
        snapshot, meta = self.snapshot.get()
        save_dir = self.save_dir_fn()
        if snapshot is None or save_dir is None:
            logger.error(
                "supervision rescue: no usable snapshot/checkpoint dir "
                f"(snapshot={'yes' if snapshot is not None else 'no'}, "
                f"dir={save_dir}); cannot certify a save — exit 1"
            )
            return 1
        meta = dict(meta or {})
        meta["rescue_reason"] = reason
        meta["rescue_rank"] = self.rank
        tag = f"emergency_step{self.snapshot.step}_rank{self.rank}"
        try:
            path = emergency_local_save(
                save_dir, tag, snapshot, meta, checksum=self.checksum
            )
        except BaseException as e:  # a failed save must NOT exit as "saved"
            logger.error(f"supervision rescue: emergency save failed: {e!r}")
            return 1
        self.rescued = True
        from deepspeed_tpu import telemetry as _tel

        _tel.get_registry().counter("supervision/emergency_saves", rank=self.rank).inc()
        # the caller exits via os._exit (no atexit): flush the sinks and
        # the aggregate stream NOW or the counter never reaches disk
        try:
            if self.aggregator is not None:
                self.aggregator.export_line(force=True)
            _tel.flush()
        except Exception:  # noqa: BLE001 — the exit code matters more
            pass
        logger.error(
            f"supervision rescue: committed verified emergency tag {path}; "
            f"exit {self.exit_code} (peer-failed-and-saved)"
        )
        return self.exit_code


class _Armed:
    def __init__(self, sup: Supervisor, site: str, timeout: float):
        self.sup = sup
        self.site = site
        self.timeout = timeout

    def __enter__(self):
        now = self.sup._clock()
        with self.sup._lock:
            self.sup._regions[threading.get_ident()] = _ArmedRegion(
                site=self.site, deadline=now + self.timeout, armed_at=now
            )
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        with self.sup._lock:
            self.sup._regions.pop(threading.get_ident(), None)
        return False  # never swallow; callers decide what an error means


def supervised_sync(name: str, supervisor: Optional[Supervisor] = None,
                    timeout: Optional[float] = None) -> None:
    """A watchdog-armed cross-process barrier (the sanctioned blocking
    sync — ds_lint's ``unguarded-collective-barrier`` flags bare ones).
    Carries the ``collective.stall`` fault site so hung-collective
    handling is provable without a real wedged pod."""
    from contextlib import nullcontext

    with supervisor.armed(f"barrier:{name}", timeout=timeout) if supervisor is not None else nullcontext():
        faults.check_stall("collective.stall")
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
