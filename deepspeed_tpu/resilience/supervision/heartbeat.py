"""Liveness side channels, independent of the ICI collectives.

Both channels carry the same tiny protocol: each rank periodically
publishes a beat (monotonically increasing sequence number); a clean
shutdown publishes a goodbye so departing ranks are never mistaken for
dead ones.  The consumer (:class:`~.supervisor.Supervisor`) polls
:meth:`events` for :class:`PeerEvent` records.

* :class:`TcpBeatChannel` — the launcher-distributed channel: the
  rank-0 supervisor runs a small line-protocol server
  (``DS_SUPERVISION_PORT``, set by ``launcher/launch.py``); every other
  rank keeps one client connection open and writes beats to it.  A
  SIGKILL'd rank's kernel closes the socket, so death is *detected* by
  EOF within one poll cycle — no timeout inference needed.  The server
  broadcasts ``dead <rank>`` notices to the surviving clients, and a
  client treats loss of the server connection as rank-0 death.

* :class:`FileBeatChannel` — shared-filesystem fallback (tests,
  single-node): each rank atomically rewrites ``<dir>/rank<i>.beat``;
  staleness beyond the beat timeout means death.  Strictly weaker
  (timeout-only detection) but needs no network and survives any
  launcher.

Telemetry piggyback (docs/telemetry.md): ``beat(seq, metrics=...)``
optionally carries the rank's compact metric snapshot — one extra JSON
payload on the line/file the channel already writes, no new
connections, nothing on the hot path.  The consumer side retains the
latest ``(seq, metrics)`` per peer (:meth:`peer_metrics`); rank 0's
supervisor feeds them to the cross-rank aggregator, which flags dead
ranks in the same exported stream the metrics ride in.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from deepspeed_tpu.resilience import atomic

# env var the launcher sets so every rank agrees on the side-channel
# endpoint without a config edit (launch.py derives it from master_port)
SUPERVISION_PORT_ENV = "DS_SUPERVISION_PORT"
SUPERVISION_ADDR_ENV = "DS_SUPERVISION_ADDR"


@dataclass
class PeerEvent:
    """One liveness transition observed on the channel."""

    rank: int
    kind: str  # "dead" | "bye" (clean departure)
    reason: str = ""
    at: float = field(default_factory=time.monotonic)


class _EventSink:
    """Thread-safe accumulator both channels feed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[PeerEvent] = []
        self._seen: set = set()  # (rank, kind) dedup

    def push(self, ev: PeerEvent) -> None:
        with self._lock:
            key = (ev.rank, ev.kind)
            if key in self._seen:
                return
            self._seen.add(key)
            self._events.append(ev)

    def drain(self) -> List[PeerEvent]:
        with self._lock:
            out, self._events = self._events, []
            return out

    def departed(self, rank: int) -> bool:
        with self._lock:
            return (rank, "bye") in self._seen or (rank, "dead") in self._seen


class FileBeatChannel:
    """Beat files on a shared filesystem.  Symmetric: every rank both
    publishes its own file and scans the others'.

    Staleness is judged by the beat SEQUENCE not advancing against the
    observer's own monotonic clock — never by comparing file mtimes to
    the local wall clock, which cross-host clock skew on a shared
    filesystem would defeat."""

    name = "file"

    def __init__(self, beat_dir: str, rank: int, world_size: int, beat_timeout: float = 5.0):
        self.beat_dir = os.path.abspath(beat_dir)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.beat_timeout = float(beat_timeout)
        self._sink = _EventSink()
        self._first_seen: Dict[int, float] = {}
        # rank -> (last observed seq, local-monotonic time it changed)
        self._last_change: Dict[int, tuple] = {}
        # rank -> (seq, compact metric snapshot) — telemetry piggyback
        self._peer_metrics: Dict[int, tuple] = {}
        os.makedirs(self.beat_dir, exist_ok=True)

    def _path(self, rank: int) -> str:
        return os.path.join(self.beat_dir, f"rank{rank}.beat")

    def start(self) -> None:  # nothing to spin up
        pass

    def beat(self, seq: int, metrics: Optional[Dict[str, float]] = None) -> None:
        doc = {"rank": self.rank, "seq": int(seq)}
        if metrics:
            doc["metrics"] = metrics
        atomic.atomic_write_text(self._path(self.rank), json.dumps(doc))
        if metrics:
            self._peer_metrics[self.rank] = (int(seq), dict(metrics))

    def peer_metrics(self) -> Dict[int, tuple]:
        """Latest ``(seq, metrics)`` piggybacked per rank (incl. own)."""
        return dict(self._peer_metrics)

    def goodbye(self) -> None:
        atomic.atomic_write_text(
            self._path(self.rank), json.dumps({"rank": self.rank, "bye": True})
        )

    def events(self) -> List[PeerEvent]:
        now = time.monotonic()
        for r in range(self.world_size):
            if r == self.rank or self._sink.departed(r):
                continue
            path = self._path(r)
            try:
                with open(path) as f:
                    data = json.loads(f.read() or "{}")
            except (OSError, ValueError):
                # not written yet — give the rank the full timeout from
                # the moment WE first looked for it
                self._first_seen.setdefault(r, now)
                if now - self._first_seen[r] > self.beat_timeout * 3:
                    self._sink.push(PeerEvent(r, "dead", "no beat file ever appeared"))
                continue
            if data.get("bye"):
                self._sink.push(PeerEvent(r, "bye", "clean departure"))
                continue
            if isinstance(data.get("metrics"), dict):
                self._peer_metrics[r] = (int(data.get("seq") or 0), data["metrics"])
            seq = data.get("seq")
            last = self._last_change.get(r)
            if last is None or last[0] != seq:
                self._last_change[r] = (seq, now)
            elif now - last[1] > self.beat_timeout:
                self._sink.push(
                    PeerEvent(r, "dead",
                              f"beat stale for >{self.beat_timeout:g}s (beat-timeout)")
                )
        return self._sink.drain()

    def stop(self) -> None:
        pass


class TcpBeatChannel:
    """Rank-0 server + per-rank client over one TCP line protocol.

    Lines: ``hello <rank>``, ``beat <rank> <seq> [metrics-json]``,
    ``bye <rank>`` from clients; ``dead <rank>`` / ``bye <rank>``
    notices from the server.  The optional metrics payload is compact
    JSON with no whitespace (the line is whitespace-split), produced by
    :func:`deepspeed_tpu.telemetry.encode_metrics`.
    """

    name = "tcp"

    def __init__(
        self,
        rank: int,
        world_size: int,
        address: str = "127.0.0.1",
        port: int = 0,
        beat_timeout: float = 5.0,
        connect_grace: float = 30.0,
    ):
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.address = address
        self.port = int(port)
        self.beat_timeout = float(beat_timeout)
        self.connect_grace = float(connect_grace)
        self._sink = _EventSink()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._server: Optional[socket.socket] = None
        self._client: Optional[socket.socket] = None
        self._client_lock = threading.Lock()
        # server state
        self._conns: Dict[int, socket.socket] = {}
        self._all_conns: List[socket.socket] = []  # accepted, incl. pre-hello
        self._conns_lock = threading.Lock()
        self._last_beat: Dict[int, float] = {}
        self._started_at = 0.0
        # rank -> (seq, compact metric snapshot) — telemetry piggyback
        self._peer_metrics: Dict[int, tuple] = {}
        self._metrics_lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._started_at = time.monotonic()
        if self.rank == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("", self.port))
            srv.listen(self.world_size + 4)
            srv.settimeout(0.25)
            self.port = srv.getsockname()[1]
            self._server = srv
            t = threading.Thread(target=self._accept_loop, name="ds-sup-accept", daemon=True)
            t.start()
            self._threads.append(t)
        else:
            t = threading.Thread(target=self._client_loop, name="ds-sup-client", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        with self._conns_lock:
            conns = list(self._all_conns)
        for s in ([self._server] + conns + [self._client]):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    # -- publishing -------------------------------------------------------
    def beat(self, seq: int, metrics: Optional[Dict[str, float]] = None) -> None:
        if metrics:
            with self._metrics_lock:
                # own metrics recorded locally on every rank; rank 0's
                # land straight in the table the aggregator reads
                self._peer_metrics[self.rank] = (int(seq), dict(metrics))
        if self.rank == 0:
            self._last_beat[0] = time.monotonic()  # server beats locally
            return
        payload = ""
        if metrics:
            from deepspeed_tpu.telemetry import encode_metrics

            payload = " " + encode_metrics(metrics)
        self._send(f"beat {self.rank} {int(seq)}{payload}\n")

    def peer_metrics(self) -> Dict[int, tuple]:
        """Latest ``(seq, metrics)`` piggybacked per rank (incl. own)."""
        with self._metrics_lock:
            return dict(self._peer_metrics)

    def goodbye(self) -> None:
        if self.rank == 0:
            self._broadcast(f"bye 0\n")
            return
        self._send(f"bye {self.rank}\n")

    def _send(self, line: str) -> None:
        with self._client_lock:
            c = self._client
        if c is None:
            return
        try:
            c.sendall(line.encode())
        except OSError:
            # server unreachable: the reader loop raises the event
            pass

    # -- consuming --------------------------------------------------------
    def events(self) -> List[PeerEvent]:
        if self.rank == 0:
            now = time.monotonic()
            with self._conns_lock:
                connected = set(self._conns)
            for r in range(1, self.world_size):
                if self._sink.departed(r):
                    continue
                last = self._last_beat.get(r)
                if last is None:
                    if r not in connected and now - self._started_at > self.connect_grace:
                        self._notice_dead(r, "never connected to the supervision channel")
                elif now - last > self.beat_timeout:
                    self._notice_dead(r, f"beat stale for >{self.beat_timeout:g}s (beat-timeout)")
        return self._sink.drain()

    # -- server internals -------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conns_lock:
                self._all_conns.append(conn)
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), name="ds-sup-conn", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        peer_rank: Optional[int] = None
        buf = b""
        try:
            # inside the try: stop() may close the socket between the
            # accept and here, and that must read as a quiet EOF
            conn.settimeout(0.5)
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(4096)
                except socket.timeout:
                    continue
                if not chunk:
                    break
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    # maxsplit keeps the beat's metrics payload intact
                    # even if a future metric name/label contains spaces
                    parts = line.decode(errors="ignore").split(None, 3)
                    if not parts:
                        continue
                    if parts[0] == "hello" and len(parts) >= 2:
                        peer_rank = int(parts[1])
                        with self._conns_lock:
                            self._conns[peer_rank] = conn
                        self._last_beat[peer_rank] = time.monotonic()
                    elif parts[0] == "beat" and len(parts) >= 2:
                        r = int(parts[1])
                        self._last_beat[r] = time.monotonic()
                        if len(parts) >= 4:
                            from deepspeed_tpu.telemetry import decode_metrics

                            m = decode_metrics(parts[3])
                            if m is not None:
                                with self._metrics_lock:
                                    self._peer_metrics[r] = (int(parts[2]), m)
                    elif parts[0] == "bye" and len(parts) >= 2:
                        r = int(parts[1])
                        self._sink.push(PeerEvent(r, "bye", "clean departure"))
                        self._broadcast(f"bye {r}\n", skip=r)
                        return
        except OSError:
            pass
        # EOF/error without a bye: the kernel closed a dead rank's socket
        if peer_rank is not None and not self._sink.departed(peer_rank):
            self._notice_dead(peer_rank, "supervision socket EOF (rank process died)")

    def _notice_dead(self, rank: int, reason: str) -> None:
        self._sink.push(PeerEvent(rank, "dead", reason))
        self._broadcast(f"dead {rank}\n", skip=rank)

    def _broadcast(self, line: str, skip: Optional[int] = None) -> None:
        with self._conns_lock:
            conns = dict(self._conns)
        for r, c in conns.items():
            if r == skip:
                continue
            try:
                c.sendall(line.encode())
            except OSError:
                pass

    # -- client internals -------------------------------------------------
    def _client_loop(self) -> None:
        deadline = time.monotonic() + self.connect_grace
        sock: Optional[socket.socket] = None
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                sock = socket.create_connection((self.address, self.port), timeout=2.0)
                break
            except OSError:
                time.sleep(0.2)
        if sock is None:
            if not self._stop.is_set():
                self._sink.push(
                    PeerEvent(0, "dead", f"could not reach rank-0 supervisor at "
                                         f"{self.address}:{self.port} within {self.connect_grace:g}s")
                )
            return
        sock.settimeout(0.5)
        with self._client_lock:
            self._client = sock
        try:
            sock.sendall(f"hello {self.rank}\n".encode())
        except OSError:
            pass
        buf = b""
        while not self._stop.is_set():
            try:
                chunk = sock.recv(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                parts = line.decode(errors="ignore").split()
                if len(parts) >= 2 and parts[0] in ("dead", "bye"):
                    self._sink.push(
                        PeerEvent(int(parts[1]), parts[0],
                                  "notice from rank-0 supervisor" if parts[0] == "dead"
                                  else "clean departure")
                    )
        if not self._stop.is_set() and not self._sink.departed(0):
            # lost the server: rank 0 itself died
            self._sink.push(PeerEvent(0, "dead", "supervision socket to rank 0 lost (EOF)"))


def resolve_endpoint(default_port: int = 0) -> tuple:
    """(address, port) for the TCP channel from the launcher env:
    ``DS_SUPERVISION_ADDR`` (default ``MASTER_ADDR`` or localhost) and
    ``DS_SUPERVISION_PORT``."""
    addr = os.environ.get(SUPERVISION_ADDR_ENV) or os.environ.get("MASTER_ADDR") or "127.0.0.1"
    port = int(os.environ.get(SUPERVISION_PORT_ENV, default_port) or default_port)
    return addr, port
