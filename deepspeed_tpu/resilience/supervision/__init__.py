"""Distributed supervision: rank-failure detection, hung-collective
watchdog, elastic restart support (docs/resilience.md §supervision).

PR 2's resilience layer makes a *single process* die safely; this
package gives a multi-host job a failure domain:

* :mod:`~deepspeed_tpu.resilience.supervision.heartbeat` — each rank's
  supervisor thread publishes liveness beats over a side channel that is
  independent of the ICI collectives (launcher-distributed TCP to the
  rank-0 supervisor, with a shared-filesystem beat-file fallback), so a
  SIGKILL'd or wedged rank is *detected* (socket EOF, stale beat)
  rather than inferred from a hang;
* :mod:`~deepspeed_tpu.resilience.supervision.supervisor` —
  :class:`Supervisor`: armed-deadline regions around every blocking
  sync (step boundary, flag-allgather, checkpoint barriers), peer-death
  notices, and the rescue orchestration that turns either into a
  verified emergency tag + exit ``44`` ("peer-failed-and-saved");
* :mod:`~deepspeed_tpu.resilience.supervision.rescue` — the host-only
  emergency save: rank-local state shards to an atomic, manifest-
  verified ``local_npz`` tag with NO collectives, so a survivor can
  still commit after its peers are gone.

Exit-code contract (extends PR 2's):

* ``43`` — preempted (SIGTERM) and saved;
* ``44`` — a peer died / a collective hung, and this rank committed a
  verified emergency tag first.  The launcher's ``--restarts N``
  relaunches on 43/44 at the shrunk world
  (``elasticity.shrink_world_info``) and the engine resumes from the
  newest verified tag through orbax's DP-resize reshard.
"""
from deepspeed_tpu.resilience.supervision.heartbeat import (  # noqa: F401
    FileBeatChannel,
    PeerEvent,
    TcpBeatChannel,
)
from deepspeed_tpu.resilience.supervision.rescue import (  # noqa: F401
    LOCAL_STATE_FILE,
    emergency_local_save,
    load_local_state,
)
from deepspeed_tpu.resilience.supervision.supervisor import (  # noqa: F401
    EXIT_PEER_FAILED_SAVED,
    PeerFailure,
    Supervisor,
    supervised_sync,
)
