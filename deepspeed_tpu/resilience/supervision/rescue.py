"""Host-only emergency checkpoint: commit a verified tag with NO
collectives, so a survivor can still save after its peers are gone.

A normal save is a collective (orbax sharded writes + staging/commit
barriers) — impossible once a peer is dead.  The rescue path instead
writes the rank's *host snapshot* of the portable state (taken at the
last step boundary, where in pure-DP topologies every rank holds the
full logical arrays) as one ``state_local.npz``, then runs the exact
PR 2 durability protocol: stage into ``<tag>.tmp``, ``meta.json``,
size+checksum ``manifest.json`` last, one rename, atomic ``latest``.
The tag is therefore verifiable and quarantine-able like any other, and
``load_checkpoint`` restores it through the same candidate scan
(``meta["format"] == "local_npz"`` routes the restore through
:func:`load_local_state`; orbax's DP-resize reshard is subsumed because
the npz holds full logical arrays that ``device_put`` re-shards for
whatever mesh the restoring job uses).

Non-native dtypes (bfloat16 & friends) are bit-cast to a same-width
integer view for ``np.savez`` and recorded in a dtype sidecar inside
the npz, so the round-trip is exact.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.resilience import atomic, manager
from deepspeed_tpu.utils.logging import logger

LOCAL_STATE_FILE = "state_local.npz"
_DTYPES_KEY = "__dtypes__"
# np.savez handles these natively; anything else ships as a bit-cast
_NATIVE_KINDS = set("biufc?")


def _flatten_with_keystr(tree: Any):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def _bitcast_for_save(arr: np.ndarray) -> Tuple[np.ndarray, Optional[str]]:
    if arr.dtype.kind in _NATIVE_KINDS:
        return arr, None
    width = arr.dtype.itemsize
    view = arr.view({1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}[width])
    return view, str(arr.dtype)


def _bitcast_for_load(arr: np.ndarray, dtype_name: Optional[str]) -> np.ndarray:
    if not dtype_name:
        return arr
    import jax.numpy as jnp

    return arr.view(jnp.dtype(dtype_name))


def emergency_local_save(
    root: str,
    tag: str,
    snapshot: Any,
    meta: Dict[str, Any],
    checksum: str = "sha256",
    save_latest: bool = True,
) -> str:
    """Commit ``snapshot`` (a host pytree of numpy arrays) as a verified
    ``local_npz`` tag under ``root``.  Pure host I/O — safe to call from
    the supervisor thread while the main thread is wedged in a dead
    collective."""
    root = os.path.abspath(root)
    os.makedirs(root, exist_ok=True)
    tag = str(tag)
    meta = dict(meta)
    meta["format"] = "local_npz"
    target = manager.begin_stage(root, tag)
    try:
        arrays: Dict[str, np.ndarray] = {}
        dtypes: Dict[str, str] = {}
        for key, leaf in _flatten_with_keystr(snapshot):
            arr = np.asarray(leaf)
            view, dtype_name = _bitcast_for_save(arr)
            arrays[key] = view
            if dtype_name:
                dtypes[key] = dtype_name
        arrays[_DTYPES_KEY] = np.frombuffer(json.dumps(dtypes).encode(), dtype=np.uint8)
        np.savez(os.path.join(target, LOCAL_STATE_FILE), **arrays)
        atomic.atomic_write_text(os.path.join(target, "meta.json"), json.dumps(meta, indent=2))
        # manifest last: its presence certifies completeness
        atomic.write_manifest(target, algorithm=checksum)
        final = manager.commit_tag(root, tag)
        if save_latest:
            manager.write_latest(root, tag)
        return final
    except BaseException:
        manager.abort_stage(root, tag)
        raise
    finally:
        manager.release_stage(root, tag)


def load_local_state(path: str, target: Any) -> Any:
    """Restore a ``local_npz`` tag into the structure of ``target``
    (keys matched by pytree key-path).  Leaves of ``target`` with no
    saved counterpart come back as zeros of the target shape/dtype
    (logged) — the ``grad_acc``-layout analog of the orbax partial
    restore (at any saved step boundary the accumulator is zeros, so no
    information is lost)."""
    import jax

    npz_path = os.path.join(path, LOCAL_STATE_FILE)
    with np.load(npz_path) as z:
        dtypes = json.loads(bytes(z[_DTYPES_KEY]).decode()) if _DTYPES_KEY in z.files else {}
        data = {k: z[k] for k in z.files if k != _DTYPES_KEY}

    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    out, missing = [], []
    for kpath, leaf in flat:
        key = jax.tree_util.keystr(kpath)
        if key in data:
            out.append(_bitcast_for_load(data[key], dtypes.get(key)))
        else:
            missing.append(key)
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            out.append(np.zeros(shape, dtype) if shape is not None and dtype is not None else leaf)
    if missing:
        logger.warning(
            f"local_npz restore: {len(missing)} leaf(s) absent from the emergency tag "
            f"(restored as zeros): {missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


class SnapshotBox:
    """Latest host snapshot + its metadata, swapped atomically under a
    lock so the supervisor thread always sees a consistent pair."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._snapshot: Optional[Any] = None
        self._meta: Optional[Dict[str, Any]] = None
        self.step: int = -1

    def update(self, snapshot: Any, meta: Dict[str, Any]) -> None:
        with self._lock:
            self._snapshot = snapshot
            self._meta = meta
            self.step = int(meta.get("global_step", -1))

    def get(self) -> Tuple[Optional[Any], Optional[Dict[str, Any]]]:
        with self._lock:
            return self._snapshot, self._meta
