"""Failure policies: bounded retry with backoff, and the divergence guard.

:func:`retry_call` is the one retry implementation in the codebase —
checkpoint I/O and distributed init both route through it, so backoff
behaviour (exponential, capped, deterministic seeded jitter, optional
overall deadline) is uniform and testable.  ``sleep``/``clock`` are
injectable so tests run at full speed.

:class:`DivergenceGuard` watches the step stream for runs of
NaN/overflow-skipped steps (the signature of a diverged run or a
loss-scale floor set too high) and trips a configured action after N
consecutive skips; the engine maps the action string to behaviour
(warn / lower the loss-scale floor / roll back to the last verified
checkpoint).
"""
from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple


class RetryError(RuntimeError):
    """Raised when a retry policy is exhausted (or its deadline passes)."""


@dataclass
class RetryPolicy:
    """Bounded retries with exponential backoff + jitter.

    ``retry_on`` is the exception allow-list; anything else (including
    :class:`~deepspeed_tpu.resilience.faults.InjectedKill`) propagates
    immediately — a process death must never be "retried".
    """

    max_attempts: int = 3
    backoff_seconds: float = 0.5
    backoff_max_seconds: float = 30.0
    jitter: float = 0.25  # extra delay fraction, uniform in [0, jitter)
    timeout_seconds: Optional[float] = None  # overall deadline across attempts
    retry_on: Tuple[type, ...] = (OSError,)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        base = min(self.backoff_seconds * (2.0 ** (attempt - 1)), self.backoff_max_seconds)
        return base * (1.0 + self.jitter * rng.random())


def retry_call(
    policy: RetryPolicy,
    fn: Callable,
    *args,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    seed: int = 0,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` under ``policy``.  Returns the first
    successful result; raises :class:`RetryError` (chained to the last
    failure) on exhaustion or deadline."""
    rng = random.Random(seed)
    start = clock()
    last: Optional[BaseException] = None
    for attempt in range(1, max(1, policy.max_attempts) + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last = e
            if attempt >= policy.max_attempts:
                break
            pause = policy.delay(attempt, rng)
            if (
                policy.timeout_seconds is not None
                and (clock() - start) + pause > policy.timeout_seconds
            ):
                raise RetryError(
                    f"{getattr(fn, '__name__', 'call')} gave up after {attempt} attempt(s): "
                    f"deadline of {policy.timeout_seconds}s would be exceeded"
                ) from e
            if on_retry is not None:
                on_retry(attempt, e, pause)
            from deepspeed_tpu.telemetry import get_registry

            get_registry().counter(
                "resilience/retries", fn=getattr(fn, "__name__", "call")
            ).inc()
            sleep(pause)
    raise RetryError(
        f"{getattr(fn, '__name__', 'call')} failed after {policy.max_attempts} attempt(s): {last!r}"
    ) from last


def retry(policy: RetryPolicy, **retry_kwargs):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        def wrapper(*args, **kwargs):
            return retry_call(policy, fn, *args, **retry_kwargs, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        wrapper.__doc__ = fn.__doc__
        return wrapper

    return deco


@dataclass
class DivergenceGuard:
    """Trip ``action`` after ``threshold`` CONSECUTIVE skipped steps.

    One clean step resets the streak — occasional overflow skips are
    normal dynamic-loss-scale behaviour; a long run of them is not.
    """

    threshold: int = 20
    action: str = "warn"
    streak: int = field(default=0, init=False)
    trips: int = field(default=0, init=False)

    def record(self, diverged: bool) -> Optional[str]:
        """Feed one step's verdict; returns the action string when the
        guard trips (and resets the streak so the action is not
        re-triggered every subsequent step)."""
        if not diverged:
            self.streak = 0
            return None
        self.streak += 1
        if self.streak >= max(1, self.threshold):
            self.streak = 0
            self.trips += 1
            return self.action
        return None
