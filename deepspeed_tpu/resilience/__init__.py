"""Resilience subsystem: the machinery that lets a multi-day run survive
preemption, bad disks and divergence (reference DeepSpeed earns this
with battle-hardened checkpoint/restore paths; here it is an explicit
subsystem with a fault-injection harness proving each recovery path —
``tests/test_resilience.py``).

Pillars:

* :mod:`~deepspeed_tpu.resilience.atomic` — atomic metadata writes and
  per-tag size+checksum manifests (a tag exists fully or not at all);
* :mod:`~deepspeed_tpu.resilience.manager` — stage/commit/quarantine/
  retention over a checkpoint tree;
* :mod:`~deepspeed_tpu.resilience.policy` — the shared retry policy
  (checkpoint I/O, distributed init) and the divergence guard;
* :mod:`~deepspeed_tpu.resilience.watchdog` — SIGTERM → emergency
  checkpoint at the next step boundary → distinctive exit code;
* :mod:`~deepspeed_tpu.resilience.faults` — the deterministic fault
  injector the tests drive everything with.
"""
from deepspeed_tpu.resilience.atomic import (  # noqa: F401
    MANIFEST_FILE,
    atomic_write_text,
    file_digest,
    fsync_dir,
    verify_manifest,
    write_manifest,
)
from deepspeed_tpu.resilience.faults import (  # noqa: F401
    FaultInjector,
    InjectedFault,
    InjectedKill,
)
from deepspeed_tpu.resilience.policy import (  # noqa: F401
    DivergenceGuard,
    RetryError,
    RetryPolicy,
    retry,
    retry_call,
)
from deepspeed_tpu.resilience.watchdog import (  # noqa: F401
    EXIT_PREEMPTED_SAVED,
    PreemptionWatchdog,
)
from deepspeed_tpu.resilience import manager  # noqa: F401


class CheckpointNotFoundError(RuntimeError):
    """Strict-mode load found no loadable (verified) checkpoint."""
