"""Checkpoint-tree operations: staging, commit, quarantine, retention.

Layout of a checkpoint root directory::

    <root>/latest            # atomic pointer file (tag name)
    <root>/<tag>/            # a COMMITTED tag (has manifest.json)
    <root>/<tag>.tmp/        # a staging dir (crashed or in-flight save)
    <root>/<tag>.corrupt*/   # quarantined tags, kept for post-mortem

The commit protocol: everything is written into ``<tag>.tmp``, the
manifest goes in last, then one ``os.rename`` publishes the tag.  A tag
directory without the staging suffix is therefore complete by
construction — a kill at ANY instruction of the save leaves either the
previous tree or the previous tree plus a ``.tmp`` dir, never a
half-written tag.
"""
from __future__ import annotations

import os
import re
import shutil
import threading
from typing import Iterable, List, Optional, Set, Tuple

from deepspeed_tpu.resilience import atomic, faults
from deepspeed_tpu.utils.logging import logger

LATEST_FILE = "latest"
STAGING_SUFFIX = ".tmp"
QUARANTINE_SUFFIX = ".corrupt"
_STEP_RE = re.compile(r"(\d+)\s*$")

# In-process registry of staging dirs an in-flight save OWNS (sync saves
# between begin_stage and commit/abort; async saves for the lifetime of
# the background commit).  begin_stage refuses to clear an owned dir —
# the "leftover from a crashed save" heuristic must not rmtree a dir a
# live background writer is mid-write into — and retention GC protects
# the tags being (re-)staged.  A real crash clears the registry with the
# process, so crashed leftovers are still reclaimed on the next save.
_ACTIVE_STAGES: Set[str] = set()
_ACTIVE_LOCK = threading.Lock()


class StageInFlightError(RuntimeError):
    """begin_stage was asked for a staging dir an in-flight save owns
    (the caller should drain the pending save first)."""


def stage_path(root: str, tag: str) -> str:
    return os.path.join(os.path.abspath(root), str(tag) + STAGING_SUFFIX)


def release_stage(root: str, tag: str) -> None:
    """Drop ownership of ``<tag>.tmp`` (idempotent; commit/abort call
    this, and an async writer's cleanup calls it after a simulated
    kill so the dead save's leftover behaves like a crash leftover)."""
    with _ACTIVE_LOCK:
        _ACTIVE_STAGES.discard(stage_path(root, tag))


def active_stage_tags(root: str) -> Set[str]:
    """Tags with an owned (in-flight) staging dir under ``root``."""
    root = os.path.abspath(root)
    with _ACTIVE_LOCK:
        owned = set(_ACTIVE_STAGES)
    out = set()
    for path in owned:
        if os.path.dirname(path) == root:
            name = os.path.basename(path)
            out.add(name[: -len(STAGING_SUFFIX)])
    return out


def begin_stage(root: str, tag: str) -> str:
    """Create a fresh staging dir for ``tag`` (clearing any leftover
    from a previous crashed/failed attempt) and take ownership of it.
    Raises :class:`StageInFlightError` if a live save already owns it."""
    path = stage_path(root, tag)
    with _ACTIVE_LOCK:
        if path in _ACTIVE_STAGES:
            raise StageInFlightError(
                f"staging dir {path} is owned by an in-flight save; drain it first"
            )
        _ACTIVE_STAGES.add(path)
    try:
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.makedirs(path)
    except BaseException:
        with _ACTIVE_LOCK:
            _ACTIVE_STAGES.discard(path)
        raise
    return path


def commit_tag(root: str, tag: str) -> str:
    """Atomically publish ``<tag>.tmp`` as ``<tag>``.  Re-saving an
    existing tag replaces it (the old tree is removed first; a kill in
    that window loses only the tag being overwritten, which the save was
    replacing anyway)."""
    root = os.path.abspath(root)
    staged, final = stage_path(root, tag), os.path.join(root, str(tag))
    faults.check("ckpt.commit", path=final)
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.rename(staged, final)
    atomic.fsync_dir(root)
    release_stage(root, tag)
    return final


def abort_stage(root: str, tag: str) -> None:
    path = stage_path(root, tag)
    if os.path.isdir(path):
        shutil.rmtree(path, ignore_errors=True)
    release_stage(root, tag)


def quarantine_tag(root: str, tag: str) -> str:
    """Rename a corrupt tag to ``<tag>.corrupt`` (suffixing a counter if
    a previous quarantine of the same tag exists) so it is never a load
    candidate again but stays on disk for inspection.  Tolerates a tag
    another process already quarantined (returns the existing dest)."""
    root = os.path.abspath(root)
    src = os.path.join(root, str(tag))
    dest = src + QUARANTINE_SUFFIX
    n = 1
    while os.path.exists(dest):
        dest = f"{src}{QUARANTINE_SUFFIX}{n}"
        n += 1
    try:
        os.rename(src, dest)
    except FileNotFoundError:
        # a concurrent quarantine (another rank) won the rename
        return src + QUARANTINE_SUFFIX
    atomic.fsync_dir(root)
    return dest


_TAG_MARKERS = (atomic.MANIFEST_FILE, "meta.json", "state")


def is_tag_dir(path: str) -> bool:
    """Positive signal that a directory is a checkpoint tag: it carries a
    manifest, a meta.json, or an orbax ``state`` tree.  Without this,
    retention GC and the fallback scan would treat ANY user directory
    under the checkpoint root (logs/, tensorboard/, ...) as a tag —
    deletable and restorable."""
    return any(os.path.exists(os.path.join(path, m)) for m in _TAG_MARKERS)


def committed_tags(root: str) -> List[str]:
    """Directories under ``root`` that look like committed tags (staging,
    quarantine and non-checkpoint dirs excluded)."""
    root = os.path.abspath(root)
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if name.endswith(STAGING_SUFFIX) or QUARANTINE_SUFFIX in name:
            continue
        path = os.path.join(root, name)
        if os.path.isdir(path) and is_tag_dir(path):
            out.append(name)
    return out


def tag_step(root: str, tag: str) -> Optional[int]:
    """A tag's global step: from ``meta.json`` when present, else parsed
    from a trailing integer in the tag name (``global_step120`` -> 120)."""
    import json

    meta_path = os.path.join(os.path.abspath(root), str(tag), "meta.json")
    try:
        with open(meta_path) as f:
            return int(json.load(f).get("global_step"))
    except (OSError, ValueError, TypeError, KeyError):
        pass
    m = _STEP_RE.search(str(tag))
    return int(m.group(1)) if m else None


def _sort_key(root: str, tag: str) -> Tuple[int, float]:
    step = tag_step(root, tag)
    try:
        mtime = os.path.getmtime(os.path.join(root, tag))
    except OSError:
        mtime = 0.0
    return (step if step is not None else -1, mtime)


def newest_first(root: str) -> List[str]:
    """Committed tags, newest first (by global step, mtime tie-break)."""
    tags = committed_tags(root)
    return sorted(tags, key=lambda t: _sort_key(root, t), reverse=True)


def verify_tag(root: str, tag: str) -> Tuple[bool, List[str]]:
    return atomic.verify_manifest(os.path.join(os.path.abspath(root), str(tag)))


def write_latest(root: str, tag: str) -> None:
    root = os.path.abspath(root)
    faults.check("ckpt.latest", path=os.path.join(root, LATEST_FILE))
    atomic.atomic_write_text(os.path.join(root, LATEST_FILE), str(tag))


def read_latest(root: str) -> Optional[str]:
    path = os.path.join(os.path.abspath(root), LATEST_FILE)
    try:
        with open(path) as f:
            return f.read().strip() or None
    except OSError:
        return None


def retention_gc(
    root: str,
    keep_last_n: int = 0,
    keep_every: int = 0,
    protect: Iterable[str] = (),
) -> List[str]:
    """Delete old committed tags.  ``keep_last_n <= 0`` keeps everything.
    ``keep_every > 0`` additionally pins any tag whose global step is a
    multiple of it (coarse long-horizon history under a tight window).
    Tags in ``protect`` (and the ``latest`` target) are never deleted;
    quarantined/staging dirs are never touched here — ``<tag>.tmp``
    dirs never count toward ``keep_last_n`` and a tag whose staging dir
    an in-flight async save owns is protected, so a background commit
    can never race the sweeper."""
    if keep_last_n <= 0:
        return []
    root = os.path.abspath(root)
    protected = set(str(t) for t in protect)
    protected |= active_stage_tags(root)
    latest = read_latest(root)
    if latest:
        protected.add(latest)
    deleted: List[str] = []
    # newest_first() excludes staging/quarantine names already; the
    # re-check here is deliberate belt-and-braces — a .tmp dir counted
    # toward keep_last_n would silently shrink the durable window
    candidates = [t for t in newest_first(root) if not t.endswith(STAGING_SUFFIX)]
    for i, tag in enumerate(candidates):
        if i < keep_last_n or tag in protected:
            continue
        step = tag_step(root, tag)
        if keep_every > 0 and step is not None and step % keep_every == 0:
            continue
        try:
            shutil.rmtree(os.path.join(root, tag))
            deleted.append(tag)
        except OSError as e:
            logger.warning(f"retention gc: could not delete tag '{tag}': {e}")
    return deleted
