"""Preemption watchdog: turn SIGTERM into a clean checkpoint-and-exit.

TPU schedulers (and most cluster managers) deliver SIGTERM with a grace
window before the hard kill.  The watchdog's signal handler only sets a
flag and a monotonic deadline — everything non-async-signal-safe
(logging, the emergency save, ``SystemExit``) happens at the next step
boundary, where the engine calls into :meth:`PreemptionWatchdog`.

Exit-code contract (see ``docs/resilience.md``):

* ``EXIT_PREEMPTED_SAVED`` (default 43) — preempted AND the emergency
  checkpoint committed; a scheduler can requeue-and-resume blindly.
* exit 1 — preempted but the save failed or the grace deadline had
  already passed; treat like a crash (resume from the previous tag).
"""
from __future__ import annotations

import os
import signal
import time
from typing import Callable, Dict, Optional, Tuple

EXIT_PREEMPTED_SAVED = 43

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionWatchdog:
    def __init__(
        self,
        grace_seconds: float = 60.0,
        exit_code: int = EXIT_PREEMPTED_SAVED,
        signals: Tuple[signal.Signals, ...] = _DEFAULT_SIGNALS,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.grace_seconds = float(grace_seconds)
        self.exit_code = int(exit_code)
        self.signals = tuple(signals)
        self._clock = clock
        self._old_handlers: Dict[int, object] = {}
        self._requested_at: Optional[float] = None
        self._signum: Optional[int] = None
        self.repeat_count = 0
        self._installed = False

    # -- signal plumbing --------------------------------------------------
    def _handle(self, signum, frame) -> None:
        # async-signal-safe: flags only; the engine acts at the next
        # step boundary
        if self._requested_at is None:
            self._requested_at = self._clock()
            self._signum = signum
            return
        # ESCALATION: a repeated signal means the step-boundary handler
        # is not coming (hung compile, deadlocked collective) or the
        # operator really wants out — restore the original disposition
        # and re-deliver, so a second Ctrl-C/SIGTERM behaves like the
        # watchdog was never installed
        self.repeat_count += 1
        old = self._old_handlers.get(signum, signal.SIG_DFL)
        signal.signal(signum, old)
        if callable(old):
            old(signum, frame)
        else:
            os.kill(os.getpid(), signum)

    def install(self) -> "PreemptionWatchdog":
        if not self._installed:
            for sig in self.signals:
                self._old_handlers[sig] = signal.signal(sig, self._handle)
            self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            for sig, old in self._old_handlers.items():
                signal.signal(sig, old)
            self._old_handlers.clear()
            self._installed = False

    __enter__ = install

    def __exit__(self, *exc_info) -> None:
        self.uninstall()

    # -- state ------------------------------------------------------------
    @property
    def preemption_requested(self) -> bool:
        return self._requested_at is not None

    @property
    def requested_at(self) -> Optional[float]:
        """Monotonic stamp of the (first) preemption signal — the anchor
        the serving drain budget counts down from (serving/watchdog.py)."""
        return self._requested_at

    @property
    def signal_name(self) -> str:
        if self._signum is None:
            return "none"
        try:
            return signal.Signals(self._signum).name
        except ValueError:
            return str(self._signum)

    def remaining(self) -> float:
        """Seconds left in the grace window (<= 0 once the deadline has
        passed; +inf when no preemption is pending)."""
        if self._requested_at is None:
            return float("inf")
        return (self._requested_at + self.grace_seconds) - self._clock()

    def reset(self) -> None:
        """Clear a pending request (after it has been handled)."""
        self._requested_at = None
        self._signum = None
        self.repeat_count = 0
