"""Durable file primitives: atomic writes and checkpoint manifests.

A checkpoint tag must either exist completely or not at all.  The two
building blocks here are:

* :func:`atomic_write_text` — the only sanctioned way to write small
  checkpoint metadata (``latest``, ``meta.json``, ``manifest.json``):
  write to ``<path>.tmp``, fsync, ``os.replace``, fsync the directory.
  A crash at any point leaves either the old file or the new file,
  never a torn one.  (ds_lint's ``non-atomic-checkpoint-write`` rule
  flags bare ``open(..., 'w')`` of these files elsewhere.)
* :func:`write_manifest` / :func:`verify_manifest` — a per-tag
  ``manifest.json`` recording every file's size and checksum, written
  LAST (so its presence certifies the tag is complete) and re-checked
  on load before any state is restored.
"""
from __future__ import annotations

import hashlib
import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.resilience import faults

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1
CHECKSUM_ALGORITHMS = ("sha256", "crc32", "none")
_CHUNK = 1 << 20


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename inside it is durable (no-op on
    platforms whose dirfd open fails, e.g. Windows)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
    """Write ``text`` to ``path`` atomically: tmp file + fsync +
    ``os.replace`` + directory fsync."""
    path = os.path.abspath(path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    faults.check("atomic.replace", path=path)
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))


def file_digest(path: str, algorithm: str = "sha256") -> str:
    """Streamed checksum of one file (``sha256``, ``crc32`` or ``none``)."""
    if algorithm == "none":
        return ""
    if algorithm == "crc32":
        crc = 0
        with open(path, "rb") as f:
            while chunk := f.read(_CHUNK):
                crc = zlib.crc32(chunk, crc)
        return f"{crc & 0xFFFFFFFF:08x}"
    if algorithm == "sha256":
        h = hashlib.sha256()
        with open(path, "rb") as f:
            while chunk := f.read(_CHUNK):
                h.update(chunk)
        return h.hexdigest()
    raise ValueError(f"unknown checksum algorithm {algorithm!r} (expected one of {CHECKSUM_ALGORITHMS})")


def _walk_files(root: str) -> List[str]:
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            out.append(rel.replace(os.sep, "/"))
    return sorted(out)


def write_manifest(tag_dir: str, algorithm: str = "sha256", extra: Optional[dict] = None) -> dict:
    """Record size + checksum for every file under ``tag_dir`` and write
    ``manifest.json`` (atomically) as the tag's completion marker."""
    tag_dir = os.path.abspath(tag_dir)
    files: Dict[str, dict] = {}
    for rel in _walk_files(tag_dir):
        if rel == MANIFEST_FILE or rel.endswith(".tmp"):
            continue
        full = os.path.join(tag_dir, rel)
        files[rel] = {"size": os.path.getsize(full), "digest": file_digest(full, algorithm)}
    manifest = {"version": MANIFEST_VERSION, "algorithm": algorithm, "files": files}
    if extra:
        manifest.update(extra)
    # fsync the data files before the manifest certifies them
    for rel in files:
        try:
            fd = os.open(os.path.join(tag_dir, rel), os.O_RDONLY)
        except OSError:
            continue
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    atomic_write_text(os.path.join(tag_dir, MANIFEST_FILE), json.dumps(manifest, indent=2))
    return manifest


def verify_manifest(tag_dir: str) -> Tuple[bool, List[str]]:
    """Check every manifest entry (existence, size, checksum).  Returns
    ``(ok, notes)``.  A tag with NO manifest is a legacy (pre-resilience)
    tag: accepted with a note rather than quarantined, so old checkpoint
    trees keep loading."""
    tag_dir = os.path.abspath(tag_dir)
    mpath = os.path.join(tag_dir, MANIFEST_FILE)
    if not os.path.exists(mpath):
        return True, ["no manifest (legacy tag); integrity not verified"]
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return False, [f"unreadable manifest: {e}"]
    algorithm = manifest.get("algorithm", "sha256")
    errors: List[str] = []
    for rel, entry in manifest.get("files", {}).items():
        full = os.path.join(tag_dir, rel)
        if not os.path.exists(full):
            errors.append(f"missing file '{rel}'")
            continue
        size = os.path.getsize(full)
        if size != entry.get("size"):
            errors.append(f"size mismatch '{rel}' ({size} != {entry.get('size')})")
            continue
        if algorithm != "none" and file_digest(full, algorithm) != entry.get("digest"):
            errors.append(f"checksum mismatch '{rel}'")
    return (not errors), errors
