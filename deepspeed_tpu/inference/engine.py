"""Inference engine.

TPU-native re-design of the reference ``InferenceEngine``
(``inference/engine.py:19``): builds the model-parallel mesh (:88), loads
checkpoints (:150), converts dtype (:175), applies the injection policy
(:135) and wraps forward (:204).  Differences, by design:

* **MP group → mesh axis.**  ``mp_size`` becomes the size of the
  ``model`` axis of a ``jax.sharding.Mesh``; weights are ``device_put``
  with Megatron-style PartitionSpecs and GSPMD inserts the collectives
  the reference's fused kernels issue manually.
* **Kernel injection → pytree transform.**  A policy
  (``inference/injection.py``) maps HF/Megatron weights into the stacked
  fused-block layout; the whole network then runs the KV-cache path in
  ``ops/transformer/inference.py`` — there is no module tree to mutate.
* **Checkpoint resize for free.**  The sharded checkpoint format reshards
  on load (orbax/tensorstore), subsuming ``MegatronSDLoader.merge/split``
  (``state_dict_factory.py:199``).
* ``generate()`` is a compiled prefill + ``lax.scan`` decode loop with a
  static-capacity KV cache (greedy, temperature, and top-k sampling).
"""
from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.analysis.shard import hooks as shard_hooks
from deepspeed_tpu.comm.mesh import MESH_AXES, MeshInfo
from deepspeed_tpu.config.config import MeshConfig
from deepspeed_tpu.utils.logging import log_dist, logger

# Host→device staging is chunked so the transient flat buffer never adds
# more than this many bytes of HBM on top of the parameters themselves
# (an XL-class model staged as ONE flat buffer peaks at ~2x its size).
_STAGE_CHUNK_BYTES = 256 << 20


def sample_logits(logits32, r, do_sample: bool, temperature: float, top_k: int):
    """The generation sampling head (STATIC params — compiled into each
    ``generate()`` signature).  ``logits32`` (..., V) float32; greedy
    when ``do_sample`` is False (note ``x / 1.0`` is bit-exact, so the
    default ``temperature=1.0`` greedy path equals a bare argmax)."""
    logits32 = logits32 / jnp.maximum(temperature, 1e-6)
    if not do_sample:
        return jnp.argmax(logits32, axis=-1).astype(jnp.int32)
    if top_k > 0:
        # k > V degenerates to no filtering; lax.top_k requires k <= V
        top_k = min(top_k, logits32.shape[-1])
        kth = jax.lax.top_k(logits32, top_k)[0][..., -1:]
        logits32 = jnp.where(logits32 < kth, -jnp.inf, logits32)
    return jax.random.categorical(r, logits32, axis=-1).astype(jnp.int32)


def sample_logits_pooled(logits32, keys, sample_flag, temperature, top_k, max_top_k: int):
    """:func:`sample_logits` for a slot pool: per-row TRACED sampling
    params (serving's per-request temperature/top-k/seed ride the fixed
    decode signature — one executable for any greedy/sampled mix).

    ``logits32`` (S, V); ``keys`` (S,) PRNG keys; ``sample_flag`` (S,)
    bool; ``temperature`` (S,) f32; ``top_k`` (S,) i32 (0 = no top-k
    filter).  Rows with ``sample_flag`` False take the bare argmax —
    bit-identical to ``sample_logits(do_sample=False, temperature=1.0)``,
    the serving ⇄ solo-``generate()`` greedy parity contract.  Traced
    per-row k thresholds against the STATIC top-``max_top_k`` head
    (``jax.lax.top_k`` needs a static k; requests with
    ``top_k > max_top_k`` are rejected at submit)."""
    greedy = jnp.argmax(logits32, axis=-1).astype(jnp.int32)
    lg = logits32 / jnp.maximum(temperature[:, None], 1e-6)
    # lax.top_k requires k <= V: a vocab narrower than max_top_k clamps
    # the static head (per-row k >= V then keeps every logit — the same
    # no-filter semantics, and greedy-only pools stay V-agnostic)
    head_k = min(max_top_k, logits32.shape[-1])
    head = jax.lax.top_k(lg, head_k)[0]  # (S, head_k), sorted desc
    kth = jnp.take_along_axis(
        head, jnp.clip(top_k - 1, 0, head_k - 1)[:, None], axis=-1
    )
    lg = jnp.where((top_k[:, None] > 0) & (lg < kth), -jnp.inf, lg)
    sampled = jax.vmap(jax.random.categorical)(keys, lg).astype(jnp.int32)
    return jnp.where(sample_flag, sampled, greedy)


@functools.partial(jax.jit, static_argnums=1)
def _split_flat(buf, shapes):
    """Split one flat staging buffer into per-leaf arrays on device.
    Module-level (static ``shapes``) so jit's in-process trace cache hits
    across engines.  No donation: XLA cannot alias one flat buffer into
    many reshaped outputs (it would just warn per call) — the HBM peak
    is bounded by _STAGE_CHUNK_BYTES chunking, not aliasing."""
    outs, off = [], 0
    for shp in shapes:
        # static `shapes` (static_argnums=1): host int math, not a sync
        n = int(np.prod(shp)) if shp else 1  # ds-lint: disable=host-sync-in-jit
        outs.append(jax.lax.dynamic_slice(buf, (off,), (n,)).reshape(shp))
        off += n
    return outs


class InferenceEngine:
    def __init__(
        self,
        model: Any = None,
        mp_size: int = 1,
        dtype: Any = None,
        checkpoint: Optional[str] = None,
        checkpoint_tag: Optional[str] = None,
        injection_policy: Optional[type] = None,
        replace_with_kernel_inject: bool = True,
        max_out_tokens: int = 1024,
        mesh=None,
        model_config: Any = None,
        params: Any = None,
        quantize_bits: int = 0,
        quantize_groups: int = 1,
        kv_cache_dtype: str = "model",
        seed: int = 0,
        init_on_device: bool = False,
        kernels: Any = None,
        **kwargs,
    ):
        """``model`` may be:

        * a HF/torch module or plain state dict — converted through an
          injection policy (``replace_with_kernel_inject`` path);
        * a preset name (``"gpt2"``, ``"bert-base"``, ...);
        * ``None`` with explicit ``model_config`` + ``params``.
        """
        self.mp_world_size = int(mp_size)
        self.dtype = dtype if dtype is not None else jnp.bfloat16
        self.max_out_tokens = int(max_out_tokens)
        if self.max_out_tokens < 1:
            raise ValueError(
                f"max_out_tokens must be >= 1 (it bounds prompt+generated "
                f"length and sizes the KV cache), got {self.max_out_tokens}"
            )
        # "model" -> cache in self.dtype; "int8" -> quantized cache (the
        # cache read rivals the weight read at long contexts; int8
        # halves that roofline term — see ops/transformer/inference)
        if kv_cache_dtype not in ("model", "int8"):
            raise ValueError(f"kv_cache_dtype must be 'model' or 'int8', got {kv_cache_dtype!r}")
        self.kv_cache_dtype = kv_cache_dtype
        self._kv_dtype = "int8" if kv_cache_dtype == "int8" else self.dtype
        self._compiled: Dict[Any, Callable] = {}

        # Pallas kernel suite (docs/kernels.md): `kernels` may be a
        # KernelsConfig, a raw `kernels` config dict, or None (keep the
        # process state — DS_KERNELS env still wins inside the dispatch)
        if kernels is not None:
            from deepspeed_tpu.config.config import KernelsConfig
            from deepspeed_tpu.ops import kernels as _kernels_mod

            if isinstance(kernels, dict):
                kernels = KernelsConfig.from_dict(kernels)
            _kernels_mod.configure_from_config(kernels)

        # -- resolve model family + params --------------------------------
        from deepspeed_tpu.models import bert as bert_mod
        from deepspeed_tpu.models import gpt2 as gpt2_mod

        if model is not None and isinstance(model, str):
            # GPT-2 presets win name collisions ("tiny"); use "bert-*"
            # names for the BERT family.
            if model in gpt2_mod.PRESETS:
                self.model_config = gpt2_mod.PRESETS[model]
            elif model in bert_mod.PRESETS or model.replace("bert-", "") in bert_mod.PRESETS:
                self.model_config = bert_mod.PRESETS.get(model) or bert_mod.PRESETS[model.replace("bert-", "")]
            else:
                raise ValueError(f"unknown model preset '{model}'")
        elif model is not None and (hasattr(model, "state_dict") or isinstance(model, dict)):
            if not replace_with_kernel_inject and injection_policy is None:
                raise ValueError("torch/state-dict models require kernel injection (replace_with_kernel_inject)")
            from deepspeed_tpu.inference.injection import replace_transformer_layer

            self.model_config, params = replace_transformer_layer(model, policy=injection_policy)
        elif model_config is not None:
            self.model_config = model_config
        else:
            raise ValueError("init_inference needs `model` (module/state_dict/preset) or model_config=")

        self._is_gpt = isinstance(self.model_config, gpt2_mod.GPT2Config)
        self._family = gpt2_mod if self._is_gpt else bert_mod
        # partition-rule engine: the family table every param layout
        # resolves through (sharding/rules.py; packed-int8 aware)
        from deepspeed_tpu.sharding.rules import rules_for_config, rules_for_family

        try:
            self._rules = rules_for_config(self.model_config)
        except ValueError:
            # duck-typed configs outside the built-in MROs keep working
            # (the same fallback as self._family above); the table is
            # only consulted when a layout actually needs resolving
            self._rules = rules_for_family("gpt2" if self._is_gpt else "bert")
        # disable remat for inference (no backward to save memory for)
        if getattr(self.model_config, "remat", False):
            self.model_config = dataclasses.replace(self.model_config, remat=False)

        # -- mesh ----------------------------------------------------------
        if mesh is None:
            from deepspeed_tpu.comm.mesh import make_mesh

            n_dev = len(jax.devices())
            if n_dev % self.mp_world_size:
                raise ValueError(f"mp_size={self.mp_world_size} does not divide {n_dev} devices")
            mesh = make_mesh(MeshConfig(model=self.mp_world_size, data=n_dev // self.mp_world_size, fsdp=1))
        self.mesh = mesh
        self.mesh_info = MeshInfo.from_mesh(mesh)

        # -- checkpoint / dtype / shard ------------------------------------
        if checkpoint is not None:
            # a random init would only serve as a shape template here, so
            # skip it — the restore target comes from checkpoint metadata
            params = self._load_checkpoint_params(checkpoint, checkpoint_tag, params)
        owns_params = False  # only engine-created trees may be donated
        if params is None:
            if init_on_device and getattr(self.model_config, "n_experts", 0) == 0:
                # generate the random init ON the chip: host generation +
                # upload of an XL-class model costs minutes over a
                # tunnel/PCIe link, on-chip generation costs seconds
                init_dev = gpt2_mod.init_params_device if self._is_gpt else bert_mod.init_params_device
                params = init_dev(self.model_config, seed=seed, dtype=self.dtype)
            else:
                init = gpt2_mod.init_params if self._is_gpt else bert_mod.init_params
                params = init(self.model_config, seed=seed)
            owns_params = True
        self._packed_int8 = False
        if quantize_bits:
            if quantize_bits == 8 and self._is_gpt:
                # true int8 serving: weights stay int8 in HBM and matmuls
                # run as (x @ q) * s in the fused decode path
                from deepspeed_tpu.runtime.weight_quantizer import pack_int8_tree

                params = pack_int8_tree(params, donate=owns_params, mesh=self.mesh)
                owns_params = True  # pack outputs are fresh arrays
                self._packed_int8 = True
            else:
                from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization

                params = WeightQuantization(bits=quantize_bits, groups=quantize_groups).quantize_dequantize_tree(params)
        self.params = self._shard_params(params, owned=owns_params)
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.params))
        log_dist(
            f"inference engine: {type(self.model_config).__name__} params={n_params/1e6:.1f}M "
            f"mp={self.mp_world_size} dtype={jnp.dtype(self.dtype).name}"
        )

    # ----------------------------------------------------------------------
    @property
    def module(self):
        """Reference parity: the 'injected model' is (config, params)."""
        return (self.model_config, self.params)

    @property
    def generation_capacity(self) -> int:
        """Hard bound on prompt + generated length: ``max_out_tokens``
        clamped by the model's positional table — the number every
        length check (generate, init_cache, serving admission) derives
        from."""
        if self._is_gpt:
            return min(self.max_out_tokens, self.model_config.n_positions)
        return self.max_out_tokens

    def _tp_spec(self, path: str, shape) -> P:
        if self.mp_world_size <= 1:
            return P()
        # partition-rule engine resolution: the family rule table
        # normalizes packed-int8 paths itself (.../<name>_w/q carries
        # the weight spec; .../<name>_w/s drops the contracted dim)
        spec = self._rules.spec(path, shape)
        return spec if spec is not None else P()

    def _shard_params(self, params, owned: bool = False):
        # int8 payloads must stay int8; scales stay f32.  Cast on HOST
        # (ml_dtypes handles bf16) so no full-precision staging copy
        # ever lands in HBM — device_put of fp32 then casting on-device
        # doubles transfer and OOMs XL-class models.
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        pstrs = ["/".join(str(getattr(k, "key", k)) for k in path) for path, _ in flat]
        def _target_dtype(pstr, leaf):
            if np.dtype(getattr(leaf, "dtype", np.float32)) == np.int8:
                return np.int8
            return np.float32 if pstr.endswith("/s") else self.dtype

        tgt_dtypes = [_target_dtype(pstr, leaf) for pstr, (_, leaf) in zip(pstrs, flat)]
        shardings = [
            NamedSharding(self.mesh, self._tp_spec(pstr, np.shape(leaf)))
            for pstr, (_, leaf) in zip(pstrs, flat)
        ]

        if all(isinstance(leaf, jax.Array) for _, leaf in flat):
            # params already device-resident (init_params_device /
            # pack_int8_tree on device): no host staging at all — one
            # jitted cast, resharded by out_shardings.  Donation only
            # when the engine created the tree — a CALLER-provided tree
            # must stay valid after init.
            dtypes = tuple(jnp.dtype(d) for d in tgt_dtypes)

            def cast_all(leaves):
                return [l.astype(d) for l, d in zip(leaves, dtypes)]

            placed = jax.jit(
                cast_all, donate_argnums=0 if owned else (), out_shardings=shardings
            )([leaf for _, leaf in flat])
            return jax.tree_util.tree_unflatten(treedef, list(placed))

        arrays = [np.asarray(leaf).astype(dt, copy=False) for (_, leaf), dt in zip(flat, tgt_dtypes)]
        if self.mp_world_size > 1:
            # TP: leaves carry different shardings — batched device_put
            placed = jax.device_put(arrays, shardings)
            return jax.tree_util.tree_unflatten(treedef, list(placed))
        # mp=1: every transfer pays a tunnel/PCIe round trip, and an
        # XL-class tree has ~600-1200 leaves (minutes of pure RTT).
        # Upload flat staging buffers (grouped by dtype, capped at
        # _STAGE_CHUNK_BYTES so peak HBM overhead stays bounded) and
        # split on device (_split_flat deliberately does NOT donate the
        # staging buffer — peak HBM is bounded by the chunk cap instead;
        # see its docstring).
        placed = [None] * len(arrays)
        by_dtype = {}
        for i, a in enumerate(arrays):
            by_dtype.setdefault(a.dtype, []).append(i)
        rep = NamedSharding(self.mesh, P())
        for dt, idxs in by_dtype.items():
            chunk, chunk_bytes = [], 0
            chunks = [chunk]
            for i in idxs:
                chunk.append(i)
                chunk_bytes += arrays[i].nbytes
                if chunk_bytes >= _STAGE_CHUNK_BYTES:
                    chunk, chunk_bytes = [], 0
                    chunks.append(chunk)
            for idx_chunk in chunks:
                if not idx_chunk:
                    continue
                buf = np.concatenate([arrays[i].reshape(-1) for i in idx_chunk])
                dev = jax.device_put(buf, rep)
                shapes = tuple(arrays[i].shape for i in idx_chunk)
                for i, part in zip(idx_chunk, _split_flat(dev, shapes)):
                    placed[i] = part
        return jax.tree_util.tree_unflatten(treedef, placed)

    def _load_checkpoint_params(self, checkpoint: str, tag: Optional[str], params):
        """Load params from a training checkpoint dir (orbax sharded
        format written by runtime/checkpointing.py); MP/DP layout of the
        writer is irrelevant — tensorstore reshards on read (the
        ``MegatronSDLoader`` merge/split analog)."""
        import orbax.checkpoint as ocp

        from deepspeed_tpu.runtime.checkpointing import LATEST_FILE

        checkpoint = os.path.abspath(checkpoint)
        state_dir = checkpoint
        if not os.path.isdir(os.path.join(state_dir, "state")):
            if tag is None:
                latest = os.path.join(checkpoint, LATEST_FILE)
                if not os.path.exists(latest):
                    raise FileNotFoundError(f"no '{LATEST_FILE}' in {checkpoint}")
                with open(latest) as f:
                    tag = f.read().strip()
            state_dir = os.path.join(checkpoint, str(tag))
        ckptr = ocp.PyTreeCheckpointer()
        state_path = os.path.join(state_dir, "state")
        if params is not None:
            target = {"params": jax.tree.map(lambda x: np.zeros(np.shape(x), np.float32), params)}
        else:
            # no template → build the restore target for the params
            # subtree from on-disk metadata (avoids materializing a full
            # random init just for its shapes)
            meta = ckptr.metadata(state_path)
            meta_params = (meta["params"] if isinstance(meta, dict) else meta.item_metadata.tree["params"])
            target = {
                "params": jax.tree.map(
                    lambda m: np.zeros(m.shape, np.float32), meta_params,
                    is_leaf=lambda m: hasattr(m, "shape"),
                )
            }
        try:
            restored = ckptr.restore(
                state_path, args=ocp.args.PyTreeRestore(item=target, partial_restore=True)
            )
        except TypeError:
            # older orbax has no partial_restore kwarg: read the whole
            # tree (host arrays, disk shapes) and keep the params subtree
            restored = ckptr.restore(state_path)
            restored = {
                "params": jax.tree.map(
                    lambda t, v: np.asarray(v, t.dtype), target["params"],
                    restored["params"],
                )
            }
        log_dist(f"inference: loaded params from {state_dir}")
        return restored["params"]

    # ----------------------------------------------------------------------
    # forward
    # ----------------------------------------------------------------------
    def _scoped(self, fn):
        """This engine's mesh becomes ambient for the trace (see
        parallel.sequence.scoped_to)."""
        from deepspeed_tpu.parallel.sequence import scoped_to

        return scoped_to(self.mesh, fn)

    def forward(self, input_ids, **kw):
        """Full-sequence forward: GPT → logits (B,T,V); BERT → encoder
        hidden states (BERT accepts token_type_ids/attention_mask
        kwargs)."""
        if self._is_gpt and kw:
            raise TypeError(
                f"forward() got unexpected kwargs {sorted(kw)} for a GPT-family "
                "model (token_type_ids/attention_mask are BERT-only)"
            )
        input_ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
        if self._is_gpt and input_ids.shape[1] > self.model_config.n_positions:
            # past n_positions the position lookup would clamp and return
            # garbage logits — raise with the derived numbers instead
            raise ValueError(
                f"forward() sequence length {input_ids.shape[1]} exceeds the "
                f"model's n_positions={self.model_config.n_positions}"
            )
        key = ("fwd", input_ids.shape, tuple(sorted(kw)))
        if key not in self._compiled:
            cfg = self.model_config
            if self._is_gpt and self._packed_int8:
                # packed weights are only understood by the fused
                # inference blocks — run the full sequence through the
                # cache path (pos=0 prefill over the whole input)
                from deepspeed_tpu.ops.transformer.inference import (
                    DeepSpeedInferenceConfig,
                    forward_with_cache,
                    init_kv_cache,
                )

                B, T = input_ids.shape
                icfg = DeepSpeedInferenceConfig(
                    hidden_size=cfg.n_embd, heads=cfg.n_head,
                    layer_norm_eps=cfg.layer_norm_epsilon, dtype=self.dtype,
                    max_out_tokens=T, use_flash_attention=cfg.use_flash_attention,
                )

                def fn(p, ids):
                    k0, v0 = init_kv_cache(cfg.n_layer, B, cfg.n_head, T, cfg.head_dim, self._kv_dtype)
                    return forward_with_cache(p, ids, k0, v0, 0, icfg)[0]

            elif self._is_gpt:
                fn = lambda p, ids: self._family.apply(p, ids, cfg, deterministic=True)
            else:
                fn = lambda p, ids, **k: self._family.encode(p, ids, cfg, deterministic=True, **k)
            self._compiled[key] = jax.jit(self._scoped(fn))
        return self._compiled[key](self.params, input_ids, **{k: jnp.asarray(v) for k, v in kw.items()})

    __call__ = forward

    # ----------------------------------------------------------------------
    # external-cache prefill/decode surface (the serving/ subsystem and
    # custom decode loops build on this instead of the closed generate())
    # ----------------------------------------------------------------------
    def inference_config(self, max_len: int):
        """The fused-block config for a cache of capacity ``max_len``."""
        from deepspeed_tpu.ops.transformer.inference import DeepSpeedInferenceConfig

        cfg = self.model_config
        return DeepSpeedInferenceConfig(
            hidden_size=cfg.n_embd,
            heads=cfg.n_head,
            layer_norm_eps=cfg.layer_norm_epsilon,
            mp_size=self.mp_world_size,
            dtype=self.dtype,
            max_out_tokens=int(max_len),
            use_flash_attention=cfg.use_flash_attention,
            moe_top_k=getattr(cfg, "moe_top_k", 2),
        )

    def init_cache(self, batch: int, max_len: int):
        """Externally-owned KV cache ``(layers, batch, heads, max_len,
        head_dim)`` in the engine's cache dtype (bf16/f32 or the int8
        code+scale pair).  ``max_len`` is validated against
        :attr:`generation_capacity` so a cache that silently wraps past
        ``max_out_tokens`` cannot be built."""
        from deepspeed_tpu.ops.transformer.inference import init_kv_cache

        if not self._is_gpt:
            raise ValueError("init_cache() requires a causal-LM (GPT-family) model")
        if max_len > self.generation_capacity:
            raise ValueError(
                f"cache max_len={max_len} exceeds the generation capacity "
                f"min(max_out_tokens={self.max_out_tokens}, "
                f"n_positions={self.model_config.n_positions}) = "
                f"{self.generation_capacity}"
            )
        cfg = self.model_config
        return init_kv_cache(cfg.n_layer, int(batch), cfg.n_head, int(max_len), cfg.head_dim, self._kv_dtype)

    def _cache_step_fn(self, T: int, max_len: int, static_prefill: bool, per_slot: bool):
        """Compiled ``forward_with_cache`` wrapper, cached per (token
        shape, cache capacity, pos form) — the caller owns the cache."""
        key = ("cstep", T, max_len, static_prefill, per_slot)
        if key not in self._compiled:
            from deepspeed_tpu.ops.transformer.inference import forward_with_cache

            icfg = self.inference_config(max_len)

            if static_prefill:
                fn = lambda p, t, k, v: forward_with_cache(p, t, k, v, 0, icfg)
            else:
                fn = lambda p, t, k, v, pos: forward_with_cache(p, t, k, v, pos, icfg)
            self._compiled[key] = jax.jit(self._scoped(fn))
        return self._compiled[key]

    def prefill(self, tokens, k_cache, v_cache):
        """Initial prefill (write offset 0, causal fast path) into an
        externally-owned cache.  Returns ``(logits, k_cache, v_cache)``."""
        tokens = jnp.asarray(np.asarray(tokens), jnp.int32)
        B, T = tokens.shape
        S = jax.tree.leaves(k_cache)[0].shape[3]
        if T > S:
            raise ValueError(f"prefill length {T} exceeds the cache capacity {S}")
        fn = self._cache_step_fn(T, S, static_prefill=True, per_slot=False)
        return fn(self.params, tokens, k_cache, v_cache)

    def decode_step(self, tokens, k_cache, v_cache, pos):
        """One decode/continuation step at write offset ``pos`` (scalar,
        or a per-row (B,) vector for slot-pool continuous batching).
        ``pos`` is traced — every position reuses one executable.
        Returns ``(logits, k_cache, v_cache)``."""
        tokens = jnp.asarray(np.asarray(tokens), jnp.int32)
        B, T = tokens.shape
        S = jax.tree.leaves(k_cache)[0].shape[3]
        # pos is concrete host-side here: bound it BEFORE tracing — past
        # capacity the cache write would clamp and silently overwrite the
        # last position forever (the wrap the max_out_tokens satellite
        # exists to forbid)
        pos_host = np.asarray(pos)
        if int(pos_host.max()) + T > S:
            raise ValueError(
                f"decode_step write offset pos={int(pos_host.max())} + T={T} "
                f"exceeds the cache capacity {S}; the sequence is out of "
                f"room (grow the cache via init_cache, or stop generating)"
            )
        pos = jnp.asarray(pos, jnp.int32)
        fn = self._cache_step_fn(T, S, static_prefill=False, per_slot=pos.ndim == 1)
        return fn(self.params, tokens, k_cache, v_cache, pos)

    # ----------------------------------------------------------------------
    # generation (GPT family)
    # ----------------------------------------------------------------------
    def _build_generate(self, B: int, T: int, N: int, do_sample: bool, temperature: float, top_k: int, eos_token_id, masked: bool = False):
        from deepspeed_tpu.ops.transformer.inference import (
            forward_with_cache,
            init_kv_cache,
        )

        cfg = self.model_config
        # Static cache capacity: T+N, rounded up to the flash-decode
        # kernel's 128-row grid when the suite is armed (docs/kernels.md)
        # — the padded tail sits beyond every query position (pos < T+N)
        # so it is never attendable; without alignment the token loop
        # would silently fall back to the lax path for most (T, N).
        from deepspeed_tpu.ops import kernels as _kernels_mod

        S = T + N
        if _kernels_mod.flash_decode_armed():
            S = -(-S // 128) * 128
        icfg = self.inference_config(S)
        eos = -1 if eos_token_id is None else int(eos_token_id)

        def sample_token(logits32, r):
            return sample_logits(
                logits32, r, do_sample=do_sample, temperature=temperature, top_k=top_k
            )

        def gen(params, tokens, rng, attention_mask):
            k_cache, v_cache = init_kv_cache(cfg.n_layer, B, cfg.n_head, S, cfg.head_dim, self._kv_dtype)
            if masked:
                # left-padded prompts: real positions start at 0 per
                # example; padded cache slots are never attendable
                # (incl. the kernel-alignment tail beyond T+N)
                prompt_mask = attention_mask.astype(bool)  # (B, T)
                position_ids = jnp.maximum(jnp.cumsum(prompt_mask.astype(jnp.int32), axis=1) - 1, 0)
                real_len = jnp.sum(prompt_mask.astype(jnp.int32), axis=1)  # (B,)
                full_mask = jnp.concatenate(
                    [prompt_mask, jnp.ones((B, N), bool),
                     jnp.zeros((B, S - T - N), bool)], axis=1)
                logits, k_cache, v_cache = forward_with_cache(
                    params, tokens, k_cache, v_cache, 0, icfg,
                    key_padding_mask=full_mask, position_ids=position_ids,
                )
            else:
                real_len = jnp.full((B,), T, jnp.int32)
                full_mask = None
                logits, k_cache, v_cache = forward_with_cache(params, tokens, k_cache, v_cache, 0, icfg)
            r0, rng = jax.random.split(rng)
            first = sample_token(logits[:, -1].astype(jnp.float32), r0)
            finished = first == eos

            # prefill ran with the STACKED cache (layer scan amortizes);
            # the token loop carries PER-LAYER cache tuples instead —
            # each unrolled layer then owns its buffer and the stacked
            # cache's per-token slice/reassembly copies (profiled at
            # ~7ms/token at XL) disappear
            n_layer = jax.tree.leaves(k_cache)[0].shape[0]

            def _split_layers(c):
                if isinstance(c, dict):
                    return tuple({k: v[i] for k, v in c.items()} for i in range(n_layer))
                return tuple(c[i] for i in range(n_layer))

            k_tup = _split_layers(k_cache)
            v_tup = _split_layers(v_cache)

            def body(carry, xs):
                tok, kc, vc, pos, fin = carry
                r, step = xs
                # the token fed at scan step s was generated at step s-1,
                # so its logical position is real_len + (s-1)
                pos_ids = (real_len + step - 1)[:, None] if masked else None
                lg, kc, vc = forward_with_cache(
                    params, tok[:, None], kc, vc, pos, icfg,
                    key_padding_mask=full_mask, position_ids=pos_ids,
                )
                nxt = sample_token(lg[:, -1].astype(jnp.float32), r)
                nxt = jnp.where(fin, eos if eos >= 0 else 0, nxt)
                fin = fin | (nxt == eos)
                return (nxt, kc, vc, pos + 1, fin), nxt

            (_, _, _, _, _), rest = jax.lax.scan(
                body,
                (first, k_tup, v_tup, jnp.int32(T), finished),
                (jax.random.split(rng, N - 1), jnp.arange(1, N, dtype=jnp.int32)),
            )
            return jnp.concatenate([tokens, first[:, None], rest.T], axis=1)

        return jax.jit(self._scoped(gen))

    def generate(
        self,
        input_ids,
        max_new_tokens: int = 32,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        eos_token_id: Optional[int] = None,
        seed: int = 0,
        attention_mask=None,
    ):
        """Autoregressive generation (KV-cache decode).  ``input_ids``
        (B, T); ragged prompts are LEFT-padded with ``attention_mask``
        (B, T, 1=real) — positions and attention then follow each
        example's real length (HF convention).  Returns
        (B, T + max_new_tokens)."""
        if not self._is_gpt:
            raise ValueError("generate() requires a causal-LM (GPT-family) model")
        input_ids = jnp.asarray(np.asarray(input_ids), jnp.int32)
        B, T = input_ids.shape
        if T + max_new_tokens > self.generation_capacity:
            raise ValueError(
                f"T+max_new_tokens = {T}+{max_new_tokens} = {T + max_new_tokens} "
                f"exceeds the generation capacity "
                f"min(max_out_tokens={self.max_out_tokens}, "
                f"n_positions={self.model_config.n_positions}) = "
                f"{self.generation_capacity} (raise max_out_tokens in "
                f"init_inference, or shorten the prompt)"
            )
        masked = attention_mask is not None
        if masked:
            am_np = np.asarray(attention_mask)
            if not np.array_equal(np.sort(am_np, axis=1), am_np):
                raise ValueError(
                    "attention_mask must be LEFT-padded (rows of 0s then 1s); "
                    "right-padded prompts would silently generate from a pad position"
                )
            if np.all(am_np == 1):
                masked = False  # all-real prompts: take the unmasked fast path
            attention_mask = jnp.asarray(am_np, jnp.int32)
        else:
            attention_mask = jnp.ones((B, T), jnp.int32)
        key = ("gen", B, T, max_new_tokens, do_sample, float(temperature), int(top_k), eos_token_id, masked)
        if key not in self._compiled:
            self._compiled[key] = self._build_generate(
                B, T, max_new_tokens, do_sample, temperature, top_k, eos_token_id, masked=masked
            )
            # ds_shard Pass 1/2 feed (no-op unless the audit armed it)
            if shard_hooks.armed():
                shard_hooks.note_jit(
                    self, "inference.generate", self._compiled[key],
                    (self.params, input_ids, jax.random.PRNGKey(seed), attention_mask),
                    leaves=shard_hooks.live_param_leaves(self.params),
                )
        # telemetry (docs/telemetry.md): closed-generate calls count
        # tokens dispatched; no fence is added — the span measures the
        # host call window, the caller owns the sync
        from deepspeed_tpu.telemetry import get_registry, get_tracer

        reg, tracer = get_registry(), get_tracer()
        if reg.enabled:
            reg.counter("inference/generate_calls", engine="inference").inc()
            reg.counter("inference/tokens_requested", engine="inference").inc(B * max_new_tokens)
        with tracer.span("generate", "inference",
                         args={"batch": B, "prompt_len": T,
                               "max_new_tokens": max_new_tokens}):
            return self._compiled[key](self.params, input_ids, jax.random.PRNGKey(seed), attention_mask)
