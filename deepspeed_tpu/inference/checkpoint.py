"""Checkpoint loaders for external (torch) model checkpoints.

Reference: ``runtime/state_dict_factory.py`` — ``SDLoaderFactory`` (:17)
and ``MegatronSDLoader`` (:199): load Megatron-LM tensor-parallel
checkpoint shards (``mp_rank_XX_model_states.pt``) and merge/split them
to the serving MP degree before kernel injection.

TPU-native difference: only the **merge to a full state dict** is needed
— once merged and converted (``inference/injection.py``), the serving
TP degree is just PartitionSpecs and GSPMD slices the weights on
``device_put`` (the reference's ``split`` path is obsolete here).

Merge rules per Megatron weight role (torch Linear is (out, in)):
* column-parallel (``query_key_value``, ``dense_h_to_4h``) — concat
  along dim 0 (each rank owns a slice of the output dim; for QKV this
  reproduces the per-head-interleaved full layout the injection policy
  expects);
* row-parallel (``attention.dense``, ``dense_4h_to_h``) — concat dim 1;
* vocab-parallel ``word_embeddings`` — concat dim 0;
* replicated (layernorms, position embeddings, biases of row-parallel
  layers) — take rank 0.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

COLUMN_PARALLEL_PATTERNS = ("query_key_value.weight", "query_key_value.bias", "dense_h_to_4h.weight", "dense_h_to_4h.bias")
ROW_PARALLEL_PATTERNS = ("attention.dense.weight", "mlp.dense_4h_to_h.weight")
VOCAB_PARALLEL_PATTERNS = ("word_embeddings.weight",)


def _to_numpy(t) -> np.ndarray:
    detach = getattr(t, "detach", None)
    if detach is not None:
        return detach().cpu().numpy()
    return np.asarray(t)


class SDLoaderFactory:
    @staticmethod
    def get_sd_loader_json(json_file) -> "MegatronSDLoader":
        """Reference :17 — json holds {"type", "checkpoints": [...],
        "version"}; also accepts an already-parsed dict."""
        data = json_file
        if isinstance(json_file, str):
            with open(json_file) as f:
                data = json.load(f)
        sd_type = data.get("type", "Megatron")
        ckpt_list = data["checkpoints"]
        version = data.get("version")
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type, version)

    @staticmethod
    def get_sd_loader(ckpt_list: List[str], sd_type: str = "Megatron", version=None) -> "MegatronSDLoader":
        if sd_type.lower() != "megatron":
            raise ValueError(f"unsupported checkpoint type '{sd_type}' (Megatron only)")
        return MegatronSDLoader(ckpt_list, version=version)


class MegatronSDLoader:
    """Loads and merges Megatron TP shards into one full state dict."""

    def __init__(self, ckpt_list: List[str], version=None):
        if not ckpt_list:
            raise ValueError("empty checkpoint list")
        self.ckpt_list = list(ckpt_list)
        self.version = version

    def _load_one(self, path: str) -> Dict[str, np.ndarray]:
        import torch

        sd = torch.load(path, map_location="cpu", weights_only=False)
        # Megatron checkpoints nest the model under 'model' or 'module'
        for key in ("model", "module"):
            if isinstance(sd, dict) and key in sd and isinstance(sd[key], dict):
                sd = sd[key]
        return {k: _to_numpy(v) for k, v in sd.items() if hasattr(v, "shape") or hasattr(v, "detach")}

    @staticmethod
    def _merge_qkv(parts: List[np.ndarray], version, num_heads: Optional[int]) -> np.ndarray:
        """Fused QKV shards.  Modern Megatron (version > 1.0 / unknown)
        stores each rank's slice per-head interleaved — plain axis-0
        concat reproduces the full interleaved layout.  version <= 1.0
        checkpoints store each rank's slice as contiguous [q|k|v]; those
        must be re-interleaved per head (reference
        ``MegatronSDLoader.merge_query_key_value`` branches the same
        way), which needs the head count."""
        if version is None or float(version) > 1.0:
            return np.concatenate(parts, axis=0)
        if num_heads is None:
            raise ValueError(
                "Megatron checkpoint version <= 1.0 stores QKV as contiguous [q|k|v]; "
                "pass num_heads= to load() so shards can be re-interleaved"
            )
        tp = len(parts)
        heads_per_rank = num_heads // tp
        out = []
        for part in parts:
            three_hd = part.shape[0]
            hd = three_hd // (3 * heads_per_rank)
            rest = part.shape[1:]
            # [q|k|v] (3, heads_r, hd, ...) -> per-head (heads_r, 3, hd, ...)
            out.append(
                part.reshape((3, heads_per_rank, hd) + rest).transpose(1, 0, 2, *range(3, 3 + len(rest))).reshape((three_hd,) + rest)
            )
        return np.concatenate(out, axis=0)

    @classmethod
    def merge_state_dicts(
        cls, shards: List[Dict[str, np.ndarray]], version=None, num_heads: Optional[int] = None
    ) -> Dict[str, np.ndarray]:
        if len(shards) == 1:
            return dict(shards[0])
        merged: Dict[str, np.ndarray] = {}
        for key in shards[0]:
            parts = [s[key] for s in shards]
            if key.endswith("query_key_value.weight") or key.endswith("query_key_value.bias"):
                merged[key] = cls._merge_qkv(parts, version, num_heads)
            elif any(key.endswith(p) for p in COLUMN_PARALLEL_PATTERNS):
                merged[key] = np.concatenate(parts, axis=0)
            elif any(key.endswith(p) for p in ROW_PARALLEL_PATTERNS):
                merged[key] = np.concatenate(parts, axis=1)
            elif any(key.endswith(p) for p in VOCAB_PARALLEL_PATTERNS):
                merged[key] = np.concatenate(parts, axis=0)
            else:
                merged[key] = parts[0]  # replicated
        return merged

    def load(self, mp_world_size: int = 1, mp_rank: int = 0, num_heads: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Returns the FULL merged state dict (serving-side slicing is
        GSPMD's job); ``mp_world_size``/``mp_rank`` kept for reference
        API parity — resharding no longer happens here.  ``ckpt_list``
        order IS the TP rank order (no re-sorting: lexicographic order
        breaks for unpadded rank numbers)."""
        shards = [self._load_one(p) for p in self.ckpt_list]
        logger.info(f"MegatronSDLoader: merged {len(shards)} TP shard(s)")
        return self.merge_state_dicts(shards, version=self.version, num_heads=num_heads)


def find_megatron_checkpoints(ckpt_dir: str, tag: Optional[str] = None) -> List[str]:
    """Locate ``mp_rank_XX_model_states.pt`` files under a checkpoint dir
    (reference naming, engine.py:1624)."""
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
    search = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
    out = []
    for name in sorted(os.listdir(search)):
        if name.startswith("mp_rank_") and name.endswith(".pt"):
            out.append(os.path.join(search, name))
    return out
