"""Kernel injection — HF/Megatron model → TPU-native fused inference.

The reference swaps ``nn.Module`` children for fused CUDA modules at
runtime (``module_inject/replace_module.py:89`` ``replace_transformer_layer``,
policies in ``module_inject/replace_policy.py``: ``HFBertLayerPolicy`` :43,
``HFGPT2LayerPolicy`` :195, ``HFGPTNEOLayerPolicy`` :102,
``MegatronLayerPolicy`` :146).  In a functional JAX world the analog is a
**pytree transform**: a policy maps the source model's weights into this
framework's stacked-block parameter layout, after which the whole network
runs through the fused inference path (``ops/transformer/inference.py``).

Tensor-parallel slicing (reference ``ReplaceWithTensorSlicing``,
``replace_module.py:11-88``, ``qkv_copy`` :24) becomes PartitionSpecs over
the ``model`` mesh axis — GSPMD does the physical slicing when params are
``device_put`` with those shardings, so the "copy loop" disappears.

Policies accept either a live ``torch.nn.Module`` (transformers model) or
a plain ``{name: ndarray}`` state dict plus a config object.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _to_numpy(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    # torch tensor (cpu or otherwise) without importing torch eagerly
    detach = getattr(t, "detach", None)
    if detach is not None:
        return detach().cpu().numpy()
    return np.asarray(t)


def _state_dict_of(model) -> Dict[str, np.ndarray]:
    if isinstance(model, dict):
        return {k: _to_numpy(v) for k, v in model.items()}
    sd = model.state_dict()
    return {k: _to_numpy(v) for k, v in sd.items()}


def _stack(sd: Dict[str, np.ndarray], fmt: str, n_layer: int, transpose: bool = False) -> np.ndarray:
    mats = [sd[fmt.format(i)] for i in range(n_layer)]
    if transpose:
        mats = [m.T for m in mats]
    return np.ascontiguousarray(np.stack(mats).astype(np.float32))


class DSPolicy:
    """Base policy: subclasses declare how to read one architecture.

    ``convert(model)`` returns ``(model_config, params)`` where ``params``
    is the stacked GPT-2/BERT-layout pytree used by models/ and
    ops/transformer/inference.py.
    """

    architectures: Tuple[str, ...] = ()

    @classmethod
    def matches(cls, model) -> bool:
        cfg = getattr(model, "config", None)
        archs = tuple(getattr(cfg, "architectures", None) or ()) if cfg is not None else ()
        name = type(model).__name__
        return any(a in cls.architectures for a in archs) or name in cls.architectures


class HFGPT2LayerPolicy(DSPolicy):
    """transformers GPT-2 (reference ``replace_policy.py:195``).

    HF GPT-2 uses Conv1D (weights already (in, out)) so no transpose; the
    fused c_attn is the same q|k|v concat our blocks use.
    """

    # (GPT2ForSequenceClassification is deliberately absent: its score
    # head has no analog in the fused LM layout)
    architectures = ("GPT2LMHeadModel", "GPT2Model")

    @classmethod
    def convert(cls, model, hf_config=None):
        from deepspeed_tpu.models.gpt2 import GPT2Config

        sd = _state_dict_of(model)
        hf = hf_config if hf_config is not None else model.config
        # tolerate both GPT2Model ("h.0...") and GPT2LMHeadModel ("transformer.h.0...")
        prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        n_layer = hf.n_layer
        cfg = GPT2Config(
            vocab_size=hf.vocab_size,
            n_positions=hf.n_positions,
            n_embd=hf.n_embd,
            n_layer=n_layer,
            n_head=hf.n_head,
            layer_norm_epsilon=hf.layer_norm_epsilon,
            remat=False,
        )
        p = prefix

        def stacked(key, transpose=False):
            return _stack(sd, p + "h.{}." + key, n_layer, transpose=transpose)

        params = {
            "wte": sd[p + "wte.weight"].astype(np.float32),
            "wpe": sd[p + "wpe.weight"].astype(np.float32),
            "blocks": {
                "ln1_g": stacked("ln_1.weight"),
                "ln1_b": stacked("ln_1.bias"),
                "qkv_w": stacked("attn.c_attn.weight"),
                "qkv_b": stacked("attn.c_attn.bias"),
                "proj_w": stacked("attn.c_proj.weight"),
                "proj_b": stacked("attn.c_proj.bias"),
                "ln2_g": stacked("ln_2.weight"),
                "ln2_b": stacked("ln_2.bias"),
                "fc_w": stacked("mlp.c_fc.weight"),
                "fc_b": stacked("mlp.c_fc.bias"),
                "fc_proj_w": stacked("mlp.c_proj.weight"),
                "fc_proj_b": stacked("mlp.c_proj.bias"),
            },
            "lnf_g": sd[p + "ln_f.weight"].astype(np.float32),
            "lnf_b": sd[p + "ln_f.bias"].astype(np.float32),
        }
        return cfg, params


class HFGPTNEOLayerPolicy(DSPolicy):
    """transformers GPT-Neo (reference ``replace_policy.py:102``).

    GPT-Neo uses separate (out, in) Linear q/k/v without biases for q/k/v
    weights' layout, so weights are transposed and q|k|v concatenated.
    Local-attention layers attend over a window; this policy maps them to
    full attention (valid superset for short sequences — documented
    deviation, window masking lands with the sparse-attention kernels).
    """

    architectures = ("GPTNeoForCausalLM", "GPTNeoModel")

    @classmethod
    def convert(cls, model, hf_config=None):
        from deepspeed_tpu.models.gpt2 import GPT2Config

        sd = _state_dict_of(model)
        hf = hf_config if hf_config is not None else model.config
        prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
        n_layer = hf.num_layers
        d = hf.hidden_size
        cfg = GPT2Config(
            vocab_size=hf.vocab_size,
            n_positions=hf.max_position_embeddings,
            n_embd=d,
            n_layer=n_layer,
            n_head=hf.num_heads,
            layer_norm_epsilon=hf.layer_norm_epsilon,
            remat=False,
        )
        p = prefix
        # HF GPT-Neo applies NO 1/sqrt(head_dim) attention scaling; our
        # attention paths always scale, so fold sqrt(head_dim) into the
        # query projection to cancel it.
        q_scale = float(np.sqrt(d // hf.num_heads))
        qkv_w, qkv_b, blocks = [], [], {}
        for i in range(n_layer):
            base = f"{p}h.{i}.attn.attention."
            parts_w = [sd[base + f"{n}_proj.weight"].T for n in ("q", "k", "v")]
            parts_w[0] = parts_w[0] * q_scale
            qkv_w.append(np.concatenate(parts_w, axis=1))
            parts_b = [
                np.asarray(sd.get(base + f"{n}_proj.bias", np.zeros(d, np.float32)), np.float32)
                for n in ("q", "k", "v")
            ]
            parts_b[0] = parts_b[0] * q_scale
            qkv_b.append(np.concatenate(parts_b))
        blocks["qkv_w"] = np.stack(qkv_w).astype(np.float32)
        blocks["qkv_b"] = np.stack(qkv_b).astype(np.float32)
        blocks["ln1_g"] = _stack(sd, p + "h.{}.ln_1.weight", n_layer)
        blocks["ln1_b"] = _stack(sd, p + "h.{}.ln_1.bias", n_layer)
        blocks["proj_w"] = _stack(sd, p + "h.{}.attn.attention.out_proj.weight", n_layer, transpose=True)
        blocks["proj_b"] = _stack(sd, p + "h.{}.attn.attention.out_proj.bias", n_layer)
        blocks["ln2_g"] = _stack(sd, p + "h.{}.ln_2.weight", n_layer)
        blocks["ln2_b"] = _stack(sd, p + "h.{}.ln_2.bias", n_layer)
        blocks["fc_w"] = _stack(sd, p + "h.{}.mlp.c_fc.weight", n_layer, transpose=True)
        blocks["fc_b"] = _stack(sd, p + "h.{}.mlp.c_fc.bias", n_layer)
        blocks["fc_proj_w"] = _stack(sd, p + "h.{}.mlp.c_proj.weight", n_layer, transpose=True)
        blocks["fc_proj_b"] = _stack(sd, p + "h.{}.mlp.c_proj.bias", n_layer)
        params = {
            "wte": sd[p + "wte.weight"].astype(np.float32),
            "wpe": sd[p + "wpe.weight"].astype(np.float32),
            "blocks": blocks,
            "lnf_g": sd[p + "ln_f.weight"].astype(np.float32),
            "lnf_b": sd[p + "ln_f.bias"].astype(np.float32),
        }
        return cfg, params


class HFBertLayerPolicy(DSPolicy):
    """transformers BERT (reference ``replace_policy.py:43``) → the
    post-LN BERT layout in ``models/bert.py``."""

    architectures = ("BertModel", "BertForMaskedLM", "BertForPreTraining", "BertForSequenceClassification")

    @classmethod
    def convert(cls, model, hf_config=None):
        from deepspeed_tpu.models.bert import BertConfig

        sd = _state_dict_of(model)
        hf = hf_config if hf_config is not None else model.config
        prefix = "bert." if any(k.startswith("bert.") for k in sd) else ""
        n_layer = hf.num_hidden_layers
        cfg = BertConfig(
            vocab_size=hf.vocab_size,
            max_position_embeddings=hf.max_position_embeddings,
            type_vocab_size=hf.type_vocab_size,
            hidden_size=hf.hidden_size,
            num_hidden_layers=n_layer,
            num_attention_heads=hf.num_attention_heads,
            intermediate_size=hf.intermediate_size,
            layer_norm_eps=hf.layer_norm_eps,
            pre_layer_norm=False,
            remat=False,
        )
        p = prefix + "encoder.layer.{}."
        qkv_w, qkv_b = [], []
        for i in range(n_layer):
            base = p.format(i) + "attention.self."
            qkv_w.append(np.concatenate([sd[base + f"{n}.weight"].T for n in ("query", "key", "value")], axis=1))
            qkv_b.append(np.concatenate([sd[base + f"{n}.bias"] for n in ("query", "key", "value")]))
        blocks = {
            "qkv_w": np.stack(qkv_w).astype(np.float32),
            "qkv_b": np.stack(qkv_b).astype(np.float32),
            "proj_w": _stack(sd, p + "attention.output.dense.weight", n_layer, transpose=True),
            "proj_b": _stack(sd, p + "attention.output.dense.bias", n_layer),
            "ln1_g": _stack(sd, p + "attention.output.LayerNorm.weight", n_layer),
            "ln1_b": _stack(sd, p + "attention.output.LayerNorm.bias", n_layer),
            "fc_w": _stack(sd, p + "intermediate.dense.weight", n_layer, transpose=True),
            "fc_b": _stack(sd, p + "intermediate.dense.bias", n_layer),
            "fc_proj_w": _stack(sd, p + "output.dense.weight", n_layer, transpose=True),
            "fc_proj_b": _stack(sd, p + "output.dense.bias", n_layer),
            "ln2_g": _stack(sd, p + "output.LayerNorm.weight", n_layer),
            "ln2_b": _stack(sd, p + "output.LayerNorm.bias", n_layer),
        }
        e = prefix + "embeddings."
        d = hf.hidden_size
        params = {
            "tok_emb": sd[e + "word_embeddings.weight"].astype(np.float32),
            "pos_emb": sd[e + "position_embeddings.weight"].astype(np.float32),
            "type_emb": sd[e + "token_type_embeddings.weight"].astype(np.float32),
            "emb_ln_g": sd[e + "LayerNorm.weight"].astype(np.float32),
            "emb_ln_b": sd[e + "LayerNorm.bias"].astype(np.float32),
            "blocks": blocks,
            "pooler_w": (
                sd[prefix + "pooler.dense.weight"].T.astype(np.float32)
                if prefix + "pooler.dense.weight" in sd
                else np.zeros((d, d), np.float32)
            ),
            "pooler_b": sd.get(prefix + "pooler.dense.bias", np.zeros(d, np.float32)).astype(np.float32),
            "mlm_dense_w": np.zeros((d, d), np.float32),
            "mlm_dense_b": np.zeros(d, np.float32),
            "mlm_ln_g": np.ones(d, np.float32),
            "mlm_ln_b": np.zeros(d, np.float32),
            "mlm_bias": np.zeros(hf.vocab_size, np.float32),
            "nsp_w": np.zeros((d, 2), np.float32),
            "nsp_b": np.zeros(2, np.float32),
        }
        # MLM head if present (BertForMaskedLM / ForPreTraining)
        mlm = "cls.predictions."
        if mlm + "transform.dense.weight" in sd:
            params["mlm_dense_w"] = sd[mlm + "transform.dense.weight"].T.astype(np.float32)
            params["mlm_dense_b"] = sd[mlm + "transform.dense.bias"].astype(np.float32)
            params["mlm_ln_g"] = sd[mlm + "transform.LayerNorm.weight"].astype(np.float32)
            params["mlm_ln_b"] = sd[mlm + "transform.LayerNorm.bias"].astype(np.float32)
            params["mlm_bias"] = sd[mlm + "bias"].astype(np.float32)
        if "cls.seq_relationship.weight" in sd:
            params["nsp_w"] = sd["cls.seq_relationship.weight"].T.astype(np.float32)
            params["nsp_b"] = sd["cls.seq_relationship.bias"].astype(np.float32)
        return cfg, params


class MegatronLayerPolicy(DSPolicy):
    """Megatron-LM GPT checkpoints (reference ``replace_policy.py:146``).

    Megatron stores transformer weights as (out, in) Linears under
    ``language_model.transformer.layers.N.*`` with fused
    query_key_value; row/column TP shards must be pre-merged (the
    checkpoint-loader's ``MegatronSDLoader.merge`` analog in
    inference/checkpoint.py does this).
    """

    architectures = ("GPT2Model_megatron", "MegatronGPT")

    @classmethod
    def matches(cls, model) -> bool:
        # Megatron checkpoints usually arrive as plain state dicts —
        # probe for the transformer key prefix.
        if isinstance(model, dict):
            return "language_model.transformer.layers.0.input_layernorm.weight" in model
        sd = model.state_dict() if hasattr(model, "state_dict") else {}
        return super().matches(model) or (
            "language_model.transformer.layers.0.input_layernorm.weight" in sd
        )

    @classmethod
    def convert(cls, model, hf_config=None):
        from deepspeed_tpu.models.gpt2 import GPT2Config

        sd = _state_dict_of(model)
        cfgsrc = hf_config if hf_config is not None else getattr(model, "config", None)
        p = "language_model.transformer.layers.{}."
        n_layer = 0
        while (p.format(n_layer) + "input_layernorm.weight") in sd:
            n_layer += 1
        if n_layer == 0:
            raise ValueError("not a Megatron GPT state dict (no transformer.layers.*)")
        wte = sd["language_model.embedding.word_embeddings.weight"].astype(np.float32)
        wpe = sd["language_model.embedding.position_embeddings.weight"].astype(np.float32)
        d = wte.shape[1]
        n_head = getattr(cfgsrc, "num_attention_heads", None) or max(1, d // 64)
        cfg = GPT2Config(
            vocab_size=wte.shape[0], n_positions=wpe.shape[0], n_embd=d,
            n_layer=n_layer, n_head=n_head, remat=False,
        )
        # Megatron stores the fused QKV output dim per-head interleaved:
        # (heads, 3, head_dim).  Our blocks expect contiguous q|k|v, so
        # permute to (3, heads, head_dim) (the reference's megatron
        # qkv-reorder in replace_module.py does the inverse on inject).
        hd = d // n_head

        def deinterleave_w(w):  # w: (d, 3d) after transpose, columns = outputs
            return w.reshape(d, n_head, 3, hd).transpose(0, 2, 1, 3).reshape(d, 3 * d)

        def deinterleave_b(b):
            return b.reshape(n_head, 3, hd).transpose(1, 0, 2).reshape(3 * d)

        qkv_w = _stack(sd, p + "attention.query_key_value.weight", n_layer, transpose=True)
        qkv_b = _stack(sd, p + "attention.query_key_value.bias", n_layer)
        blocks = {
            "ln1_g": _stack(sd, p + "input_layernorm.weight", n_layer),
            "ln1_b": _stack(sd, p + "input_layernorm.bias", n_layer),
            "qkv_w": np.stack([deinterleave_w(w) for w in qkv_w]),
            "qkv_b": np.stack([deinterleave_b(b) for b in qkv_b]),
            "proj_w": _stack(sd, p + "attention.dense.weight", n_layer, transpose=True),
            "proj_b": _stack(sd, p + "attention.dense.bias", n_layer),
            "ln2_g": _stack(sd, p + "post_attention_layernorm.weight", n_layer),
            "ln2_b": _stack(sd, p + "post_attention_layernorm.bias", n_layer),
            "fc_w": _stack(sd, p + "mlp.dense_h_to_4h.weight", n_layer, transpose=True),
            "fc_b": _stack(sd, p + "mlp.dense_h_to_4h.bias", n_layer),
            "fc_proj_w": _stack(sd, p + "mlp.dense_4h_to_h.weight", n_layer, transpose=True),
            "fc_proj_b": _stack(sd, p + "mlp.dense_4h_to_h.bias", n_layer),
        }
        params = {
            "wte": wte,
            "wpe": wpe,
            "blocks": blocks,
            "lnf_g": sd["language_model.transformer.final_layernorm.weight"].astype(np.float32),
            "lnf_b": sd["language_model.transformer.final_layernorm.bias"].astype(np.float32),
        }
        return cfg, params


# Generic-policy registry, walked in order (reference replace_policy.py
# keeps the same list-of-policies shape).
ALL_POLICIES = [HFGPT2LayerPolicy, HFGPTNEOLayerPolicy, HFBertLayerPolicy, MegatronLayerPolicy]


def replace_transformer_layer(model, policy: Optional[type] = None, hf_config=None):
    """Reference ``replace_transformer_layer`` (``replace_module.py:89``) —
    here: resolve a policy and convert the whole model to the fused
    native parameter layout.  Returns ``(model_config, params)``."""
    if policy is not None:
        return policy.convert(model, hf_config=hf_config)
    for pol in ALL_POLICIES:
        if pol.matches(model):
            logger.info(f"injection: matched policy {pol.__name__}")
            return pol.convert(model, hf_config=hf_config)
    raise ValueError(
        f"No injection policy for {type(model).__name__}; pass injection_policy= "
        f"(available: {[p.__name__ for p in ALL_POLICIES]})"
    )
