"""deepspeed_tpu — a TPU-native training/inference framework with the
capabilities of DeepSpeed (reference v0.4.5), re-designed for JAX/XLA:
SPMD named-axis meshes instead of process groups, sharding rules instead
of optimizer-wrapper hooks (ZeRO 1-3), Pallas kernels instead of CUDA,
XLA collectives over ICI instead of NCCL.

Public API mirrors the reference's ``deepspeed/__init__.py``:
``initialize`` (:58), ``init_inference`` (:227), ``init_distributed``,
``add_config_arguments`` (:211).
"""
from __future__ import annotations

import argparse
from typing import Any, Callable, Optional, Tuple

from deepspeed_tpu.version import __version__
from deepspeed_tpu.comm.distributed import init_distributed
from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.utils.logging import log_dist, logger

__git_hash__ = None
__git_branch__ = None


def initialize(
    args=None,
    model: Optional[Callable] = None,
    model_parameters: Any = None,
    optimizer: Any = None,
    training_data: Any = None,
    lr_scheduler: Any = None,
    mesh=None,
    tp_spec_fn=None,
    partition_rules=None,
    loss_fn: Optional[Callable] = None,
    dist_init_required: Optional[bool] = None,
    collate_fn: Optional[Callable] = None,
    config: Any = None,
    config_params: Any = None,
):
    """Build a ready-to-train engine.

    Reference signature preserved (``deepspeed/__init__.py:58-157``) with
    TPU-native meanings:

    * ``model`` — callable ``(params, batch, rng) -> loss`` (or outputs if
      ``loss_fn`` is given).  Flax modules: pass
      ``lambda p, b, rng: module.apply({'params': p}, b, rngs={'dropout': rng})``.
    * ``model_parameters`` — the initial parameter pytree (the reference
      passes ``model.parameters()`` here).
    * ``config`` — dict or path to a DeepSpeed-style JSON config.
    * ``mesh`` — optional prebuilt ``jax.sharding.Mesh``; default built
      from the config's ``mesh`` block over all devices.

    Returns ``(engine, optimizer, dataloader, lr_scheduler)``.
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    from deepspeed_tpu.comm.mesh import MeshInfo, make_mesh

    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config:
        config = args.deepspeed_config
    if config is None:
        raise DeepSpeedConfigError("initialize() needs `config` (dict or json path)")
    if model is None:
        raise ValueError("initialize() needs `model` (callable (params, batch, rng) -> loss/outputs)")
    is_pipe = isinstance(model, PipelineModule)
    if model_parameters is None and not is_pipe:
        raise ValueError("initialize() needs `model_parameters` (initial parameter pytree)")

    if dist_init_required is None or dist_init_required:
        init_distributed(verbose=False)

    # Resolve the mesh first (the batch triad needs the dp world size).
    if mesh is None:
        import json as _json

        from deepspeed_tpu.config.config import MeshConfig

        raw = config
        if isinstance(raw, str):
            with open(raw) as f:
                raw = _json.load(f)
        mesh = make_mesh(MeshConfig.from_dict(raw.get("mesh")))
    info = MeshInfo.from_mesh(mesh)
    ds_config = DeepSpeedConfig(config, world_size=info.dp_world_size)

    stream_reason = "pipeline module" if is_pipe else None
    if not is_pipe and ds_config.zero_config.offload_param.enabled:
        from deepspeed_tpu.runtime.zero.param_offload import ZeroInfinityEngine

        stream_reason = ZeroInfinityEngine.streamable(model, ds_config, info, optimizer)
        if stream_reason is not None:
            # refuse (not warn-then-OOM) when the model the user asked to
            # STREAM would not fit the in-HBM fallback engine
            ZeroInfinityEngine.check_fallback_fits(
                model_parameters, ds_config, info, stream_reason
            )
            from deepspeed_tpu.utils.logging import logger as _logger

            _logger.warning(
                f"offload_param: falling back to the in-HBM engine — {stream_reason}"
            )
    if not is_pipe and ds_config.zero_config.offload_param.enabled and stream_reason is None:
        # ZeRO-Infinity param offload: params exceed HBM — stream layer
        # groups through the device (reference
        # partitioned_param_swapper.py:36 / features.md:116 "13B on one
        # 32GB device"); models advertise streamability via
        # model.stream_spec (models/gpt2.py)
        from deepspeed_tpu.runtime.zero.param_offload import ZeroInfinityEngine

        engine = ZeroInfinityEngine(
            model=model,
            params=model_parameters,
            config=ds_config,
            mesh=mesh,
            lr_scheduler=lr_scheduler,
        )
    elif is_pipe:
        # reference: PipelineEngine iff model is a PipelineModule
        # (deepspeed/__init__.py:125-149)
        from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

        if loss_fn is not None:
            if model.loss_fn is not None and model.loss_fn is not loss_fn:
                raise ValueError("loss_fn given both to PipelineModule and initialize()")
            model.loss_fn = loss_fn
        engine = PipelineEngine(
            module=model,
            config=ds_config,
            mesh=mesh,
            params=model_parameters,
            optimizer=optimizer,
            lr_scheduler=lr_scheduler,
            tp_spec_fn=tp_spec_fn,
            partition_rules=partition_rules,
        )
    else:
        engine = DeepSpeedEngine(
            model=model,
            params=model_parameters,
            config=ds_config,
            optimizer=optimizer,
            lr_scheduler=lr_scheduler,
            mesh=mesh,
            tp_spec_fn=tp_spec_fn,
            partition_rules=partition_rules,
            loss_fn=loss_fn,
            dist_init_required=dist_init_required,
        )

    dataloader = None
    if training_data is not None:
        import jax

        local_dp = max(1, info.dp_world_size // jax.process_count())
        dataloader = DeepSpeedDataLoader(
            training_data,
            batch_size=ds_config.train_micro_batch_size_per_gpu * local_dp,
            shuffle=True,
            seed=ds_config.seed,
            drop_last=ds_config.dataloader_drop_last,
            collate_fn=collate_fn,
        )

    return engine, engine.optimizer, dataloader, engine.lr_schedule


def init_inference(model=None, **kwargs):
    """Reference ``init_inference`` (:227) — builds an InferenceEngine."""
    from deepspeed_tpu.inference.engine import InferenceEngine

    return InferenceEngine(model=model, **kwargs)


def init_serving(model=None, serving=None, **kwargs):
    """TPU-native extension: a continuous-batching ServingEngine over an
    :func:`init_inference` engine (docs/serving.md).  ``serving`` is the
    ``serving`` config block (dict or ServingConfig); remaining kwargs go
    to ``init_inference``."""
    from deepspeed_tpu.serving import ServingEngine

    return ServingEngine(init_inference(model=model, **kwargs), config=serving)


def add_config_arguments(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """Reference ``add_config_arguments`` (:211): the standard argparse
    group so recipes keep their ``--deepspeed --deepspeed_config x.json``
    flags."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument(
        "--deepspeed",
        default=False,
        action="store_true",
        help="Enable DeepSpeed (helper flag for user code, no impact on engine)",
    )
    group.add_argument("--deepspeed_config", default=None, type=str, help="DeepSpeed json configuration file")
    group.add_argument(
        "--deepscale",
        default=False,
        action="store_true",
        help="Deprecated enable DeepSpeed (helper flag for user code, no impact on engine)",
    )
    group.add_argument("--deepscale_config", default=None, type=str, help="Deprecated DeepSpeed json configuration file")
    group.add_argument("--local_rank", default=-1, type=int, help="Reserved for compatibility; unused on TPU")
    return parser


# `zero` namespace for reference-style `with deepspeed.zero.Init()` usage.
from deepspeed_tpu.runtime.zero import api as zero  # noqa: E402
