"""CIFAR-10 tiny CNN — the first rung of the workload ladder.

Reference: the DeepSpeedExamples ``cifar`` recipe (BASELINE config 1:
CIFAR-10 tiny CNN, ZeRO-0, single device) — the smoke-test model every
engine feature must be able to drive end-to-end.

TPU-idiomatic: convs via ``lax.conv_general_dilated`` in NHWC (the TPU-
native conv layout), pooling via ``lax.reduce_window``; params are a
plain pytree like the transformer models, so the same engine/ZeRO/
checkpoint machinery applies unchanged.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class CifarConfig:
    num_classes: int = 10
    channels: int = 3
    image_size: int = 32
    conv1_filters: int = 32
    conv2_filters: int = 64
    hidden: int = 256

    def num_params(self) -> int:
        c1, c2, h = self.conv1_filters, self.conv2_filters, self.hidden
        flat = (self.image_size // 4) ** 2 * c2
        return (
            3 * 3 * self.channels * c1 + c1
            + 3 * 3 * c1 * c2 + c2
            + flat * h + h
            + h * self.num_classes + self.num_classes
        )


CIFAR_TINY = CifarConfig()


def init_params(cfg: CifarConfig = CIFAR_TINY, seed: int = 0) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)

    def he(*shape, fan_in):
        return (rng.standard_normal(shape) * np.sqrt(2.0 / fan_in)).astype(np.float32)

    flat = (cfg.image_size // 4) ** 2 * cfg.conv2_filters
    return {
        "conv1_w": he(3, 3, cfg.channels, cfg.conv1_filters, fan_in=9 * cfg.channels),
        "conv1_b": np.zeros(cfg.conv1_filters, np.float32),
        "conv2_w": he(3, 3, cfg.conv1_filters, cfg.conv2_filters, fan_in=9 * cfg.conv1_filters),
        "conv2_b": np.zeros(cfg.conv2_filters, np.float32),
        "fc1_w": he(flat, cfg.hidden, fan_in=flat),
        "fc1_b": np.zeros(cfg.hidden, np.float32),
        "fc2_w": he(cfg.hidden, cfg.num_classes, fan_in=cfg.hidden),
        "fc2_b": np.zeros(cfg.num_classes, np.float32),
    }


def _conv(x, w, b):
    # NHWC x HWIO -> NHWC, SAME padding (TPU-native layout)
    y = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + b.astype(x.dtype)


def _maxpool2(x):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, window_dimensions=(1, 2, 2, 1), window_strides=(1, 2, 2, 1), padding="VALID"
    )


def apply(params: Dict[str, Any], images: jnp.ndarray, cfg: CifarConfig = CIFAR_TINY) -> jnp.ndarray:
    """``images``: (B, H, W, C) float → logits (B, num_classes)."""
    x = images
    x = jax.nn.relu(_conv(x, params["conv1_w"], params["conv1_b"]))
    x = _maxpool2(x)
    x = jax.nn.relu(_conv(x, params["conv2_w"], params["conv2_b"]))
    x = _maxpool2(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1_w"].astype(x.dtype) + params["fc1_b"].astype(x.dtype))
    return x @ params["fc2_w"].astype(x.dtype) + params["fc2_b"].astype(x.dtype)


def loss_fn(params: Dict[str, Any], batch: Dict[str, Any], rng=None, cfg: CifarConfig = CIFAR_TINY) -> jnp.ndarray:
    """``batch``: {"images": (B,H,W,C), "labels": (B,)} → mean xent."""
    from deepspeed_tpu.ops.normalize import token_nll

    logits = apply(params, batch["images"], cfg)
    return jnp.mean(token_nll(logits, batch["labels"]))


def accuracy(params: Dict[str, Any], batch: Dict[str, Any], cfg: CifarConfig = CIFAR_TINY) -> jnp.ndarray:
    logits = apply(params, batch["images"], cfg)
    return jnp.mean((jnp.argmax(logits, axis=-1) == batch["labels"]).astype(jnp.float32))


def make_model(cfg: CifarConfig = CIFAR_TINY):
    def model_fn(params, batch, rng):
        return loss_fn(params, batch, rng=rng, cfg=cfg)

    return model_fn, lambda seed=0: init_params(cfg, seed=seed), None
