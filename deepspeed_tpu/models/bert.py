"""BERT family — the bing_bert workload model (BASELINE config 2:
BERT-large pretraining, ZeRO 1/2 + FusedAdam; reference tests carry a
full in-tree BERT in ``tests/unit/modeling.py``).

Same TPU-idiomatic structure as gpt2.py: stacked blocks + lax.scan,
flash attention (non-causal), TP specs on the weights.  Pre-LN variant
(the reference's fused "stochastic_transformer" kernels target pre-LN
BERT; ``tests/unit/modelingpreln.py``) with a config switch for post-LN.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash_attention import flash_attention, mha_reference
from deepspeed_tpu.models.gpt2 import _dropout, _layer_norm


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = True
    use_flash_attention: bool = True
    remat: bool = True
    # lax.scan unroll factor for the layer loop (see gpt2.GPT2Config)
    scan_unroll: int = 1

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def num_params(self) -> int:
        d, l, i = self.hidden_size, self.num_hidden_layers, self.intermediate_size
        per_layer = 4 * d * d + 2 * d * i + 9 * d + i
        emb = (self.vocab_size + self.max_position_embeddings + self.type_vocab_size) * d + 2 * d
        return emb + l * per_layer + 2 * d


BERT_TINY = BertConfig(vocab_size=512, max_position_embeddings=128, hidden_size=64, num_hidden_layers=2, num_attention_heads=4, intermediate_size=128)
BERT_BASE = BertConfig()
BERT_LARGE = BertConfig(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16, intermediate_size=4096)

PRESETS = {"tiny": BERT_TINY, "bert-base": BERT_BASE, "bert-large": BERT_LARGE}


def init_params(cfg: BertConfig, seed: int = 0) -> Dict[str, Any]:
    rng = np.random.default_rng(seed)
    d, l, i = cfg.hidden_size, cfg.num_hidden_layers, cfg.intermediate_size

    def n(*shape, s=0.02):
        return (rng.standard_normal(shape) * s).astype(np.float32)

    def z(*shape):
        return np.zeros(shape, np.float32)

    def o(*shape):
        return np.ones(shape, np.float32)

    return {
        "tok_emb": n(cfg.vocab_size, d),
        "pos_emb": n(cfg.max_position_embeddings, d),
        "type_emb": n(cfg.type_vocab_size, d),
        "emb_ln_g": o(d),
        "emb_ln_b": z(d),
        "blocks": {
            "ln1_g": o(l, d), "ln1_b": z(l, d),
            "qkv_w": n(l, d, 3 * d), "qkv_b": z(l, 3 * d),
            "proj_w": n(l, d, d), "proj_b": z(l, d),
            "ln2_g": o(l, d), "ln2_b": z(l, d),
            "fc_w": n(l, d, i), "fc_b": z(l, i),
            "fc_proj_w": n(l, i, d), "fc_proj_b": z(l, d),
        },
        "pooler_w": n(d, d),
        "pooler_b": z(d),
        # MLM head: transform + tied decoder bias; NSP head
        "mlm_dense_w": n(d, d),
        "mlm_dense_b": z(d),
        "mlm_ln_g": o(d),
        "mlm_ln_b": z(d),
        "mlm_bias": z(cfg.vocab_size),
        "nsp_w": n(d, 2),
        "nsp_b": z(2),
    }


def init_params_device(cfg: BertConfig, seed: int = 0, dtype=jnp.float32):
    """Random init generated ON DEVICE (same tree structure/shapes as
    ``init_params``, independent random stream) — see
    ``models/gpt2.init_params_device`` for when to use which."""
    d, l, i = cfg.hidden_size, cfg.num_hidden_layers, cfg.intermediate_size

    def build(key):
        ks = iter(jax.random.split(key, 16))

        def n(shape, s=0.02):
            return (jax.random.normal(next(ks), shape, jnp.float32) * s).astype(dtype)

        z = lambda *shape: jnp.zeros(shape, dtype)
        o = lambda *shape: jnp.ones(shape, dtype)
        return {
            "tok_emb": n((cfg.vocab_size, d)),
            "pos_emb": n((cfg.max_position_embeddings, d)),
            "type_emb": n((cfg.type_vocab_size, d)),
            "emb_ln_g": o(d),
            "emb_ln_b": z(d),
            "blocks": {
                "ln1_g": o(l, d), "ln1_b": z(l, d),
                "qkv_w": n((l, d, 3 * d)), "qkv_b": z(l, 3 * d),
                "proj_w": n((l, d, d)), "proj_b": z(l, d),
                "ln2_g": o(l, d), "ln2_b": z(l, d),
                "fc_w": n((l, d, i)), "fc_b": z(l, i),
                "fc_proj_w": n((l, i, d)), "fc_proj_b": z(l, d),
            },
            "pooler_w": n((d, d)),
            "pooler_b": z(d),
            "mlm_dense_w": n((d, d)),
            "mlm_dense_b": z(d),
            "mlm_ln_g": o(d),
            "mlm_ln_b": z(d),
            "mlm_bias": z(cfg.vocab_size),
            "nsp_w": n((d, 2)),
            "nsp_b": z(2),
        }

    # out_shardings=None: init params land unsharded; the engine shards
    # them on first scoped step (docs/ds_lint.md, bare-jit)
    return jax.jit(build, out_shardings=None)(jax.random.PRNGKey(seed))


def tp_spec_fn(path: str, shape) -> Optional[P]:
    """Adapter over the partition-rule engine's ``bert`` family table
    (sharding/rules.py) — the single source of truth for this layout."""
    from deepspeed_tpu.sharding.rules import rules_for_family

    return rules_for_family("bert").spec(path, shape)


def _bert_block(cfg: BertConfig, x, lp, mask_bias, rng, deterministic):
    B, T, D = x.shape
    H, hd = cfg.num_attention_heads, cfg.head_dim
    r1 = r2 = r_attn = None
    if rng is not None:
        r1, r2, r_attn = jax.random.split(rng, 3)

    def attn_part(h):
        qkv = h @ lp["qkv_w"].astype(h.dtype) + lp["qkv_b"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        def heads(t):
            return t.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
        q, k, v = heads(q), heads(k), heads(v)
        # padding-mask bias + attention-probability dropout go through
        # the fused path natively (flash_attention falls back to
        # mha_reference for shapes its grid can't serve)
        rate = 0.0 if deterministic or r_attn is None else cfg.attention_probs_dropout_prob
        if cfg.use_flash_attention:
            out = flash_attention(q, k, v, causal=False, bias=mask_bias, dropout_rate=rate, dropout_rng=r_attn)
        else:
            m4 = None
            if rate > 0.0:
                m4 = jax.random.bernoulli(r_attn, 1.0 - rate, (B, H, T, T)).astype(jnp.uint8)
            out = mha_reference(q, k, v, causal=False, bias=mask_bias, dropout_mask=m4, keep_prob=1.0 - rate)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, D)
        return out @ lp["proj_w"].astype(out.dtype) + lp["proj_b"].astype(out.dtype)

    def mlp_part(h):
        h = h @ lp["fc_w"].astype(h.dtype) + lp["fc_b"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=False)
        return h @ lp["fc_proj_w"].astype(h.dtype) + lp["fc_proj_b"].astype(h.dtype)

    eps = cfg.layer_norm_eps
    if cfg.pre_layer_norm:
        x = x + _dropout(attn_part(_layer_norm(x, lp["ln1_g"], lp["ln1_b"], eps)), cfg.hidden_dropout_prob, r1, deterministic)
        x = x + _dropout(mlp_part(_layer_norm(x, lp["ln2_g"], lp["ln2_b"], eps)), cfg.hidden_dropout_prob, r2, deterministic)
    else:
        x = _layer_norm(x + _dropout(attn_part(x), cfg.hidden_dropout_prob, r1, deterministic), lp["ln1_g"], lp["ln1_b"], eps)
        x = _layer_norm(x + _dropout(mlp_part(x), cfg.hidden_dropout_prob, r2, deterministic), lp["ln2_g"], lp["ln2_b"], eps)
    return x


def encode(params, input_ids, cfg: BertConfig, token_type_ids=None, attention_mask=None, rng=None, deterministic=True):
    B, T = input_ids.shape
    dtype = params["blocks"]["qkv_w"].dtype
    x = jnp.take(params["tok_emb"], input_ids, axis=0) + params["pos_emb"][:T][None]
    if token_type_ids is None:
        # BERT semantics: absent segment ids mean "all segment A" — the
        # type-0 embedding is still added (HF does the same).
        x = x + params["type_emb"][0][None, None]
    else:
        x = x + jnp.take(params["type_emb"], token_type_ids, axis=0)
    x = _layer_norm(x.astype(dtype), params["emb_ln_g"], params["emb_ln_b"], cfg.layer_norm_eps)

    mask_bias = None
    if attention_mask is not None:
        neg = jnp.asarray(-1e9, jnp.float32)
        mask_bias = jnp.where(attention_mask[:, None, None, :].astype(bool), 0.0, neg)

    L = cfg.num_hidden_layers
    layer_rngs = jax.random.split(rng, L) if rng is not None else jnp.zeros((L, 2), jnp.uint32)
    block = functools.partial(_bert_block, cfg)

    def scan_body(carry, xs):
        lp, lr = xs
        return block(carry, lp, mask_bias, lr if rng is not None else None, deterministic), None

    if cfg.remat:
        scan_body = jax.checkpoint(scan_body, prevent_cse=False)
    x, _ = jax.lax.scan(scan_body, x, (params["blocks"], layer_rngs), unroll=max(1, cfg.scan_unroll))
    return x


def mlm_nsp_loss(params, batch, rng=None, cfg: BertConfig = None, deterministic=False):
    """Pretraining loss: masked-LM + next-sentence prediction.

    ``batch``: input_ids, masked_lm_labels (-100 = unmasked), optional
    token_type_ids / attention_mask / next_sentence_label.
    """
    x = encode(
        params,
        batch["input_ids"],
        cfg,
        token_type_ids=batch.get("token_type_ids"),
        attention_mask=batch.get("attention_mask"),
        rng=rng,
        deterministic=deterministic,
    )
    # MLM
    h = x @ params["mlm_dense_w"].astype(x.dtype) + params["mlm_dense_b"].astype(x.dtype)
    h = jax.nn.gelu(h, approximate=False)
    h = _layer_norm(h, params["mlm_ln_g"], params["mlm_ln_b"], cfg.layer_norm_eps)
    logits = (h @ params["tok_emb"].T.astype(h.dtype)).astype(jnp.float32) + params["mlm_bias"]
    labels = batch["masked_lm_labels"]
    valid = labels >= 0
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * valid.astype(jnp.float32)
    mlm_loss = jnp.sum(nll) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)

    loss = mlm_loss
    if "next_sentence_label" in batch:
        pooled = jnp.tanh(x[:, 0] @ params["pooler_w"].astype(x.dtype) + params["pooler_b"].astype(x.dtype))
        nsp_logits = (pooled @ params["nsp_w"].astype(pooled.dtype) + params["nsp_b"].astype(pooled.dtype)).astype(jnp.float32)
        nsp_labels = batch["next_sentence_label"]
        nsp = jax.nn.logsumexp(nsp_logits, axis=-1) - jnp.take_along_axis(nsp_logits, nsp_labels[..., None], axis=-1)[..., 0]
        loss = loss + jnp.mean(nsp)
    return loss


def make_model(cfg: BertConfig):
    def model_fn(params, batch, rng):
        # rng=None ⇒ eval mode (engine passes None from eval_batch/predict)
        deterministic = rng is None or cfg.hidden_dropout_prob == 0.0
        return mlm_nsp_loss(params, batch, rng=rng, cfg=cfg, deterministic=deterministic)

    return model_fn, functools.partial(init_params, cfg), tp_spec_fn
