"""GPT-2 family — the flagship training model.

The reference trains GPT-2 through client Megatron-LM code (SURVEY.md §6
workload ladder: GPT-2 345M/1.5B ZeRO-3); this framework ships the model
natively, TPU-idiomatic:

* all transformer blocks **stacked on a leading layer dim** and executed
  with ``lax.scan`` — one trace/compile regardless of depth, and the
  layer dim doubles as the pipeline-partition dim;
* attention through the Pallas flash-attention op (ops/attention);
* Megatron-style tensor parallelism expressed as PartitionSpecs on the
  weights (``tp_spec_fn``): qkv/fc column-parallel, proj row-parallel,
  vocab-sharded embedding — GSPMD inserts the psums the reference gets
  from explicit mpu collectives;
* activation checkpointing via ``jax.checkpoint`` policy on the scanned
  block (reference ``runtime/activation_checkpointing``).

Params are a plain pytree of jnp arrays (fp32 masters; engine casts).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention.flash_attention import flash_attention, mha_reference
# single shared implementation (ops/normalize.py); aliased because
# models/bert.py imports these names from here
from deepspeed_tpu.ops.normalize import dropout as _dropout, layer_norm as _layer_norm, token_nll


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    use_flash_attention: bool = True
    # "flash" | "ring" | "ulysses" | "sparse" — ring/ulysses run
    # sequence-parallel over the mesh's `seq` axis (parallel/sequence.py);
    # sparse uses the block-sparse kernel with `sparsity_config`
    # (default: unidirectional BigBird), the reference's long-sequence
    # recipe (SURVEY §5.7)
    attention_mode: str = "flash"
    # a SparsityConfig instance (ops/attention/sparse.py); None ⇒ BigBird
    sparsity_config: Any = None
    # MoE: >0 replaces every block's FFN with an n_experts MoE layer
    # (experts sharded over the `expert` mesh axis, moe/layer.py)
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    remat: bool = True  # activation checkpointing per block
    # >0: cross-entropy computed in time-chunks of this size under remat,
    # so the (B, T, vocab) logits tensor never materializes whole —
    # memory drops by ~B*T*V*6 bytes at ~10% extra logit-matmul flops
    xent_chunk_size: int = 0
    remat_policy: str = "nothing_saveable"  # or "dots_with_no_batch_dims_saveable"
    # selective checkpointing: non-empty ⇒ overrides remat_policy with
    # save_only_these_names over the tags placed in _block —
    # "qkv" (B,T,3D), "attn_ctx" (B,T,D), "ffn_pre" (B,T,4D).  Saving all
    # three keeps 8D·B·T bytes/layer and cuts the backward's recompute
    # from a full block forward (~1/4 of step flops under
    # nothing_saveable) to the flash-attention forward + elementwise ops
    # (~3%) — the reference gets the same effect from its fused kernels
    # saving their intermediates (csrc/transformer/ds_transformer_cuda.cpp)
    remat_save_names: tuple = ()
    # lax.scan unroll factor for the layer loop: >1 trades compile time
    # for fewer loop-carried copies / less per-iteration bookkeeping
    scan_unroll: int = 1
    # flash kernel block override: (block_q, block_k[, bwd_block_q,
    # bwd_block_k]); empty ⇒ the op's measured defaults
    flash_blocks: tuple = ()
    dtype: Any = jnp.float32  # activation dtype is set by the engine cast

    @property
    def head_dim(self) -> int:
        assert self.n_embd % self.n_head == 0
        return self.n_embd // self.n_head

    def num_params(self) -> int:
        d, l, v, s = self.n_embd, self.n_layer, self.vocab_size, self.n_positions
        if self.n_experts > 0:
            E = self.n_experts
            # attention (qkv+proj) + LNs + router + E expert FFNs
            per_layer = 4 * d * d + 8 * d + d * E + E * (8 * d * d + 5 * d)
        else:
            per_layer = 12 * d * d + 13 * d
        return v * d + s * d + l * per_layer + 2 * d


# Model zoo (sizes as in the GPT-2 paper; 1.5B == "xl" is the BASELINE
# north-star model).
GPT2_TINY = GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=4)
GPT2_SMALL = GPT2Config()  # 124M
GPT2_MEDIUM = GPT2Config(n_embd=1024, n_layer=24, n_head=16)  # 350M
GPT2_LARGE = GPT2Config(n_embd=1280, n_layer=36, n_head=20)  # 774M
GPT2_XL = GPT2Config(n_embd=1600, n_layer=48, n_head=25)  # 1.5B

# GPT-Neo-2.7B dims (BASELINE ladder's inference rung; HF weights map
# through HFGPTNEOLayerPolicy — this preset serves the random-init
# serving/throughput path at the same scale)
GPT_NEO_27B = GPT2Config(n_positions=2048, n_embd=2560, n_layer=32, n_head=20)

PRESETS = {
    "tiny": GPT2_TINY,
    "gpt2": GPT2_SMALL,
    "gpt2-small": GPT2_SMALL,
    "gpt2-medium": GPT2_MEDIUM,
    "gpt2-large": GPT2_LARGE,
    "gpt2-xl": GPT2_XL,
    "gpt2-1.5b": GPT2_XL,
    "gpt-neo-2.7b": GPT_NEO_27B,
    "gpt-neo": GPT_NEO_27B,
}


def init_params(cfg: GPT2Config, seed: int = 0) -> Dict[str, Any]:
    """GPT-2 init: normal(0.02), residual projections scaled by
    1/sqrt(2*n_layer)."""
    rng = np.random.default_rng(seed)
    d, l = cfg.n_embd, cfg.n_layer
    std = 0.02
    proj_std = std / np.sqrt(2 * l)

    def n(*shape, s=std):
        return (rng.standard_normal(shape) * s).astype(np.float32)

    def z(*shape):
        return np.zeros(shape, np.float32)

    def o(*shape):
        return np.ones(shape, np.float32)

    if cfg.n_experts > 0:
        from deepspeed_tpu.moe.layer import MoEConfig, init_moe_params

        mcfg = MoEConfig(num_experts=cfg.n_experts, d_model=d, d_ff=4 * d)
        per_layer = [init_moe_params(mcfg, rng, std=std, proj_std=proj_std) for _ in range(l)]
        ffn = {k: np.stack([p[k] for p in per_layer]) for k in per_layer[0]}
    else:
        ffn = {
            "fc_w": n(l, d, 4 * d),
            "fc_b": z(l, 4 * d),
            "fc_proj_w": n(l, 4 * d, d, s=proj_std),
            "fc_proj_b": z(l, d),
        }
    return {
        "wte": n(cfg.vocab_size, d),
        "wpe": n(cfg.n_positions, d, s=0.01),
        "blocks": {
            "ln1_g": o(l, d),
            "ln1_b": z(l, d),
            "qkv_w": n(l, d, 3 * d),
            "qkv_b": z(l, 3 * d),
            "proj_w": n(l, d, d, s=proj_std),
            "proj_b": z(l, d),
            "ln2_g": o(l, d),
            "ln2_b": z(l, d),
            **ffn,
        },
        "lnf_g": o(d),
        "lnf_b": z(d),
    }


def init_params_device(cfg: GPT2Config, seed: int = 0, dtype=jnp.float32):
    """Random init generated ON DEVICE (same tree structure/shapes as
    ``init_params``, independent random stream).

    For benchmark/serving paths where host generation + upload of an
    XL-class model costs minutes over PCIe/tunnel while on-chip
    generation costs seconds.  Not bitwise-equal to ``init_params`` —
    use the host init when pinned numerics matter."""
    if cfg.n_experts > 0:
        raise NotImplementedError("device init does not cover MoE; use init_params")
    d, l = cfg.n_embd, cfg.n_layer
    std, proj_std = 0.02, 0.02 / np.sqrt(2 * l)

    def build(key):
        ks = iter(jax.random.split(key, 8))

        def n(shape, s=std):
            return (jax.random.normal(next(ks), shape, jnp.float32) * s).astype(dtype)

        z = lambda *shape: jnp.zeros(shape, dtype)
        o = lambda *shape: jnp.ones(shape, dtype)
        return {
            "wte": n((cfg.vocab_size, d)),
            "wpe": n((cfg.n_positions, d), s=0.01),
            "blocks": {
                "ln1_g": o(l, d), "ln1_b": z(l, d),
                "qkv_w": n((l, d, 3 * d)), "qkv_b": z(l, 3 * d),
                "proj_w": n((l, d, d), s=proj_std), "proj_b": z(l, d),
                "ln2_g": o(l, d), "ln2_b": z(l, d),
                "fc_w": n((l, d, 4 * d)), "fc_b": z(l, 4 * d),
                "fc_proj_w": n((l, 4 * d, d), s=proj_std), "fc_proj_b": z(l, d),
            },
            "lnf_g": o(d),
            "lnf_b": z(d),
        }

    # out_shardings=None: init params land unsharded; the engine shards
    # them on first scoped step (docs/ds_lint.md, bare-jit)
    return jax.jit(build, out_shardings=None)(jax.random.PRNGKey(seed))


def tp_spec_fn(path: str, shape) -> Optional[P]:
    """Megatron-style tensor-parallel specs over the ``model`` axis
    (reference delegates TP to Megatron mpu; inference-side slicing in
    module_inject/replace_module.py:11-88 follows the same column/row
    split), plus expert-parallel specs over ``expert`` for MoE weights.
    Thin adapter over the partition-rule engine's ``gpt2`` family table
    (sharding/rules.py) — the single source of truth for this layout."""
    from deepspeed_tpu.sharding.rules import rules_for_family

    return rules_for_family("gpt2").spec(path, shape)


# per-(config-values, seq) layout cache: layouts are static numpy, built once
_SPARSE_LAYOUTS: Dict[Any, Any] = {}


def _sparsity_cache_key(sc, T: int):
    # value-based key (id() would collide after gc and never hit for
    # per-call default configs)
    vals = tuple(
        (k, tuple(v) if isinstance(v, list) else v)
        for k, v in sorted(vars(sc).items())
        if isinstance(v, (int, float, str, bool, list, tuple, type(None)))
    )
    return (type(sc).__name__, vals, T)


def _sparse_attn(cfg: GPT2Config, q, k, v, T: int):
    from deepspeed_tpu.ops.attention.sparse import BigBirdSparsityConfig, block_sparse_attention

    sc = cfg.sparsity_config
    if sc is None:
        # prefer BIG blocks: the splash kernels run one (q-row, edge)
        # pair per grid step, so per-step launch overhead (~1µs)
        # amortizes over block² work — block 256 beat 128 by ~1.3x at
        # 8k on v5e (r5 crossover sweep), and MXU efficiency rises too
        # T/block must cover the 3-block sliding window or make_layout
        # refuses (short sequences fall back to smaller blocks)
        block = next((b for b in (256, 128, 64, 16) if T % b == 0 and T // b >= 3), 16)
        sc = BigBirdSparsityConfig(
            num_heads=cfg.n_head, block=block, num_random_blocks=1,
            num_sliding_window_blocks=3, num_global_blocks=1, attention="unidirectional",
        )
    key = _sparsity_cache_key(sc, T)
    if key not in _SPARSE_LAYOUTS:
        _SPARSE_LAYOUTS[key] = sc.make_layout(T)
    return block_sparse_attention(q, k, v, _SPARSE_LAYOUTS[key], sc.block, causal=True)


def _block(cfg: GPT2Config, x, lp, rng, deterministic: bool, token_mask=None):
    """One transformer block; ``lp`` holds this layer's slice of the
    stacked params."""
    B, T, D = x.shape
    H, hd = cfg.n_head, cfg.head_dim
    r1 = r2 = r3 = None
    if rng is not None:
        r1, r2, r3 = jax.random.split(rng, 3)

    h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"], cfg.layer_norm_epsilon)
    qkv = h @ lp["qkv_w"].astype(h.dtype) + lp["qkv_b"].astype(h.dtype)
    qkv = checkpoint_name(qkv, "qkv")
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(B, T, H, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    if cfg.attention_mode == "ring":
        from deepspeed_tpu.parallel.sequence import ring_attention

        attn = ring_attention(q, k, v, causal=True)
    elif cfg.attention_mode == "ulysses":
        from deepspeed_tpu.parallel.sequence import ulysses_attention

        attn = ulysses_attention(q, k, v, causal=True, use_flash=cfg.use_flash_attention)
    elif cfg.attention_mode == "sparse":
        attn = _sparse_attn(cfg, q, k, v, T)
    elif cfg.attention_mode != "flash":
        raise ValueError(f"unknown attention_mode {cfg.attention_mode!r} (flash|ring|ulysses|sparse)")
    elif cfg.use_flash_attention and T >= 128:
        fb = cfg.flash_blocks
        fb_kw = (
            dict(zip(("block_q", "block_k", "bwd_block_q", "bwd_block_k"), fb)) if fb else {}
        )
        attn = flash_attention(q, k, v, causal=True, **fb_kw)
    else:
        attn = mha_reference(q, k, v, causal=True)
    attn = attn.transpose(0, 2, 1, 3).reshape(B, T, D)
    attn = checkpoint_name(attn, "attn_ctx")
    attn = attn @ lp["proj_w"].astype(attn.dtype) + lp["proj_b"].astype(attn.dtype)
    x = x + _dropout(attn, cfg.dropout, r1, deterministic)

    h = _layer_norm(x, lp["ln2_g"], lp["ln2_b"], cfg.layer_norm_epsilon)
    if cfg.n_experts > 0:
        from deepspeed_tpu.moe.layer import moe_ffn_from_block

        # training ⇔ a dropout/jitter rng was threaded in (eval passes None)
        h, aux = moe_ffn_from_block(
            lp, h, top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            rng=r2, training=rng is not None, token_mask=token_mask,
        )
    else:
        h = h @ lp["fc_w"].astype(h.dtype) + lp["fc_b"].astype(h.dtype)
        h = checkpoint_name(h, "ffn_pre")
        h = jax.nn.gelu(h, approximate=True)
        h = _dropout(h, cfg.dropout, r2, deterministic)
        h = h @ lp["fc_proj_w"].astype(h.dtype) + lp["fc_proj_b"].astype(h.dtype)
        aux = jnp.zeros((), jnp.float32)
    x = x + _dropout(h, cfg.dropout, r3, deterministic)
    return x, aux


def apply(params: Dict[str, Any], tokens: jnp.ndarray, cfg: GPT2Config, rng=None, deterministic: bool = True, return_aux: bool = False, token_mask=None, pld_theta=None, return_hidden: bool = False):
    """Forward pass: ``tokens (B, T) int32`` → logits ``(B, T, V)``.

    ``return_aux=True`` additionally returns the summed MoE
    load-balancing loss (zero for dense models).  ``token_mask (B, T)``
    excludes padding from MoE routing/aux.  ``pld_theta`` (traced scalar)
    enables progressive layer drop: layer l of L is kept with probability
    ``1 - (l+1)/L·(1-theta)`` via ``lax.cond`` — dropped layers skip
    their compute entirely (runtime/progressive_layer_drop.py).
    ``return_hidden=True`` returns the post-final-LN hidden states
    (B, T, D) instead of logits (used by the chunked-xent loss so the
    full logits tensor never materializes)."""
    B, T = tokens.shape
    x = jnp.take(params["wte"], tokens, axis=0) + params["wpe"][:T][None]
    x = x.astype(params["blocks"]["qkv_w"].dtype)

    n_layer = cfg.n_layer
    if rng is not None:
        layer_rngs = jax.random.split(rng, n_layer)
    else:
        layer_rngs = jnp.zeros((n_layer, 2), jnp.uint32)

    block_fn = functools.partial(_block, cfg)
    use_pld = pld_theta is not None and rng is not None and not deterministic
    keep_probs = None
    if use_pld:
        from deepspeed_tpu.runtime.progressive_layer_drop import layer_keep_probs

        keep_probs = layer_keep_probs(pld_theta, n_layer)

    def scan_body(carry, xs):
        x, aux_acc = carry
        if use_pld:
            lp, lr, keep_p = xs
        else:
            lp, lr = xs
        r = lr if rng is not None else None

        def run_block(x_in):
            return block_fn(x_in, lp, r, deterministic, token_mask)

        if use_pld:
            keep = jax.random.bernoulli(jax.random.fold_in(lr, 7), keep_p)

            def kept_branch(x_in):
                # inverted stochastic-depth scaling: the block's residual
                # delta is scaled by 1/keep_p so the training-time
                # expectation matches the deterministic eval forward
                y_in, aux_in = run_block(x_in)
                y_scaled = x_in + (y_in - x_in) / keep_p.astype(y_in.dtype)
                return y_scaled, aux_in

            y, aux = jax.lax.cond(keep, kept_branch, lambda x_in: (x_in, jnp.zeros((), jnp.float32)), x)
        else:
            y, aux = run_block(x)
        return (y, aux_acc + aux), None

    if cfg.remat:
        if cfg.remat_save_names:
            policy = jax.checkpoint_policies.save_only_these_names(*cfg.remat_save_names)
        else:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
        scan_body = jax.checkpoint(scan_body, policy=policy, prevent_cse=False)

    scan_xs = (params["blocks"], layer_rngs, keep_probs) if use_pld else (params["blocks"], layer_rngs)
    (x, aux_total), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), scan_xs, unroll=max(1, cfg.scan_unroll)
    )
    x = _layer_norm(x, params["lnf_g"], params["lnf_b"], cfg.layer_norm_epsilon)
    if return_hidden:
        return (x, aux_total) if return_aux else x
    logits = x @ params["wte"].T.astype(x.dtype)  # tied embedding head
    if return_aux:
        return logits, aux_total
    return logits


def _chunked_xent(hidden: jnp.ndarray, wte: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """Masked-mean next-token NLL computed per time-chunk under remat:
    each chunk's (B, C, V) logits are built, reduced, and discarded —
    the backward recomputes them chunk-by-chunk, so peak memory holds
    one chunk of logits instead of the whole (B, T, V) tensor."""
    B, T, D = hidden.shape
    pad = (-T) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        mask = jnp.pad(mask, ((0, 0), (0, pad)))
    n = (T + pad) // chunk
    hs = hidden.reshape(B, n, chunk, D).transpose(1, 0, 2, 3)
    ls = labels.reshape(B, n, chunk).transpose(1, 0, 2)
    ms = mask.reshape(B, n, chunk).transpose(1, 0, 2)

    @functools.partial(jax.checkpoint, prevent_cse=False)
    def body(carry, inp):
        xc, lc, mc = inp
        logits = xc @ wte.T.astype(xc.dtype)
        nll = token_nll(logits, lc) * mc
        s, c = carry
        return (s + jnp.sum(nll), c + jnp.sum(mc)), None

    (total, count), _ = jax.lax.scan(body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)), (hs, ls, ms))
    return total / jnp.maximum(count, 1.0)


def loss_fn(params: Dict[str, Any], batch: Dict[str, Any], rng=None, cfg: GPT2Config = None, deterministic: bool = False) -> jnp.ndarray:
    """Next-token cross entropy.  ``batch``: {"input_ids": (B, T)} with
    optional "labels" (default: shifted input_ids) and "attention_mask"."""
    from deepspeed_tpu.runtime.progressive_layer_drop import PLD_THETA_KEY

    tokens = batch["input_ids"]
    chunked = cfg.xent_chunk_size > 0
    out, moe_aux = apply(
        params, tokens, cfg, rng=rng, deterministic=deterministic, return_aux=True,
        token_mask=batch.get("attention_mask") if cfg.n_experts > 0 else None,
        pld_theta=batch.get(PLD_THETA_KEY), return_hidden=chunked,
    )
    # one shared shift/mask derivation for both reductions: mask indexes
    # the *label* position (tokens[:, 1:]), not the query
    if "labels" in batch:
        labels, out_shift = batch["labels"], out
        mask = batch.get("attention_mask")
        mask = mask[:, : labels.shape[1]].astype(jnp.float32) if mask is not None else None
    else:
        labels, out_shift = tokens[:, 1:], out[:, :-1]
        mask = batch.get("attention_mask")
        mask = mask[:, 1 : 1 + labels.shape[1]].astype(jnp.float32) if mask is not None else None
    aux = cfg.moe_aux_weight * moe_aux if cfg.n_experts > 0 else 0.0

    if chunked:
        ones = jnp.ones(labels.shape, jnp.float32) if mask is None else mask
        return _chunked_xent(out_shift, params["wte"], labels, ones, cfg.xent_chunk_size) + aux

    nll = token_nll(out_shift, labels)
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0) + aux
    return jnp.mean(nll) + aux


def _stream_embed(cfg: GPT2Config, resident, tokens):
    """Streaming executor's stage 0: token+position embedding."""
    T = tokens.shape[1]
    x = jnp.take(resident["wte"], tokens, axis=0) + resident["wpe"][:T][None].astype(resident["wte"].dtype)
    return x


def _stream_group(cfg: GPT2Config, gblocks, x, rngs, deterministic):
    """Streaming executor's repeated stage: scan of ``_block`` over one
    GROUP of stacked layers (gblocks leaves lead with the group dim).
    Remat per block keeps the in-group activation footprint O(1)."""
    block_fn = functools.partial(_block, cfg)

    def body(carry, xs):
        lp, lr = xs
        r = lr if not deterministic else None
        y, _aux = block_fn(carry, lp, r, deterministic, None)
        return y, None

    if cfg.remat:
        if cfg.remat_save_names:
            policy = jax.checkpoint_policies.save_only_these_names(*cfg.remat_save_names)
        else:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
        body = jax.checkpoint(body, policy=policy, prevent_cse=False)
    x, _ = jax.lax.scan(body, x, (gblocks, rngs))
    return x


def _stream_head_loss(cfg: GPT2Config, resident, x, batch):
    """Streaming executor's final stage: final LN + tied head + xent
    (mirrors ``loss_fn``'s tail, chunked when configured)."""
    x = _layer_norm(x, resident["lnf_g"], resident["lnf_b"], cfg.layer_norm_epsilon)
    tokens = batch["input_ids"]
    if "labels" in batch:
        labels, x_shift = batch["labels"], x
        mask = batch.get("attention_mask")
        mask = mask[:, : labels.shape[1]].astype(jnp.float32) if mask is not None else None
    else:
        labels, x_shift = tokens[:, 1:], x[:, :-1]
        mask = batch.get("attention_mask")
        mask = mask[:, 1 : 1 + labels.shape[1]].astype(jnp.float32) if mask is not None else None
    if cfg.xent_chunk_size > 0:
        ones = jnp.ones(labels.shape, jnp.float32) if mask is None else mask
        return _chunked_xent(x_shift, resident["wte"], labels, ones, cfg.xent_chunk_size)
    logits = x_shift @ resident["wte"].T.astype(x_shift.dtype)
    nll = token_nll(logits, labels)
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def make_model(cfg: GPT2Config):
    """Returns (model_fn, init_fn, tp_spec_fn) — ``model_fn`` plugs
    straight into ``deepspeed_tpu.initialize(model=...)``.

    ``model_fn.stream_spec`` advertises the layer-streaming structure the
    ZeRO-Infinity param-offload executor needs (runtime/zero/
    param_offload.py): which params subtree is stacked per layer, and the
    embed / layer-group / head stage functions."""

    def model_fn(params, batch, rng):
        # rng=None ⇒ eval mode (engine passes None from eval_batch/predict)
        deterministic = rng is None or cfg.dropout == 0.0
        return loss_fn(params, batch, rng=rng, cfg=cfg, deterministic=deterministic)

    from deepspeed_tpu.runtime.zero.param_offload import StreamSpec

    model_fn.stream_spec = StreamSpec(
        n_layer=cfg.n_layer,
        blocks_key="blocks",
        embed=functools.partial(_stream_embed, cfg),
        group=functools.partial(_stream_group, cfg),
        head_loss=functools.partial(_stream_head_loss, cfg),
        deterministic=cfg.dropout == 0.0,
        # MoE experts need the expert mesh axis; ring/ulysses need the
        # seq axis — both incompatible with the data-only streaming
        # mesh.  flash and sparse are fine: both are single-device
        # kernels with host-side (numpy) layout prep only.
        supported=cfg.n_experts == 0 and cfg.attention_mode in ("flash", "sparse"),
    )
    return model_fn, functools.partial(init_params, cfg), tp_spec_fn
