"""Environment / ops report — the ``ds_report`` analog (reference
``env_report.py``).  Prints which ops lower to Pallas vs plain XLA vs
native C++, the device inventory, and asserts **zero CUDA ops** (the
north-star requirement): any op whose lowering would require CUDA is a
FAIL row.
"""
from __future__ import annotations

import os
import sys


GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
OKAY = f"{GREEN}[OKAY]{END}"
FAIL = f"{RED}[FAIL]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"


def op_report(verbose: bool = True) -> bool:
    from deepspeed_tpu.ops.registry import all_ops

    max_dots = 50
    print("-" * 64)
    print("deepspeed_tpu op lowering report")
    print("-" * 64)
    print("op name" + "." * (max_dots - len("op name")) + "lowering / status")
    print("-" * 64)
    ok = True
    cuda_ops = 0
    for name, spec in sorted(all_ops().items()):
        compatible = spec.is_compatible()
        ok = ok and compatible
        if spec.lowering == "cuda":
            cuda_ops += 1
        status = OKAY if compatible else FAIL
        print(f"{name}{'.' * (max_dots - len(name))}[{spec.lowering}] {status}")
    print("-" * 64)
    if cuda_ops:
        print(f"CUDA ops detected: {cuda_ops} {FAIL}")
        ok = False
    else:
        print(f"CUDA ops detected: 0 {OKAY}")
    return ok


def _compilation_cache_status() -> str:
    """Whether XLA's persistent compilation cache is on, and where.
    Checked the same way jax resolves it: config flag first, then the
    environment variable."""
    import jax

    cache_dir = None
    try:
        cache_dir = jax.config.jax_compilation_cache_dir
    except AttributeError:
        pass
    cache_dir = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        return "disabled"
    min_size = getattr(jax.config, "jax_persistent_cache_min_entry_size_bytes", None)
    detail = f", min entry size {min_size}B" if min_size else ""
    return f"enabled ({cache_dir}{detail})"


def debug_report() -> None:
    import jax

    print()
    print("DeepSpeed-TPU general environment info:")
    from deepspeed_tpu.version import __version__

    devices = jax.devices()
    rows = [
        ("deepspeed_tpu version", __version__),
        ("jax version", jax.__version__),
        ("default backend", jax.default_backend()),
        ("detected platform", devices[0].platform if devices else "none"),
        ("device count", jax.device_count()),
        ("local device count", jax.local_device_count()),
        ("process count", jax.process_count()),
        ("devices", ", ".join(str(d) for d in devices[:8]) + (" ..." if jax.device_count() > 8 else "")),
        ("compilation cache", _compilation_cache_status()),
    ]
    try:
        import jaxlib

        rows.insert(2, ("jaxlib version", jaxlib.__version__))
    except Exception:
        pass
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def resilience_report(config=None) -> None:
    """Resilience configuration summary rows (docs/resilience.md).
    ``config`` may be a DeepSpeedConfig, a ResilienceConfig, or None
    (prints the defaults a config-less run gets)."""
    from deepspeed_tpu.config.config import ResilienceConfig

    r = getattr(config, "resilience", config)
    if r is None:
        r = ResilienceConfig()
    ck, wd, rt, dv, sv = r.checkpoint, r.watchdog, r.retry, r.divergence, r.supervision
    print()
    print("resilience configuration:")
    rows = [
        (
            "atomic checkpoints",
            f"enabled (verify_on_load={'on' if ck.verify_on_load else 'off'}, checksum={ck.checksum})"
            if ck.atomic
            else f"{YELLOW}DISABLED{END} (non-atomic legacy writes)",
        ),
        (
            "retention policy",
            "keep all tags"
            if ck.keep_last_n <= 0
            else f"keep_last_n={ck.keep_last_n}"
            + (f", keep_every={ck.keep_every} steps" if ck.keep_every > 0 else ""),
        ),
        (
            "preemption watchdog",
            f"enabled (grace {wd.grace_seconds:g}s, exit code {wd.exit_code})"
            if wd.enabled
            else "disabled",
        ),
        (
            "retry policy",
            f"{rt.max_attempts} attempt(s), backoff {rt.backoff_seconds:g}s "
            f"(cap {rt.backoff_max_seconds:g}s"
            + (f", deadline {rt.timeout_seconds:g}s)" if rt.timeout_seconds else ")"),
        ),
        (
            "divergence guard",
            f"{dv.action} after {dv.threshold} skipped steps" if dv.enabled else "disabled",
        ),
        (
            "supervision",
            f"enabled ({sv.channel} channel, beat {sv.beat_interval_seconds:g}s)"
            if sv.enabled
            else "disabled (one dead rank hangs the collectives forever)",
        ),
        (
            "supervision deadlines",
            f"death after {sv.beat_timeout_seconds:g}s stale beat, hung sync after "
            f"{sv.sync_timeout_seconds:g}s; exit {sv.exit_code} = peer-failed-and-saved",
        ),
        (
            "elastic restarts",
            (lambda n: f"{n} (launcher --restarts, resumes from newest verified tag)"
             if n else "0 (launch with --restarts N to relaunch on exit 43/44)")(
                int(os.environ.get("DS_RESTARTS", "0") or 0)
            ),
        ),
    ]
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def overlap_report(config=None) -> None:
    """Overlap configuration summary rows (docs/performance.md).
    ``config`` may be a DeepSpeedConfig, an OverlapConfig, or None
    (prints the defaults a config-less run gets)."""
    from deepspeed_tpu.config.config import OverlapConfig

    o = getattr(config, "overlap", config)
    if o is None or not hasattr(o, "prefetch"):
        o = OverlapConfig()
    pf, ac, tl = o.prefetch, o.async_checkpoint, o.timeline
    print()
    print("overlap configuration:")
    rows = [
        (
            "input prefetch",
            f"enabled (depth {pf.depth}, pipelined load+place)"
            if pf.enabled
            else f"{YELLOW}DISABLED{END} (train step waits on host transfer)",
        ),
        (
            "async checkpointing",
            f"enabled (drain timeout {ac.drain_timeout_seconds:g}s)"
            if ac.enabled
            else "disabled (saves stall training for the full write)",
        ),
        (
            "step timeline",
            f"enabled (window {tl.window} steps: data_wait/compute/ckpt_stall/other)"
            if tl.enabled
            else "disabled",
        ),
    ]
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def sanitizer_report(config=None) -> None:
    """ds_san availability/overhead rows (docs/ds_san.md).  ``config``
    may be a DeepSpeedConfig, a SanitizerConfig, or None (defaults +
    the DS_SAN env switch a config-less run would see)."""
    import os
    import timeit

    from deepspeed_tpu.config.config import SanitizerConfig

    s = getattr(config, "sanitizer", config)
    if s is None or not hasattr(s, "checkers"):
        s = SanitizerConfig.from_env() if os.environ.get("DS_SAN") == "1" else SanitizerConfig()
    import jax

    has_guard = hasattr(jax, "transfer_guard")
    try:
        from jax.experimental import checkify  # noqa: F401

        has_checkify = True
    except ImportError:
        has_checkify = False
    # the only hot-path cost when armed: one signature per compiled call
    from deepspeed_tpu.analysis.sanitizer.recompile import signature

    tree = {"params": {f"l{i}": {"w": __import__("numpy").zeros((4, 4))} for i in range(32)}}
    sig_us = timeit.timeit(lambda: signature(tree), number=200) / 200 * 1e6
    print()
    print("sanitizer (ds_san) configuration:")
    rows = [
        (
            "ds_san",
            f"{GREEN}ENABLED{END} ({', '.join(s.checkers)})"
            if s.enabled
            else "disabled (opt in: DS_SAN=1 or the `sanitizer` config block)",
        ),
        ("compile budget", f"{s.compile_budget} compiles per call site"),
        ("sharding drift sweep", f"every {s.drift_interval} steps + after checkpoint load"),
        (
            "transfer guard support",
            f"jax.transfer_guard available {OKAY}" if has_guard else f"missing {FAIL}",
        ),
        (
            "nonfinite probe support",
            f"checkify available {OKAY}" if has_checkify else f"missing {WARNING}",
        ),
        ("armed overhead", f"~{sig_us:.0f}us signature per compile check (32-leaf state)"),
    ]
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def comm_report(config=None) -> None:
    """Comm-layer strategy table (docs/comm.md).  ``config`` may be a
    DeepSpeedConfig, a CommConfig, or None (defaults).  Prints the
    config knobs plus the policy table — which strategy a few
    representative fp32 tensor sizes get over an 8-rank dp grid — and
    the per-exchange wire bytes/param of each strategy."""
    from deepspeed_tpu.comm.strategy import (
        select_strategy,
        strategy_wire_bytes_per_param,
    )
    from deepspeed_tpu.config.config import CommConfig

    c = getattr(config, "comm", config)
    if c is None or not hasattr(c, "threshold_bytes"):
        c = CommConfig()
    print()
    print("comm layer configuration:")
    rows = [
        ("strategy (config)", c.strategy),
        ("threshold_bytes", f"{c.threshold_bytes} (below: always dense)"),
        ("quantize_bits", c.quantize_bits),
        ("error_feedback", "on" if c.error_feedback else "off"),
        ("stochastic_rounding", "on" if c.stochastic_rounding else "off"),
    ]
    import numpy as np

    for label, nbytes in (
        ("16 KB fp32 @ dp=8", 16 << 10),
        ("4 MB fp32 @ dp=8", 4 << 20),
        ("500 MB fp32 @ dp=8", 500 << 20),
    ):
        d = select_strategy(c, nbytes, np.float32, 8)
        rows.append((label, f"{d.strategy} ({d.reason})"))
    for s in ("dense", "int8", "onebit"):
        rows.append(
            (f"wire B/param ({s})", f"{strategy_wire_bytes_per_param(s):g}")
        )
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def sharding_report(config=None) -> None:
    """Partition-rule engine + mesh topology rows (docs/sharding.md):
    the family rule catalog, the derived mesh shape and its ICI×DCN
    factoring over the available devices, and the cross-replica
    weight-update sharding status with its ~dp× byte/FLOP model."""
    from deepspeed_tpu.config.config import MeshConfig, ZeroConfig
    from deepspeed_tpu.sharding.mesh import MESH_AXES, resolve_mesh_shape, _granules, split_dcn_ici
    from deepspeed_tpu.sharding.rules import family_catalog
    from deepspeed_tpu.sharding.update import weight_update_model

    mc = getattr(config, "mesh", None) or MeshConfig()
    zc = getattr(config, "zero_config", None) or ZeroConfig()
    print()
    print("sharding / partition-rule engine:")
    rows = [
        (
            "partition-rule families",
            ", ".join(f"{k} ({v} rules)" for k, v in family_catalog().items()),
        ),
    ]
    try:
        import jax

        devices = jax.devices()
        sizes = resolve_mesh_shape(mc, len(devices))
        rows.append(
            ("mesh shape", " × ".join(f"{ax}={sizes[ax]}" for ax in MESH_AXES if sizes[ax] > 1) or "1 device")
        )
        granules = _granules(devices)
        if granules is not None and len(granules) > 1:
            split = split_dcn_ici(sizes, len(granules))
            if split is not None:
                dcn, ici = split
                rows.append(
                    (
                        "topology",
                        f"{len(granules)} slices: dcn="
                        + "×".join(str(dcn[ax]) for ax in MESH_AXES)
                        + " ici=" + "×".join(str(ici[ax]) for ax in MESH_AXES),
                    )
                )
            else:
                rows.append(("topology", f"{len(granules)} granules (unfactorable — flat order)"))
        else:
            rows.append(("topology", "single slice (all-ICI)"))
        dp = sizes.get("data", 1) * sizes.get("fsdp", 1)
    except Exception as e:  # no devices / bad mesh config: still report
        rows.append(("mesh shape", f"unavailable ({e})"))
        dp = 1
    cross = zc.stage >= 1 and getattr(zc, "cross_replica_weight_update", True)
    rows.append(
        (
            "weight-update sharding",
            (
                f"cross-replica (default ZeRO-1): ~{dp}x less update FLOPs + "
                f"opt-state bytes/replica, one params all-gather/step"
                if cross and dp > 1
                else ("off (zero_optimization.cross_replica_weight_update=false)"
                      if zc.stage >= 1 else "n/a (zero stage 0)")
            ),
        )
    )
    if dp > 1:
        m = weight_update_model(125_000_000, dp)
        rows.append(
            (
                "byte model @125M params",
                f"{m['opt_state_bytes_per_replica'] / 1e6:.0f} MB opt state/replica "
                f"(vs {weight_update_model(125_000_000, dp, sharded=False)['opt_state_bytes_per_replica'] / 1e6:.0f} replicated), "
                f"{m['update_allgather_bytes'] / 1e6:.0f} MB all-gather/step",
            )
        )
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def serving_report(config=None) -> None:
    """Serving-layer summary rows (docs/serving.md).  ``config`` may be
    a DeepSpeedConfig, a ServingConfig, or None (defaults).  Prints the
    slot-pool sizing knobs, the KV dtype, the scheduler policy knobs and
    the per-slot cache-byte formula (model dims are engine-time
    knowledge, so the formula is shown with the knobs filled in)."""
    from deepspeed_tpu.config.config import ServingConfig

    s = getattr(config, "serving", config)
    if s is None or not hasattr(s, "num_slots"):
        s = ServingConfig()
    print()
    print("serving configuration:")
    max_len = s.max_len if s.max_len else "derived (engine capacity // chunk * chunk)"
    rows = [
        ("slot pool", f"{s.num_slots} slots x {max_len} positions"),
        (
            "kv cache dtype",
            "int8 (codes + f32 scales, ~2x less HBM/slot)"
            if s.kv_cache_dtype == "int8"
            else "model (engine dtype; int8 if the engine's kv cache is)",
        ),
        (
            "pool bytes/slot",
            "2 x layers x heads x max_len x head_dim x itemsize"
            + (" x ~0.53 (int8+scales)" if s.kv_cache_dtype == "int8" else ""),
        ),
        (
            "chunked prefill",
            f"{s.prefill_chunk} tokens/chunk, "
            f"{s.prefill_chunks_per_step} chunk(s) interleaved per decode step",
        ),
        (
            "admission",
            f"max_queue={s.max_queue} (submit() rejects past it), "
            + (
                f"queue-wait deadline {s.deadline_seconds:g}s"
                if s.deadline_seconds
                else "no queue-wait deadline"
            ),
        ),
        ("default generation budget", f"{s.max_new_tokens} tokens/request"),
        # resilience rows (docs/serving.md §Resilience)
        (
            "overload shedding",
            f"estimated-TTFT test vs slo_ttft_ms={s.slo_ttft_ms:g} "
            "(priority 0 bypasses; sheds carry retry_after)"
            if s.slo_ttft_ms
            else "off (slo_ttft_ms=0; hard max_queue bound only)",
        ),
        (
            "degradation ladder",
            f"engage >= {s.degrade_queue_watermark:g}x max_queue for "
            f"{s.degrade_engage_steps} ticks, disengage after "
            f"{s.degrade_disengage_steps}; rungs: clamp max_new_tokens"
            + (f"->{s.degrade_max_new_tokens}" if s.degrade_max_new_tokens else "(off)")
            + " | 1 prefill chunk/step | shed low priority",
        ),
        (
            "graceful drain",
            f"SIGTERM -> stop admission, drain <= {s.drain_deadline_seconds:g}s, "
            "journal commit, exit 43",
        ),
        (
            "request journal",
            f"{s.journal_dir} ({s.journal_segment_records} records/segment, "
            f"compact past {s.journal_keep_segments} segments)"
            if s.journal_dir
            else "off (journal_dir unset; a crash loses queued+in-flight work)",
        ),
    ]
    # paged KV rows (docs/serving.md §Paged KV & prefix caching)
    kv = getattr(s, "kvcache", None)
    if kv is not None:
        if not kv.enabled:
            rows.append((
                "paged kv cache",
                "off (serving.kvcache.enabled=false; slot-contiguous pool)",
            ))
        else:
            rows += [
                (
                    "paged kv cache",
                    f"on: {kv.page_len}-token pages, "
                    + (f"{kv.num_pages} pages"
                       if kv.num_pages
                       else "pages derived (garbage + 2x slot capacity)")
                    + "; shared prefixes dedup via radix index + COW tails",
                ),
                (
                    "pinned prefixes",
                    f"{len(kv.pinned_prefixes)} pinned "
                    f"({sum(len(p) for p in kv.pinned_prefixes)} tokens, never evicted)"
                    if kv.pinned_prefixes
                    else "none (prefixes learned from traffic, LRU-evicted)",
                ),
                (
                    "session kv reuse",
                    (f"warm park, ttl {kv.session_ttl_seconds:g}s"
                     if kv.session_ttl_seconds else "warm park, no ttl")
                    + (f"; cold spill -> {kv.spill_dir} (manifest-gated, "
                       "recover() re-pins)"
                       if kv.spill_dir else "; no spill dir (cold sessions drop)"),
                ),
            ]
            # KV tiering rows (docs/serving.md §KV tiering)
            t = getattr(kv, "tiers", None)
            if t is not None and t.enabled:
                rows.append((
                    "kv tiering",
                    f"on: T1 host <= {t.host_pages} pages"
                    + (f", T2 disk -> {t.disk_dir}" if t.disk_dir
                       else ", no T2 (host-only)")
                    + f"; demote past {t.demote_watermark:g} pool watermark"
                    + (f", tail residency {t.residency_window} tokens"
                       if t.residency_window else "")
                    + f", prefetch {t.prefetch_ahead} hint(s)/step",
                ))
                rows += _kv_tier_rows()
            elif t is not None:
                rows.append((
                    "kv tiering",
                    "off (serving.kvcache.tiers.enabled=false; "
                    "parked sessions stay in HBM until spill/drop)",
                ))
    # fleet front-door rows (docs/serving.md §Fleet)
    f = getattr(s, "fleet", None)
    if f is not None:
        rows += [
            (
                "fleet router",
                f"{f.replicas} replica(s), least-estimated-TTFT placement, "
                f"{f.route_retries} failover retr"
                + ("y" if f.route_retries == 1 else "ies")
                + " per submit",
            ),
            (
                "fleet breaker",
                f"trip at {f.breaker_failures} consecutive failures, "
                f"backoff {f.breaker_backoff_seconds:g}s.."
                f"{f.breaker_backoff_max_seconds:g}s, "
                f"{f.breaker_halfopen_probes} half-open probe(s)",
            ),
            (
                "fleet hedging",
                f"duplicate after {f.hedge_factor:g}x p99 TTFT "
                f"(armed past {f.hedge_min_observations} samples; "
                "first token wins, loser cancelled)"
                if f.hedge
                else "off (hedge=false; per-request opt-in via submit)",
            ),
            (
                "fleet restart",
                f"supervised, <= {f.max_restarts} restart(s)/replica, "
                f"{f.restart_backoff_seconds:g}s backoff"
                + (f", budget decays 1/{f.restart_budget_reset_seconds:g}s "
                   "clean service"
                   if f.restart_budget_reset_seconds else "")
                + "; journal replay re-binds in-flight ids (lossless)",
            ),
        ]
        # elastic fleet rows (docs/serving.md §Elastic fleet)
        e = getattr(f, "elastic", None)
        if e is not None and e.enabled:
            rows += [
                (
                    "fleet autoscaler",
                    f"{e.min_replicas}..{e.max_replicas} replicas; up at "
                    f"queue>{e.scale_up_queue_depth} or "
                    f"ttft>{e.scale_up_ttft_seconds:g}s "
                    f"x{e.engage_ticks} ticks (cooldown "
                    f"{e.scale_up_cooldown_seconds:g}s), down at "
                    f"queue<={e.scale_down_queue_depth} "
                    f"x{e.disengage_ticks} ticks (cooldown "
                    f"{e.scale_down_cooldown_seconds:g}s)",
                ),
                (
                    "fleet warm pool",
                    f"{e.warm_pool_size} pre-built replica(s) "
                    "(compiled off the routing thread)"
                    if e.warm_pool_size
                    else "off (scale-up builds inline)",
                ),
                (
                    "fleet migration",
                    f"live KV session migration on drain (spill wire "
                    f"format, manifest-gated); <= {e.migration_retries} "
                    f"retr{'y' if e.migration_retries == 1 else 'ies'}, "
                    f"{e.migration_deadline_seconds:g}s drain deadline "
                    "(in-flight past it aborts the scale-down)",
                ),
            ]
        elif e is not None:
            rows.append((
                "fleet autoscaler",
                "off (serving.fleet.elastic.enabled=false; fixed replica "
                "count)",
            ))
    # front-door rows (docs/serving.md §Front-door)
    fd = getattr(s, "frontdoor", None)
    if fd is not None:
        rows.append((
            "http front-door",
            f"on: {fd.host}:{fd.port or 'ephemeral'}, chunked streaming "
            f"(poll {fd.stream_poll_seconds:g}s), 429/503 + Retry-After, "
            "SIGTERM drain -> stream-out -> exit 43"
            if fd.enabled
            else "off (serving.frontdoor.enabled=false; rpc/in-process "
            "submit only)",
        ))
    tn = getattr(s, "tenants", None)
    if tn is not None:
        if not tn.enabled:
            rows.append((
                "tenants",
                "off (serving.tenants.enabled=false; single-tenant "
                "admission)",
            ))
        else:
            bucket = (
                f"{tn.refill_tokens_per_second:g} tok/s burst "
                f"{tn.burst_tokens:g}"
                if tn.refill_tokens_per_second or tn.burst_tokens
                else "unlimited (accounting/WFQ only)"
            )
            rows += [
                (
                    "tenants",
                    f"on: default bucket {bucket}, weight {tn.weight:g}, "
                    f"slo {tn.slo_class}; {len(tn.overrides)} override(s) "
                    f"({', '.join(sorted(tn.overrides)) or 'none'})",
                ),
                (
                    "tenant kv quotas",
                    (f"kv_pages_max={tn.kv_pages_max}"
                     if tn.kv_pages_max else "pages uncapped")
                    + ", "
                    + (f"pinned_prefixes_max={tn.pinned_prefixes_max}"
                       if tn.pinned_prefixes_max else "pins uncapped")
                    + " (over-quota allocs defer, over-quota pins degrade)",
                ),
            ]
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def autoscaler_report(autoscaler) -> None:
    """LIVE autoscaler rows (ds_report with a running fleet, bench
    tools): current phase, warm pool, last scale events, migrations."""
    s = autoscaler.stats()
    wp = s["warm_pool"]
    rows = [
        ("autoscaler replicas",
         f"{s['replicas']} (bounds {s['min_replicas']}..{s['max_replicas']})"),
        ("autoscaler phase",
         s["phase"] + (f" (victim {s['victim']})" if s["victim"] else "")
         + f"; hot {s['hot_ticks']} cold {s['cold_ticks']} of "
         f"{s['ticks']} ticks"),
        ("warm pool",
         f"{wp['ready']}/{wp['size']} ready ({wp['built']} built, "
         f"{wp['build_failures']} failed)"),
        ("scale events",
         f"{s['scale_ups']} up / {s['scale_downs']} down "
         f"({s['scale_downs_aborted']} aborted)"),
        ("scale reactions",
         "up "
         + (f"{s['last_scale_up_reaction_s']:.3f}s"
            if s["last_scale_up_reaction_s"] is not None else "n/a")
         + ", down "
         + (f"{s['last_scale_down_reaction_s']:.3f}s"
            if s["last_scale_down_reaction_s"] is not None else "n/a")),
        ("migrations",
         f"{s['migrations_completed']} completed / "
         f"{s['migrations_failed']} failed "
         f"({s['sessions_migrated']} session(s) moved)"),
    ]
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def telemetry_report(config=None) -> None:
    """Telemetry-plane rows (docs/telemetry.md): enabled sinks and
    cadence from the config, plus the LIVE process plane (registry
    size, last export age, trace state) when one is armed."""
    from deepspeed_tpu import telemetry as tel
    from deepspeed_tpu.config.config import TelemetryConfig

    t = getattr(config, "telemetry", config)
    if t is None or not hasattr(t, "exporters"):
        t = TelemetryConfig()
    live = tel.status()
    print()
    print("telemetry configuration:")
    age = live["last_export_age_seconds"]
    rows = [
        (
            "metrics registry",
            f"enabled (ring {t.ring} samples/metric)"
            if t.enabled
            else "disabled (zero-overhead: no publishes anywhere)",
        ),
        (
            "exporters",
            ", ".join(t.exporters) + f" every {t.export_interval_seconds:g}s"
            if t.exporters
            else "none configured (jsonl | prometheus | tensorboard)",
        ),
        (
            "trace (Perfetto)",
            f"enabled ({t.trace_buffer_events} event ring -> "
            f"{t.trace_path or '<output_path>/trace.json'})"
            if t.trace
            else "disabled",
        ),
        (
            "cross-rank aggregation",
            "piggybacks on supervision beats (min/mean/max + dead-rank flags)"
            if t.aggregate and t.enabled
            else "off",
        ),
        (
            "live registry",
            f"{live['registry_size']} metric(s), rank {live['rank']}"
            if live["enabled"]
            else "not armed in this process",
        ),
        (
            "last export",
            # exports==0 means "never", full stop — a loop that has not
            # flushed yet must not print a bogus epoch-sized age
            "never"
            if live["sinks"] and (age is None or not live["exports"])
            else (f"{age:.1f}s ago ({live['exports']} total)" if age is not None
                  else "n/a (no sinks armed)"),
        ),
        (
            "profiler capture",
            f"dir {t.profiler_dir}, {t.profiler_capture_ms}ms window"
            + (f", on TTFT > {t.slo_ttft_breach_ms:g}ms" if t.slo_ttft_breach_ms else " (on-demand)")
            if t.profiler_dir
            else "off (set telemetry.profiler_dir)",
        ),
        (
            "anomaly watch",
            f"step-wall spike > {t.spike_factor:g}x window mean "
            f"(>= {t.spike_min_window} samples); straggler > "
            f"{t.straggler_factor:g}x cluster median"
            if t.enabled else "off (telemetry disabled)",
        ),
    ]
    rows += _attribution_rows(t)
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def _kv_tier_rows() -> list:
    """LIVE tier-state rows from the ``kvcache/tier/*`` gauges an armed
    engine publishes each step (per-tier page counts/bytes, hit rates,
    in-flight migrations, last swap-hide ratio).  Empty before the
    first step — the config row above already says tiering is on."""
    from deepspeed_tpu import telemetry as tel

    g = {}
    for m in tel.get_registry().metrics():
        if m.name.startswith("kvcache/tier/") and m.kind == "gauge" \
                and m.value is not None:
            g[m.name[len("kvcache/tier/"):]] = m.value
    if not g:
        return []
    hits = g.get("hits_t1", 0) + g.get("hits_t2", 0)
    probes = hits + g.get("tier_misses", 0)
    return [
        (
            "kv tier residency",
            f"T1 {g.get('host_entries', 0):.0f} entr(ies) / "
            f"{g.get('host_pages', 0):.0f} page(s) / "
            f"{g.get('host_bytes', 0) / 2**20:.1f} MB, "
            f"T2 {g.get('disk_entries', 0):.0f} entr(ies) / "
            f"{g.get('disk_pages', 0):.0f} page(s)",
        ),
        (
            "kv tier traffic",
            f"demote {g.get('demote_t0_t1', 0):.0f}v {g.get('demote_t1_t2', 0):.0f}d, "
            f"promote {g.get('promote_t1_t0', 0) + g.get('promote_t2_t0', 0):.0f}^ "
            f"({g.get('promote_t2_t1', 0):.0f} prefetched), "
            f"hit rate {hits / probes:.0%} over {probes:.0f} probe(s), "
            f"{g.get('inflight', 0):.0f} migration(s) in flight"
            if probes else
            f"demote {g.get('demote_t0_t1', 0):.0f}v {g.get('demote_t1_t2', 0):.0f}d, "
            f"no promotion probes yet, "
            f"{g.get('inflight', 0):.0f} migration(s) in flight",
        ),
        (
            "kv swap hiding",
            f"{g.get('swap_hidden_ratio', 1.0):.0%} of "
            f"{g.get('swap_seconds_total', 0.0):.2f}s swap IO hidden "
            "beneath serving steps",
        ),
    ]


def _attribution_rows(t) -> list:
    """Per-kernel attribution summary (docs/telemetry.md §Attribution):
    the top-3 buckets by roofline time share from the LIVE registry's
    ``attribution/*`` gauges, when a compiled step has published them."""
    if not t.attribution:
        return [("attribution", "off (telemetry.attribution=false)")]
    from deepspeed_tpu import telemetry as tel

    reg = tel.get_registry()
    shares = []
    for m in reg.metrics():
        if m.name == "attribution/time_share_pct" and m.kind == "gauge" \
                and m.value is not None:
            shares.append((m.labels.get("bucket", "?"),
                           m.labels.get("engine", "?"), m.value))
    if not shares:
        return [("attribution", "armed (no compiled step has published yet)")]
    shares.sort(key=lambda s: -s[2])
    top = ", ".join(f"{b} {v:.0f}% [{e}]" for b, e, v in shares[:3])
    return [("attribution top-3", top)]


def kernels_report(config=None) -> None:
    """Pallas kernel-suite rows (docs/kernels.md): which kernels are
    armed for this process/backend and the block autotuner cache state
    (mode / path / entry count / LRU hits)."""
    from deepspeed_tpu.ops import kernels as k

    c = getattr(config, "kernels", None)
    if c is not None:
        k.configure_from_config(c)
    rep = k.kernels_report()
    at = rep["autotune"]
    print()
    print("pallas kernel suite:")
    rows = [
        ("suite armed", f"{'yes' if rep['suite_armed'] else 'no'} (DS_KERNELS={rep['env']})"),
        ("flash_decode kernel", "armed" if rep["flash_decode"] else "off"),
        ("fused_update kernel", "armed" if rep["fused_update"] else "off"),
        ("autotune mode", at["mode"]),
        ("autotune cache", at["path"] + ("" if at["cache_ok"] else " [CORRUPT -> defaults]")),
        ("autotune entries", f"{at['entries']} on disk, {at['lru']} in LRU"),
        ("autotune hits/misses", f"{at['hits']}/{at['misses']} ({at['tunes']} tuned this process)"),
    ]
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def bench_history_report() -> None:
    """Bench trajectory rows: last run's sha + rung count from
    BENCH.json, history depth and the current regression-gate status
    from ``bench_history.jsonl`` (docs/performance.md §Regression
    workflow)."""
    import json

    from deepspeed_tpu.telemetry import regression as reg

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # honors the DS_BENCH_HISTORY_PATH override, like every writer
    hist_path = reg.default_history_path(root)
    bench_path = os.path.join(root, "BENCH.json")
    print()
    print("bench history / perf sentinel:")
    rows = []
    if os.path.exists(bench_path):
        try:
            with open(bench_path) as f:
                doc = json.load(f)
            rungs = doc.get("rungs", {})
            measured = sum(1 for r in rungs.values() if not r.get("skipped"))
            rows.append((
                "last bench run",
                f"sha {doc.get('git_sha', '?')}, {measured}/{len(rungs)} rung(s) "
                f"measured{'' if doc.get('complete') else ' (INCOMPLETE)'}",
            ))
        except (OSError, ValueError) as e:
            rows.append(("last bench run", f"BENCH.json unreadable ({e})"))
    else:
        rows.append(("last bench run", "no BENCH.json yet (run bench.py)"))
    history = reg.history_load(hist_path)
    bench_lines = [h for h in history if h.get("kind") == "bench"]
    if not bench_lines:
        rows.append(("bench history", "empty (bench runs append bench_history.jsonl)"))
    else:
        runs = len({h.get("run_id") for h in bench_lines})
        rows.append((
            "bench history",
            f"{len(bench_lines)} record(s) over {runs} run(s), "
            f"{len({h.get('metric') for h in bench_lines})} metric(s)",
        ))
        ok, bad = reg.gate(reg.bench_diff(history))
        # the band is named so a divergence from a CI gate run with
        # per-metric overrides reads as a settings difference, not a bug
        rows.append((
            "regression gate",
            f"{GREEN}GREEN{END} (default 5% band)" if ok
            else f"{RED}RED{END} at the default 5% band ({len(bad)} regressing: "
                 + ", ".join(v["metric"] for v in bad[:3]) + ")",
        ))
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def analysis_report() -> None:
    """Static-analysis suite rows: per-tool rule counts, checked-in
    baseline sizes, and a live ds_race self-run (cheap — AST-only, no
    jax import) so drift from the baseline shows up in the report
    (docs/ds_lint.md / docs/ds_san.md / docs/ds_race.md)."""
    import json
    import time

    from deepspeed_tpu.analysis.baseline import BASELINE_NAME
    from deepspeed_tpu.analysis.core import Severity, all_rules
    from deepspeed_tpu.analysis.race import (
        RACE_BASELINE_NAME, all_race_rules, race_paths,
    )
    from deepspeed_tpu.analysis.race.stress import all_scenarios
    from deepspeed_tpu.analysis.sanitizer.cli import SAN_BASELINE_NAME
    from deepspeed_tpu.analysis.shard.rules import all_shard_rules
    from deepspeed_tpu.analysis.shard.runner import (
        SHARD_BASELINE_NAME, read_run_status,
    )

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def baseline_size(name: str) -> str:
        path = os.path.join(root, name)
        if not os.path.exists(path):
            return "no baseline"
        try:
            with open(path) as f:
                return f"{len(json.load(f)['findings'])} grandfathered"
        except (OSError, ValueError, KeyError) as e:
            return f"baseline unreadable ({e})"

    def tiers(rules) -> str:
        counts = {t: sum(1 for r in rules.values() if r.tier == t)
                  for t in (Severity.A, Severity.B, Severity.C)}
        return "/".join(f"{counts[t]}{t.name}" for t in (Severity.A, Severity.B, Severity.C))

    lint_rules, race_rules = all_rules(), all_race_rules()
    print()
    print("analysis suite:")
    rows = [
        ("ds_lint", f"{len(lint_rules)} rule(s) ({tiers(lint_rules)}), "
                    f"{baseline_size(BASELINE_NAME)}"),
        ("ds_san", f"runtime checkers (see sanitizer section), "
                   f"{baseline_size(SAN_BASELINE_NAME)}"),
        ("ds_race", f"{len(race_rules)} rule(s) ({tiers(race_rules)}) + "
                    f"{len(all_scenarios())} stress scenario(s), "
                    f"{baseline_size(RACE_BASELINE_NAME)}"),
    ]
    shard_rules = all_shard_rules()
    rows.append(("ds_shard", f"{len(shard_rules)} rule(s) ({tiers(shard_rules)}), "
                             f"{baseline_size(SHARD_BASELINE_NAME)}"))
    t0 = time.monotonic()
    try:
        res = race_paths([os.path.join(root, "deepspeed_tpu")])
        new = len(res.findings) + len(res.parse_errors)
        status = (f"{GREEN}GREEN{END}" if new == 0
                  else f"{RED}RED{END} ({new} unbaselined finding(s))")
        rows.append(("ds_race self-run",
                     f"{status} over {res.files} file(s) in "
                     f"{time.monotonic() - t0:.1f}s"))
    except Exception as e:  # noqa: BLE001 — a report must not crash the report
        rows.append(("ds_race self-run", f"{RED}failed{END}: {e!r}"))
    # ds_shard compiles every engine, far too heavy for a report — show
    # the persisted verdict of the last real run instead
    status = read_run_status(root)
    if status is None:
        rows.append(("ds_shard self-run",
                     "no run recorded (bin/ds_shard to refresh)"))
    else:
        verdict = status.get("verdict", "?")
        color = GREEN if verdict == "GREEN" else RED
        rows.append((
            "ds_shard self-run",
            f"{color}{verdict}{END} over {len(status.get('sites', []))} "
            f"site(s), {status.get('new_tier_a', '?')} new tier-A, "
            f"{len(status.get('skips', []))} skip(s) at "
            f"{status.get('timestamp', '?')}",
        ))
    for name, value in rows:
        print(f"{name} " + "." * (30 - len(name)) + f" {value}")


def cli_main() -> int:
    ok = op_report()
    debug_report()
    resilience_report()
    overlap_report()
    sanitizer_report()
    comm_report()
    sharding_report()
    serving_report()
    telemetry_report()
    kernels_report()
    analysis_report()
    bench_history_report()
    return 0 if ok else 1


def main():
    sys.exit(cli_main())


if __name__ == "__main__":
    main()
