"""Activation checkpointing (rematerialization).

TPU-native analog of the reference's
``runtime/activation_checkpointing/checkpointing.py`` (``checkpoint()``
:677, ``CheckpointFunction`` :351, ``configure()`` :759, RNG tracking
``CudaRNGStatesTracker`` :122).

The reference hand-rolls recompute-in-backward with torch autograd
Functions, explicit RNG state save/restore, activation *partitioning*
across model-parallel ranks, and optional CPU placement of the saved
inputs.  Under XLA each of those is a policy handed to ``jax.checkpoint``:

* recompute-with-same-randomness is automatic — JAX threads the PRNG key
  functionally, so the recomputed forward sees identical randomness with
  no state juggling;
* ``partition_activations`` → saved residuals kept sharded over the
  ``model``/``seq`` axes (they already are under GSPMD; the knob adds a
  sharding constraint on the carried inputs);
* ``cpu_checkpointing`` → ``jax.checkpoint`` offload policy
  (``save_and_offload_only_these_names`` / host offload of residuals);
* ``contiguous_memory_optimization`` → no-op (XLA's allocator already
  packs buffers; kept for config compatibility).

``checkpoint(fn, *args)`` keeps the reference's call signature so ported
Megatron-style models run unchanged.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax

from deepspeed_tpu.config.config import ActivationCheckpointingConfig
from deepspeed_tpu.utils.logging import log_dist

_CONFIG = ActivationCheckpointingConfig()
_NUM_LAYERS: Optional[int] = None


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None) -> None:
    """Reference ``configure()`` (checkpointing.py:759): set module-level
    checkpointing behavior, either from a DeepSpeedConfig or explicit args."""
    global _CONFIG, _NUM_LAYERS
    if deepspeed_config is not None:
        cfg = getattr(deepspeed_config, "activation_checkpointing", None)
        if cfg is not None:
            _CONFIG = cfg
    import dataclasses

    updates = {}
    if partition_activations is not None:
        updates["partition_activations"] = partition_activations
    if contiguous_checkpointing is not None:
        updates["contiguous_memory_optimization"] = contiguous_checkpointing
    if checkpoint_in_cpu is not None:
        updates["cpu_checkpointing"] = checkpoint_in_cpu
    if synchronize is not None:
        updates["synchronize_checkpoint_boundary"] = synchronize
    if profile is not None:
        updates["profile"] = profile
    if num_checkpoints is not None:
        _NUM_LAYERS = num_checkpoints
        updates["number_checkpoints"] = num_checkpoints
    if updates:
        _CONFIG = dataclasses.replace(_CONFIG, **updates)
    log_dist(
        f"activation checkpointing configured: partition={_CONFIG.partition_activations} "
        f"cpu={_CONFIG.cpu_checkpointing}"
    )


def is_configured() -> bool:
    return _CONFIG is not None


def get_config() -> ActivationCheckpointingConfig:
    return _CONFIG


# ---------------------------------------------------------------------------
# policies
# ---------------------------------------------------------------------------

def _policy_for(config: ActivationCheckpointingConfig):
    """Map config knobs to a jax.checkpoint policy.

    * default — save nothing, recompute everything (the reference's
      behavior: only layer inputs survive; everything inside recomputes).
    * cpu_checkpointing — additionally offload what *is* saved to host
      memory (the reference's PA_TO_CPU path, checkpointing.py:689).
    """
    cp = jax.checkpoint_policies
    if config.cpu_checkpointing and hasattr(cp, "offload_dot_with_no_batch_dims"):
        return cp.offload_dot_with_no_batch_dims("device", "pinned_host")
    return None  # = save nothing


def checkpoint(function: Callable, *args, **kwargs):
    """Checkpoint a forward: ``checkpoint(fn, *args)`` runs ``fn(*args)``
    now and recomputes it during backward (reference ``checkpoint()``,
    checkpointing.py:677).  Randomness inside ``fn`` must flow through an
    explicit PRNG key argument — then recompute reuses it exactly."""
    fn = jax.checkpoint(function, policy=_policy_for(_CONFIG))
    return fn(*args, **kwargs)


def checkpoint_wrapper(function: Callable, config: Optional[ActivationCheckpointingConfig] = None) -> Callable:
    """Decorator form: returns a rematerialized version of ``function``."""
    cfg = config if config is not None else _CONFIG
    return jax.checkpoint(function, policy=_policy_for(cfg))


def checkpoint_sequential(apply_block: Callable, params_stacked: Any, x: Any,
                          rng=None, every: int = 1) -> Any:
    """Scan ``apply_block`` over stacked per-layer params with remat every
    ``every`` layers (the reference's Megatron usage: chunked
    ``checkpoint(custom(l, l+chunk), hidden)``)."""
    blk = jax.checkpoint(apply_block, policy=_policy_for(_CONFIG))

    if every <= 1:
        def body(carry, p):
            h, r = carry
            r2 = None if r is None else jax.random.fold_in(r, 1)
            return (blk(p, h, r), r2), None

        (x, _), _ = jax.lax.scan(body, (x, rng), params_stacked)
        return x

    # group layers into chunks of `every`, remat at chunk granularity
    leaves = jax.tree.leaves(params_stacked)
    L = leaves[0].shape[0]
    assert L % every == 0, f"{L} layers not divisible by checkpoint interval {every}"
    grouped = jax.tree.map(lambda l: l.reshape((L // every, every) + l.shape[1:]), params_stacked)

    def chunk_fn(pchunk, h, r):
        def body(carry, p):
            hh, rr = carry
            r2 = None if rr is None else jax.random.fold_in(rr, 1)
            return (apply_block(p, hh, rr), r2), None

        (h, _), _ = jax.lax.scan(body, (h, r), pchunk)
        return h

    chunk_fn = jax.checkpoint(chunk_fn, policy=_policy_for(_CONFIG))

    def outer(carry, pchunk):
        h, r = carry
        r2 = None if r is None else jax.random.fold_in(r, 1)
        return (chunk_fn(pchunk, h, r), r2), None

    (x, _), _ = jax.lax.scan(outer, (x, rng), grouped)
    return x


# Reference-parity RNG helpers (checkpointing.py:122-238).  In JAX the
# "tracker" is just named fold_in streams on an explicit key.
class CudaRNGStatesTracker:
    """API-compatible shim: named RNG streams over functional keys."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, seed: int):
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def _fork():
            key = self.states_[name]
            self.states_[name], _ = jax.random.split(key)
            yield
        return _fork()


_CUDA_RNG_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker() -> CudaRNGStatesTracker:
    return _CUDA_RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int) -> None:
    """Reference checkpointing.py:272: seed the model-parallel stream."""
    _CUDA_RNG_TRACKER.reset()
    _CUDA_RNG_TRACKER.add("model-parallel-rng", seed + 2718)
