"""Progressive Layer Drop (PLD).

Reference: ``runtime/progressive_layer_drop.py`` (``ProgressiveLayerDrop``
:5) — the theta schedule from "Accelerating Training of Transformer-Based
Language Models with Progressive Layer Dropping" (Zhang & He, 2020):
``theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar``, so early
steps keep almost every layer and the keep-probability anneals down to
``theta_bar``.  The engine hooks it at forward (theta into the model) and
step (advance t) — reference ``engine.py:1101`` / ``:1343``.

TPU-native integration: theta must be a *traced* value (it changes every
step inside the compiled train step), so the engine computes
``theta(global_step)`` in-graph and injects it into the batch dict as
``PLD_THETA_KEY``; models that support PLD (models/gpt2.py) pop it and
apply per-layer stochastic depth inside their ``lax.scan``: layer l of L
is kept with probability ``1 - (l+1)/L * (1 - theta)`` (deeper layers
drop more, matching the paper's progressive schedule along depth).
"""
from __future__ import annotations

import jax.numpy as jnp

PLD_THETA_KEY = "__pld_theta__"


class ProgressiveLayerDrop:
    """Reference signature: ``ProgressiveLayerDrop(theta, gamma)``."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self, global_step) -> jnp.ndarray:
        """Traced schedule — safe to call inside jit."""
        t = jnp.asarray(global_step, jnp.float32)
        return (1.0 - self.theta) * jnp.exp(-self.gamma * t) + self.theta

    def update_state(self, global_step: int) -> None:
        # closed-form host math — no device dispatch on the hot path
        import math

        self.current_theta = (1.0 - self.theta) * math.exp(-self.gamma * int(global_step)) + self.theta

    def get_state(self) -> dict:
        return {"progressive_layer_drop": True, "pld_theta": self.current_theta}


def layer_keep_probs(theta, n_layers: int) -> jnp.ndarray:
    """Per-layer keep probability: p_l = 1 - (l+1)/L * (1 - theta)."""
    depth_frac = (jnp.arange(n_layers, dtype=jnp.float32) + 1.0) / n_layers
    return 1.0 - depth_frac * (1.0 - jnp.asarray(theta, jnp.float32))
