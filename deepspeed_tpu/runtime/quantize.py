"""MoQ — Mixture-of-Quantization progressive quantize-training.

Reference: ``runtime/quantize.py`` (``Quantizer`` :12), driven from
``engine._take_model_step`` (:1284-1290): weights are fake-quantized
in place with a bit-width that anneals from ``quantize_bits_start`` to
``quantize_bits_target`` past ``quantize_schedule_offset`` steps,
optionally gated by the Hessian-eigenvalue flatness signal
(``runtime/eigenvalue.py``; engine.step :1334-1341).

TPU-native form: the quantize-dequantize pass is one jitted tree-map
over matmul weights using the grouped quantizer op (``ops/quantizer``),
applied by the engine right after the optimizer update at the
grad-accumulation boundary — params stay a pure pytree; there is no
in-place mutation, just the next state's params.
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import QuantizeTrainingConfig
from deepspeed_tpu.ops.quantizer.quantizer import quantize as grouped_qdq
from deepspeed_tpu.utils.logging import log_dist, logger


class Quantizer:
    """Progressive-precision weight quantizer (reference ``Quantizer`` :12)."""

    def __init__(self, config: QuantizeTrainingConfig):
        self.cfg = config
        self.q_period = max(1, int(config.quantize_schedule_offset))
        self._log_bits = None

    # -- schedule ----------------------------------------------------------
    def current_bits(self, global_step) -> jnp.ndarray:
        """Traced bit-width schedule: hold ``start`` bits until the
        offset, then step down one bit per period until ``target``."""
        start, target = self.cfg.quantize_bits_start, self.cfg.quantize_bits_target
        step = jnp.asarray(global_step, jnp.int32)
        periods = jnp.maximum(0, (step - self.q_period) // self.q_period + 1)
        bits = jnp.maximum(target, start - periods)
        return bits.astype(jnp.int32)

    def scale_period_by_eigenvalue(self, eigenvalue: float, max_eigenvalue: float) -> None:
        """Eigenvalue gate (reference engine.step :1334-1341): sharp layers
        (large curvature) lengthen the precision-drop period.

        Calibration-time API: ``q_period`` is baked into the compiled
        train step at trace time, so call this *before* the first
        ``train_batch`` (e.g. after an ``Eigenvalue.compute_eigenvalue``
        probe); changing it on a live engine requires clearing the
        engine's compiled cache (``engine._compiled.clear()``)."""
        ratio = max(1e-6, float(eigenvalue)) / max(1e-6, float(max_eigenvalue))
        self.q_period = max(1, int(self.q_period * (1.0 + ratio)))

    # -- application -------------------------------------------------------
    def _qdq_leaf(self, w: jnp.ndarray, bits: jnp.ndarray, key) -> jnp.ndarray:
        # stacked (L, in, out) weights quantize per layer — scale groups
        # must never straddle the layer boundary (a loud layer would
        # crush its co-grouped neighbor's resolution)
        g = self.cfg.quantize_groups
        if w.ndim >= 3:
            L = w.shape[0]
            per_layer = w.size // L
            if per_layer % g != 0:
                logger.warning(
                    f"MoQ: per-layer size {per_layer} not divisible by quantize_groups="
                    f"{g}; using one scale group per layer for this tensor"
                )
                g = 1
            groups = L * g
        else:
            if w.size % g != 0:
                logger.warning(
                    f"MoQ: tensor of {w.size} elements not divisible by quantize_groups="
                    f"{g}; falling back to one scale group for this tensor"
                )
                g = 1
            groups = g
        # bits is traced; the grouped quantizer computes 2.0**(bits-1)
        return grouped_qdq(
            w,
            groups=groups,
            bits=bits,
            symmetric=self.cfg.quantize_type != "asymmetric",
            stochastic=self.cfg.quantize_rounding == "stochastic",
            key=key,
        )

    def quantize_params(self, params: Any, global_step, rng: Optional[jax.Array] = None) -> Any:
        """Fake-quantize every matmul weight (names ``*_w``, ≥2-D);
        norms, biases and embeddings stay full precision (reference
        quantizes the transformer matmul weights)."""
        import zlib

        bits = self.current_bits(global_step)
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def visit(path, w):
            name = str(getattr(path[-1], "key", path[-1])) if path else ""
            if w.ndim >= 2 and name.endswith("_w") and "emb" not in name:
                key = jax.random.fold_in(rng, zlib.crc32(name.encode()) & 0x7FFFFFFF)
                return self._qdq_leaf(w, bits, key)
            return w

        return jax.tree_util.tree_map_with_path(visit, params)

    def maybe_log(self, global_step: int) -> None:
        if not self.cfg.quantize_verbose:
            return
        bits = int(self.current_bits(global_step))
        if bits != self._log_bits:
            self._log_bits = bits
            log_dist(f"MoQ: weights now quantized to {bits} bits (period={self.q_period})")
