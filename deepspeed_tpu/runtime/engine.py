"""The training engine.

TPU-native re-design of the reference's ``DeepSpeedEngine``
(``runtime/engine.py:85``).  The reference engine is a mutable
``nn.Module`` wrapper that intercepts autograd; here the hot path is a
**pure jitted train step** over an explicit ``TrainState`` pytree, and the
engine object is a thin stateful host shell (step counters, timers,
checkpoint I/O) — SURVEY.md §7 design stance.

API mapping (reference → here):

* ``engine(batch); engine.backward(loss); engine.step()`` →  the same
  three calls work (micro-batch at a time, grad accumulation in state),
  but ``forward`` runs the fused forward+backward (JAX cannot split
  autodiff across Python calls); ``backward`` folds the cached grads into
  the accumulator; ``step`` applies the update at the boundary.
* ``engine.train_batch(batch)`` — one full global batch (all
  micro-batches) in a single compiled step; preferred path.
* ZeRO stage selection (``_configure_zero_optimizer``,
  engine.py:888-982) → sharding-rule selection (zero/stages.py).
* fp16 loss scaling (``_configure_fp16_optimizer``) → LossScaleState in
  the TrainState; bf16 default needs none.
"""
from __future__ import annotations

import functools
import os
import time
from contextlib import nullcontext
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.analysis.shard import hooks as shard_hooks
from deepspeed_tpu.comm.mesh import MeshInfo
from deepspeed_tpu.config.config import DeepSpeedConfig
from deepspeed_tpu.sharding import (
    batch_pspec,
    build_mesh,
    derive_topology,
    dp_rows_spec,
    stacked_batch_pspec,
)
from deepspeed_tpu.sharding.rules import PartitionRules
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.runtime.fp16.loss_scaler import LossScaler
from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule
from deepspeed_tpu.runtime.zero.stages import ZeroShardingRules, opt_state_specs
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (
    BACKWARD_TIMER,
    FORWARD_TIMER,
    STEP_TIMER,
    TRAIN_BATCH_TIMER,
    SynchronizedWallClockTimer,
    ThroughputTimer,
)

MEMORY_OPT_ALLREDUCE_SIZE = 500_000_000


class _PlacedBatch:
    """Explicit marker for batches already stacked + device-placed by
    ``engine.prefetch_loader`` — lets ``train_batch`` skip re-placement
    without guessing from shapes."""

    __slots__ = ("tree",)

    def __init__(self, tree: Any):
        self.tree = tree


def _global_norm(tree: Any) -> jnp.ndarray:
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(tree)]
    return jnp.sqrt(sum(leaves)) if leaves else jnp.zeros((), jnp.float32)


def _clip_by_global_norm(tree: Any, max_norm: float) -> Tuple[Any, jnp.ndarray]:
    norm = _global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * factor).astype(g.dtype), tree), norm


class DeepSpeedEngine:
    def __init__(
        self,
        model: Callable,
        params: Any,
        config: DeepSpeedConfig,
        optimizer: Any = None,
        lr_scheduler: Any = None,
        mesh=None,
        tp_spec_fn=None,
        partition_rules=None,
        loss_fn: Optional[Callable] = None,
        rng: Optional[jax.Array] = None,
        dist_init_required: Optional[bool] = None,
    ):
        """``model``: callable ``(params, batch, rng) -> loss`` (or outputs
        if ``loss_fn`` given, then ``loss_fn(outputs, batch) -> loss``).
        ``params``: initial parameter pytree (host or device arrays).
        ``partition_rules``: how parameter layouts resolve — a
        :class:`~deepspeed_tpu.sharding.rules.PartitionRules`, a family
        name (``"gpt2"``/``"bert"``/``"neo"``/``"moe"``), or an ordered
        ``(regex, PartitionSpec)`` table; ``tp_spec_fn`` (legacy) wraps
        into the same engine.
        """
        self.config = config
        self._model_fn = model
        self._loss_fn = loss_fn
        if mesh is not None:
            self.mesh = mesh
            self.topology = derive_topology(mesh)
        else:
            self.mesh, self.topology = build_mesh(config.mesh)
        self.mesh_info = MeshInfo.from_mesh(self.mesh)
        # -- partition-rule engine (docs/sharding.md) ----------------------
        self.partition_rules = PartitionRules.coerce(partition_rules, tp_spec_fn)
        self.global_rank = jax.process_index()
        self.world_size = self.mesh_info.world_size

        # -- precision ----------------------------------------------------
        if config.fp16.enabled:
            self.compute_dtype = jnp.float16
        elif config.bf16.enabled:
            self.compute_dtype = jnp.bfloat16
        else:
            self.compute_dtype = jnp.float32
        self.loss_scaler = LossScaler.from_config(config.fp16)

        # -- sharding rules (ZeRO stage -> specs), resolved through the
        # partition-rule engine; data_size arms cross-replica
        # weight-update sharding (the default ZeRO-1, docs/sharding.md)
        self.zero_rules = ZeroShardingRules(
            config.zero_config,
            fsdp_size=self.mesh_info.fsdp_world_size,
            tp_spec_fn=self.partition_rules.tp_spec_fn(),
            data_size=self.mesh_info.sizes.get("data", 1),
        )

        # -- optimizer -----------------------------------------------------
        self.optimizer = optimizer if optimizer is not None else self._configure_basic_optimizer()
        self.lr_schedule = self._configure_lr_schedule(lr_scheduler)
        self.client_lr_scheduler = lr_scheduler

        # -- ZeRO-Offload / Infinity (host-resident optimizer) -------------
        # reference: cpu_offload grads→host + DeepSpeedCPUAdam
        # (stage2.py:898-1023, engine.py:776-780); NVMe moments via the
        # pipelined swapper.  Device keeps compute-dtype params only.
        self._offload_cfg = config.zero_config.offload_optimizer
        self._offload = bool(self._offload_cfg.enabled)
        self._host_opt = None
        if config.zero_config.offload_param.enabled and getattr(model, "stream_spec", None) is None:
            # The real param-offload path is the streaming
            # ZeroInfinityEngine (runtime/zero/param_offload.py), chosen
            # by initialize() when the model advertises a ``stream_spec``
            # and the combo is streamable.  Landing here without a spec
            # means params stay HBM-resident (sharded 1/fsdp per chip).
            logger.warning(
                "offload_param: model exposes no stream_spec, so params stay "
                "HBM-resident (sharded 1/fsdp); models.gpt2.make_model "
                "provides the >HBM layer-streaming path"
            )
        # Multi-host offload: fp32 masters + moments are sharded 1/P per
        # host as one flat slice (the reference's per-DP-rank partitioned
        # CPU buffers, stage2.py:898-1023); each host steps its slice and
        # the updated masters reassemble via a process all-gather.
        # DS_OFFLOAD_SHARDS=K simulates K hosts in one process (tests).
        env_shards = int(os.environ.get("DS_OFFLOAD_SHARDS", "1"))
        if jax.process_count() > 1:
            # real multi-host: one slice per process, always — a larger
            # env override would leave slices no process owns
            if env_shards > 1 and env_shards != jax.process_count():
                logger.warning(
                    f"DS_OFFLOAD_SHARDS={env_shards} ignored: with "
                    f"{jax.process_count()} processes each host owns exactly one slice"
                )
            self._offload_shards = jax.process_count()
        else:
            self._offload_shards = max(1, env_shards)
        if self._offload:
            if optimizer is not None:
                raise ValueError(
                    "offload_optimizer cannot be combined with a client optimizer "
                    "(the host step owns the update); drop optimizer= or the offload block"
                )
            if not getattr(self, "_use_grad_acc", True):
                raise NotImplementedError("offload_optimizer is not supported with the pipeline engine yet")

        # -- flat-fallback leaves (reference flattened partitions,
        # stage2.py:432 / partition_parameters.py:688): leaves with no
        # fsdp-divisible dim live in engine state as zero-padded 1-D
        # vectors sharded over fsdp; the model sees them re-materialized
        # inside the differentiated step, so their grads come back flat
        # (and reduce-scattered) automatically.  Disabled for the
        # pipeline engine, which owns its own parameter layout.
        self._flat_plan = (
            self.zero_rules.plan_flat(params) if getattr(self, "_use_grad_acc", True) else {}
        )
        if self._flat_plan:
            params = self._flatten_state_leaves(params)
            log_dist(
                f"zero: {len(self._flat_plan)} param(s) with no fsdp-divisible dim "
                f"stored flat-padded over fsdp={self.mesh_info.fsdp_world_size}"
            )

        # -- state ---------------------------------------------------------
        self._param_specs = self.zero_rules.tree_param_specs(params)
        self._grad_specs = self.zero_rules.tree_grad_specs(params)
        # update-phase layout: one params-shaped tree of opt-state specs;
        # constraining the averaged grads to it inside the update makes
        # GSPMD shard the whole optimizer computation across the dp grid
        # (cross-replica weight-update sharding, arXiv:2004.13336)
        self._update_specs = self.zero_rules.tree_opt_specs_like(params)
        if self._offload:
            self._host_opt = self._configure_host_offload_optimizer(params)
            params = self._shard_params(params, dtype=self.compute_dtype)
            opt_state = {}
            self._opt_specs = {}
        else:
            params = self._shard_params(params)
            opt_state = jax.eval_shape(self.optimizer.init, params)
            self._opt_specs = opt_state_specs(opt_state, params, self.zero_rules)
            opt_state = jax.jit(
                self.optimizer.init,
                out_shardings=jax.tree.map(self._sh, self._opt_specs, is_leaf=lambda x: isinstance(x, P)),
            )(params)

        if rng is None:
            rng = jax.random.PRNGKey(config.seed)
        # subclasses that never accumulate (pipeline) skip the fp32 buffer
        self._use_grad_acc = getattr(self, "_use_grad_acc", True)
        # gas==1: train_batch consumes grads inside the same compiled
        # program, so the persistent params-sized fp32 accumulator is dead
        # HBM (3.1GB at 774M — the margin between fitting selective-remat
        # activations on one chip or not).  Allocate it lazily, only if
        # the three-call micro API (forward/backward/step) is used.
        self._lazy_grad_acc = (
            self._use_grad_acc
            and config.gradient_accumulation_steps == 1
            and not self._offload
        )
        self.state: Dict[str, Any] = {
            "params": params,
            "opt_state": opt_state,
            "grad_acc": jax.jit(
                lambda p: jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p),
                out_shardings=jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda x: isinstance(x, P)),
            )(params)
            if self._use_grad_acc and not self._lazy_grad_acc
            else {},
            "micro_step": jnp.zeros((), jnp.int32),
            "global_step": jnp.zeros((), jnp.int32),
            "global_samples": jnp.zeros((), jnp.int32),
            "loss_scale": self.loss_scaler.init(),
            "rng": rng,
        }
        self._state_shardings = {
            "params": jax.tree.map(self._sh, self._param_specs, is_leaf=lambda x: isinstance(x, P)),
            "opt_state": jax.tree.map(self._sh, self._opt_specs, is_leaf=lambda x: isinstance(x, P)),
            "grad_acc": jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda x: isinstance(x, P))
            if self._use_grad_acc and not self._lazy_grad_acc
            else {},
            "micro_step": self._sh(P()),
            "global_step": self._sh(P()),
            "global_samples": self._sh(P()),
            "loss_scale": jax.tree.map(lambda _: self._sh(P()), self.state["loss_scale"]),
            "rng": self._sh(P()),
        }
        # Place every state leaf with its NamedSharding now: leaves created
        # by plain jnp ops otherwise enter the first compiled call with a
        # default GSPMDSharding, which differs from the NamedSharding the
        # step's outputs carry — forcing a silent full recompile at step 2.
        self.state = jax.device_put(self.state, self._state_shardings)

        # -- activation checkpointing (reference _configure_checkpointing,
        # engine.py:523) — publish the config block to the module-level
        # checkpoint() API so user models pick it up
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as act_ckpt

        act_ckpt.configure(deepspeed_config=config)

        # -- MoQ quantize-training + progressive layer drop ----------------
        # (reference engine hooks: _take_model_step :1284-1290 for MoQ,
        # forward :1101 / step :1343 for PLD)
        self.quantizer = None
        if config.quantize_training.enabled:
            if self._offload:
                raise NotImplementedError("quantize_training (MoQ) is not supported with offload_optimizer")
            from deepspeed_tpu.runtime.quantize import Quantizer

            self.quantizer = Quantizer(config.quantize_training)
        self.progressive_layer_drop = None
        if config.progressive_layer_drop.enabled:
            if not getattr(self, "_use_grad_acc", True):
                raise NotImplementedError(
                    "progressive_layer_drop is not wired into the pipeline engine yet "
                    "(theta injection lives in the micro-step path)"
                )
            from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop

            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.progressive_layer_drop.theta, gamma=config.progressive_layer_drop.gamma
            )

        # -- 1-bit Adam/LAMB compressed-exchange phase ---------------------
        # After freeze_step the engine switches to a SECOND compiled
        # train step that keeps per-rank gradients UNREDUCED (vmap over
        # data-axis slices) and exchanges the momentum through the
        # error-feedback 1-bit collective (comm/collectives.py) — the
        # reference's comm-volume saving (onebit/adam.py:110-220 over
        # nccl.py:47-186; onebit/lamb.py for the large-batch rung),
        # realized as two executables because a single program would pay
        # for both exchange paths every step.
        from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam
        from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb

        self._onebit_frozen = False
        # fsdp>1 composes via the two-level exchange (flat dim sharded over
        # fsdp, 1-bit over data within each group) and gradient clipping
        # runs on the per-rank local norms before the exchange — both
        # envelope restrictions of round 2 are gone (VERDICT r2 #6).
        onebit_blockers = {
            "data axis must be > 1": self.mesh_info.sizes.get("data", 1) > 1,
            "pipeline engine unsupported": self._use_grad_acc,
            "offload_optimizer unsupported": not self._offload,
            "quantize_training (MoQ) unsupported": self.quantizer is None,
            "progressive_layer_drop unsupported": self.progressive_layer_drop is None,
        }
        self._onebit_exchange_ok = isinstance(
            self.optimizer, (OnebitAdam, OnebitLamb)
        ) and all(onebit_blockers.values())
        if (
            self._onebit_exchange_ok
            and self.mesh_info.fsdp_world_size > 1
            and self.zero_stage >= 1
        ):
            # the frozen layout replicates int8 momentum signs (1 B) +
            # flat fp32 variance (4 B) + packed params (4 B) and keeps a
            # per-chip fp32 worker-error row (1/n of an (n, Mp) grid ≈
            # 4 B/param/chip) — ~13 bytes/param/chip STATIC, plus
            # step-transient decompressed fp32 momentum and grad rows.
            # Models that only fit BECAUSE of ZeRO sharding will OOM at
            # the freeze step, not at init.
            n_p = sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(params))
            logger.warning(
                "1-bit optimizer + ZeRO(fsdp>1): the compressed phase "
                "replicates the momentum signs (int8) + flat fp32 "
                "variance/params and keeps a per-chip fp32 worker-error row "
                f"(~{13 * n_p / 2**30:.1f}GiB static per chip, plus fp32 "
                "momentum/grad transients during the step) — ZeRO's state "
                "sharding does not apply after freeze_step; ensure HBM "
                "headroom or keep fsdp=1 "
                "(layout trade-off measured in tests/test_onebit.py::"
                "test_frozen_variance_layout_wire_bytes)"
            )
        if isinstance(self.optimizer, (OnebitAdam, OnebitLamb)) and not self._onebit_exchange_ok:
            failed = [k for k, ok in onebit_blockers.items() if not ok]
            logger.warning(
                f"1-bit {type(self.optimizer).__name__}: compressed gradient "
                "exchange DISABLED — the optimizer will fall back to local "
                "momentum quantization with full-precision allreduce "
                f"({'; '.join(failed)})"
            )

        # -- resilience (watchdog / divergence guard / checkpoint dirs) ----
        # (docs/resilience.md; engines built without a DeepSpeedConfig
        # resilience block fall back to the defaults)
        from deepspeed_tpu.config.config import ResilienceConfig
        from deepspeed_tpu.resilience import DivergenceGuard, PreemptionWatchdog

        self.resilience = getattr(config, "resilience", None) or ResilienceConfig()
        self._divergence_guard = (
            DivergenceGuard(
                threshold=self.resilience.divergence.threshold,
                action=self.resilience.divergence.action,
            )
            if self.resilience.divergence.enabled
            else None
        )
        # the directory emergency saves / auto-rollback target: explicit
        # watchdog.save_dir, else wherever the run last saved/loaded
        self._resilience_ckpt_dir: Optional[str] = self.resilience.watchdog.save_dir
        self._watchdog = None
        if self.resilience.watchdog.enabled:
            self._watchdog = PreemptionWatchdog(
                grace_seconds=self.resilience.watchdog.grace_seconds,
                exit_code=self.resilience.watchdog.exit_code,
            ).install()

        # -- distributed supervision (heartbeat plane + hung-collective
        # watchdog + exit-44 rescue; docs/resilience.md).  Launcher-
        # spawned children also pick up their DS_FAULT_PLAN here, so
        # kill/stall sites fire inside real multi-process tests.
        from deepspeed_tpu.resilience import faults as _faults

        _faults.install_from_env()
        self._supervision = None
        self._train_loader = None  # registered resumable dataloader
        if self.resilience.supervision.enabled:
            self._supervision = self._build_supervisor(self.resilience.supervision)

        # -- overlap: input prefetch / async checkpointing / step timeline
        # (docs/performance.md; runtime/overlap/)
        from deepspeed_tpu.config.config import OverlapConfig
        from deepspeed_tpu.runtime.overlap import AsyncCheckpointWriter, StepTimeline

        self.overlap = getattr(config, "overlap", None) or OverlapConfig()
        self.timeline = StepTimeline(
            enabled=self.overlap.timeline.enabled, window=self.overlap.timeline.window
        )
        # per-step compute fencing costs a host<->device round trip per
        # step (the sync ThroughputTimer deliberately avoids off report
        # steps); default follows the wall_clock_breakdown opt-in, whose
        # per-step timers already sync
        fence = self.overlap.timeline.fence
        self._timeline_fence = config.wall_clock_breakdown if fence is None else bool(fence)
        self._async_writer = (
            AsyncCheckpointWriter(
                drain_timeout_seconds=self.overlap.async_checkpoint.drain_timeout_seconds
            )
            if self.overlap.async_checkpoint.enabled
            else None
        )
        # executables built so far — the compile-stability regression
        # tests pin this to 1 over a steady-state training loop (any
        # shape/static-arg drift shows up as a recount)
        self.compilation_count = 0

        # -- telemetry plane (docs/telemetry.md) ---------------------------
        # Arm the process-wide registry/tracer BEFORE the comm layer so
        # its trace-time strategy decisions land in the registry; the
        # TensorBoard monitor is created here (it is a telemetry sink —
        # the engine's loss/lr/loss-scale events route through the
        # manager, never via direct add_scalar: ds_lint raw-metric-emit)
        from deepspeed_tpu import telemetry as _telemetry
        from deepspeed_tpu.utils.monitor import TensorBoardMonitor

        self.monitor = TensorBoardMonitor(
            output_path=config.tensorboard.output_path,
            job_name=config.tensorboard.job_name,
            enabled=config.tensorboard.enabled,
            rank=self.global_rank,
        )
        self.telemetry = _telemetry.configure(
            getattr(config, "telemetry", None),
            rank=self.global_rank, label="train", monitor=self.monitor,
        )
        if self.telemetry.collect or self.telemetry.tracer.enabled:
            self.timeline.attach_telemetry(self.telemetry, prefix="train")

        # -- Pallas kernel suite (docs/kernels.md) -------------------------
        # Process-wide arming from the `kernels` block; resolved by the
        # ops-level dispatches at trace time (fused update, flash decode)
        from deepspeed_tpu.ops import kernels as _kernels_mod

        _kernels_mod.configure_from_config(getattr(config, "kernels", None))

        # -- unified comm layer (docs/comm.md) -----------------------------
        # Strategy-selected collectives: the gradient exchange routes
        # through self.comm, which picks dense / int8-quantized (EQuARX)
        # / error-feedback-compressed per (size, dtype, topology) at
        # TRACE time — no recompile per strategy, one executable each.
        self._init_comm_layer(config)

        # -- ds_san runtime sanitizer (opt-in: `sanitizer` config block
        # or DS_SAN=1; docs/ds_san.md).  None in production — every hook
        # below is a near-free attribute check.
        from deepspeed_tpu.analysis.sanitizer import maybe_from_config

        self._sanitizer = maybe_from_config(getattr(config, "sanitizer", None))
        self._san_last_batch = None  # last stacked batch, for the NaN probe

        # -- host-side bookkeeping ----------------------------------------
        from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler

        self._last_loss = None
        self._last_info = None
        self.flops_profiler = FlopsProfiler(config.flops_profiler, engine=self)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=config.train_batch_size, steps_per_output=config.steps_per_print
        )
        self.wall_clock_breakdown = config.wall_clock_breakdown
        self._cached_loss = None
        self._compiled = {}
        self._train_step_cost: Dict[str, float] = {}
        self.skipped_steps = 0
        # Host-side mirror of state["global_step"].  Reading the device
        # scalar costs a full host<->device round trip (on a remote/
        # tunneled TPU that is ~100ms), so the hot path must never sync
        # on it; the mirror advances with every non-skipped step and is
        # reconciled from the device value at checkpoint load.
        self._host_global_step = 0
        self._host_micro_step = 0

        log_dist(
            f"engine: zero_stage={self.zero_stage} dtype={self.compute_dtype.__name__} "
            f"micro_bs={config.train_micro_batch_size_per_gpu} gas={config.gradient_accumulation_steps} "
            f"dp={self.mesh_info.dp_world_size} (data={self.mesh_info.sizes.get('data',1)} × "
            f"fsdp={self.mesh_info.fsdp_world_size}) tp={self.mesh_info.model_parallel_world_size} "
            f"pp={self.mesh_info.pipe_parallel_world_size}"
        )

    # ------------------------------------------------------------------
    # configuration helpers
    # ------------------------------------------------------------------
    def _sh(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec if spec is not None else P())

    def _configure_basic_optimizer(self):
        """Reference ``_configure_basic_optimizer`` (engine.py:752-809)."""
        from deepspeed_tpu.ops.adam.fused_adam import SGD, FusedAdam, FusedAdamW
        from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb

        name = self.config.optimizer.name or C.ADAM_OPTIMIZER
        params = dict(self.config.optimizer.params)
        params.pop("torch_adam", None)
        lr = params.pop("lr", 1e-3)
        if name == C.ADAM_OPTIMIZER:
            adam_w_mode = params.pop("adam_w_mode", True)
            return FusedAdam(lr=lr, adam_w_mode=adam_w_mode, **params)
        if name == C.ADAMW_OPTIMIZER:
            return FusedAdamW(lr=lr, **params)
        if name == C.LAMB_OPTIMIZER:
            return FusedLamb(lr=lr, **params)
        if name == C.ONEBIT_ADAM_OPTIMIZER:
            from deepspeed_tpu.runtime.fp16.onebit.adam import OnebitAdam

            return OnebitAdam(lr=lr, fsdp_size=self.mesh_info.fsdp_world_size, **params)
        if name == C.ONEBIT_LAMB_OPTIMIZER:
            from deepspeed_tpu.runtime.fp16.onebit.lamb import OnebitLamb

            return OnebitLamb(lr=lr, **params)
        if name == C.SGD_OPTIMIZER:
            return SGD(lr=lr, **params)
        raise ValueError(f"Unknown optimizer '{name}'")

    def _configure_lr_schedule(self, client_scheduler):
        if callable(client_scheduler):
            return client_scheduler
        if self.config.scheduler.type:
            return get_lr_schedule(self.config.scheduler.type, self.config.scheduler.params)
        base_lr = getattr(self.optimizer, "lr", 1e-3)
        return lambda step: jnp.asarray(base_lr, jnp.float32)

    def _shard_params(self, params: Any, dtype=jnp.float32) -> Any:
        shardings = jax.tree.map(self._sh, self._param_specs, is_leaf=lambda x: isinstance(x, P))

        def host_cast(p):
            # cast host-side (ml_dtypes handles bf16) so device transfer
            # moves target-dtype bytes — no full-precision staging in HBM
            return np.asarray(p).astype(dtype) if not isinstance(p, jax.Array) else jnp.asarray(p, dtype)

        return jax.device_put(jax.tree.map(host_cast, params), shardings)

    def _configure_host_offload_optimizer(self, params):
        """Build the host optimizer (reference _configure_basic_optimizer's
        DeepSpeedCPUAdam branch, engine.py:776-780).  With P > 1 offload
        shards, fp32 masters + moments live as one flat 1/P slice per
        host (reference per-DP-rank partitioned pinned buffers,
        stage2.py:898-1023); each host steps only its slice."""
        from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer

        name = self.config.optimizer.name or C.ADAM_OPTIMIZER
        if name not in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER):
            raise ValueError(f"offload_optimizer supports Adam/AdamW, got '{name}'")
        p = dict(self.config.optimizer.params)
        nvme_dir = None
        if self._offload_cfg.device == "nvme":
            if not self._offload_cfg.nvme_path:
                raise ValueError("offload_optimizer.device=nvme requires nvme_path")
            nvme_dir = os.path.join(self._offload_cfg.nvme_path, "zero_infinity_swap")
        kw = dict(
            lr=p.get("lr", 1e-3),
            betas=tuple(p.get("betas", (0.9, 0.999))),
            eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=(name == C.ADAMW_OPTIMIZER) or bool(p.get("adam_w_mode", True)),
            aio_config=self.config.aio,
            pipeline=self._offload_cfg.pipeline_read or self._offload_cfg.pipeline_write,
        )
        if self._offload_shards <= 1:
            return HostOffloadOptimizer(
                jax.tree.map(np.asarray, params), nvme_swap_dir=nvme_dir, **kw
            )
        from deepspeed_tpu.runtime.fp16.onebit.adam import pack_flat

        P_shards = self._offload_shards
        flat = np.asarray(pack_flat(jax.tree.map(np.asarray, params), P_shards))
        L = flat.shape[0] // P_shards
        self._offload_slice_len = L

        def mk(i):
            nv = None if nvme_dir is None else os.path.join(nvme_dir, f"shard{i}")
            if nv is not None:
                os.makedirs(nv, exist_ok=True)
            return HostOffloadOptimizer({"flat": flat[i * L : (i + 1) * L].copy()}, nvme_swap_dir=nv, **kw)

        if jax.process_count() > 1:
            # one slice per host; reassembly goes through process_allgather
            self._host_shard_ids = [jax.process_index()]
        else:
            # simulated multi-host (DS_OFFLOAD_SHARDS): this process owns
            # every slice and steps them in turn — exercises the exact
            # slice/step/assemble math single-process
            self._host_shard_ids = list(range(P_shards))
        self._host_opts = [mk(i) for i in self._host_shard_ids]
        log_dist(
            f"ZeRO-Offload: masters sharded 1/{P_shards} per host "
            f"({L * 4 / 1e9:.2f} GB master slice/host)"
        )
        return self._host_opts[0]

    # ------------------------------------------------------------------
    # properties (reference engine exposes config as methods, :227-506)
    # ------------------------------------------------------------------
    @property
    def zero_stage(self) -> int:
        return self.config.zero_config.stage
    zero_optimization_stage = zero_stage

    @property
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    @property
    def global_steps(self) -> int:
        return self._host_global_step

    @property
    def micro_steps(self) -> int:
        return self._host_micro_step

    @property
    def loss_scale(self) -> float:
        # explicit d2h read (sanitizer transfer-guard clean)
        return float(jax.device_get(self.state["loss_scale"].scale))

    @property
    def module(self):
        return self._model_fn

    def get_lr(self):
        return [float(self.lr_schedule(self._host_global_step))]

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._host_micro_step % self.gradient_accumulation_steps == 0

    # ------------------------------------------------------------------
    # flat-fallback leaf layout (see __init__)
    # ------------------------------------------------------------------
    def _flatten_state_leaves(self, tree: Any) -> Any:
        """Natural layout → state layout (flat-pad leaves in the plan)."""
        from deepspeed_tpu.runtime.zero.stages import _path_str

        def f(path, leaf):
            info = self._flat_plan.get(_path_str(path))
            if info is None:
                return leaf
            _, n, padded = info
            flat = jnp.ravel(jnp.asarray(leaf))
            return jnp.pad(flat, (0, padded - n))

        return jax.tree_util.tree_map_with_path(f, tree)

    def _unflatten_state_leaves(self, tree: Any) -> Any:
        """State layout → natural layout (no dtype change)."""
        from deepspeed_tpu.runtime.zero.stages import _path_str

        def f(path, leaf):
            info = self._flat_plan.get(_path_str(path))
            if info is None:
                return leaf
            shape, n, _ = info
            return leaf[:n].reshape(shape)

        return jax.tree_util.tree_map_with_path(f, tree)

    def _materialize_params(self, params: Any, dtype) -> Any:
        """State-layout params → full-shape compute-dtype params (traced
        inside the step; for flat leaves the replicate-constraint turns
        the fsdp shards into an all-gather at first use)."""
        from deepspeed_tpu.runtime.zero.stages import _path_str

        def f(path, leaf):
            info = self._flat_plan.get(_path_str(path)) if self._flat_plan else None
            x = leaf
            if info is not None:
                shape, n, _ = info
                x = jax.lax.with_sharding_constraint(x, self._sh(P()))
                x = x[:n].reshape(shape)
            return x.astype(dtype)

        return jax.tree_util.tree_map_with_path(f, params)

    def _map_param_shaped_subtrees(self, tree: Any, ref: Any, fn) -> Any:
        """Convert optimizer-state m/v mirrors between layouts (shared
        traversal lives in zero/stages.py)."""
        from deepspeed_tpu.runtime.zero.stages import map_param_shaped_subtrees

        return map_param_shaped_subtrees(tree, ref, fn)

    # -- portable (natural-layout) checkpoint conversion ----------------
    # Flat-padded leaf sizes depend on fsdp_size, so checkpoints store
    # the natural layout: a job restoring at a different fsdp degree
    # re-pads for its own mesh (the elastic-resize story stays intact).
    def _to_portable_state(self, state: Any) -> Any:
        if not self._flat_plan:
            return state
        ref = state["params"]  # state layout — the shape reference for m/v mirrors
        out = dict(state)
        out["params"] = self._unflatten_state_leaves(state["params"])
        if self._use_grad_acc and out.get("grad_acc"):
            out["grad_acc"] = self._unflatten_state_leaves(state["grad_acc"])
        if out.get("opt_state"):
            out["opt_state"] = self._map_param_shaped_subtrees(
                state["opt_state"], ref, self._unflatten_state_leaves
            )
        return out

    def _from_portable_state(self, portable: Any) -> Any:
        if not self._flat_plan:
            return portable
        out = dict(portable)
        if out.get("opt_state"):
            out["opt_state"] = self._map_param_shaped_subtrees(
                portable["opt_state"], portable["params"], self._flatten_state_leaves
            )
        out["params"] = self._flatten_state_leaves(portable["params"])
        if self._use_grad_acc and out.get("grad_acc"):
            out["grad_acc"] = self._flatten_state_leaves(portable["grad_acc"])
        return out

    def _portable_target(self) -> Any:
        """Abstract (ShapeDtypeStruct) tree describing the on-disk
        checkpoint layout, with shardings for orbax resharding-on-read."""
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s),
            self.state,
            self._state_shardings,
        )
        if not self._flat_plan:
            return abstract
        from deepspeed_tpu.runtime.zero.stages import _path_str

        repl = self._sh(P())

        def unflat_abs(tree):
            def f(path, leaf):
                info = self._flat_plan.get(_path_str(path))
                if info is None:
                    return leaf
                shape, _, _ = info
                return jax.ShapeDtypeStruct(shape, leaf.dtype, sharding=repl)

            return jax.tree_util.tree_map_with_path(f, tree)

        out = dict(abstract)
        out["params"] = unflat_abs(abstract["params"])
        if self._use_grad_acc and out.get("grad_acc"):
            out["grad_acc"] = unflat_abs(abstract["grad_acc"])
        if out.get("opt_state"):
            out["opt_state"] = self._map_param_shaped_subtrees(
                out["opt_state"], abstract["params"], unflat_abs
            )
        return out

    # ------------------------------------------------------------------
    # core compiled steps
    # ------------------------------------------------------------------
    def _compute_loss(self, params, batch, rng, ls_state):
        cparams = self._materialize_params(params, self.compute_dtype)
        out = self._model_fn(cparams, batch, rng)
        loss = self._loss_fn(out, batch) if self._loss_fn is not None else out
        loss = jnp.asarray(loss)
        if loss.ndim != 0:
            loss = jnp.mean(loss)
        return self.loss_scaler.scale_loss(loss.astype(jnp.float32), ls_state), loss

    def _micro_grads(self, state, batch):
        """Shared micro-batch body: fused forward+backward, returns the raw
        (still loss-scaled) grads without touching the accumulator."""
        if self.progressive_layer_drop is not None and isinstance(batch, dict):
            from deepspeed_tpu.runtime.progressive_layer_drop import PLD_THETA_KEY

            batch = dict(batch)
            batch[PLD_THETA_KEY] = self.progressive_layer_drop.get_theta(state["global_step"])
        rng = jax.random.fold_in(state["rng"], state["micro_step"])
        (scaled_loss, loss), grads = jax.value_and_grad(
            lambda p: self._compute_loss(p, batch, rng, state["loss_scale"]), has_aux=True
        )(state["params"])
        # dense grad-exchange site: the comm layer's sharding constraint
        # is what GSPMD lowers to the grad psum / psum_scatter
        grads = self.comm.constrain_grads(
            grads, jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda x: isinstance(x, P))
        )
        state = dict(state)
        state["micro_step"] = state["micro_step"] + 1
        state["global_samples"] = state["global_samples"] + self.train_micro_batch_size_per_gpu * self.mesh_info.dp_world_size
        return state, loss, grads

    def _micro_step_impl(self, state, batch):
        """One micro-batch: fused forward+backward, accumulate grads."""
        state, loss, grads = self._micro_grads(state, batch)
        state["grad_acc"] = jax.tree.map(
            lambda a, g: a + g.astype(jnp.float32), state["grad_acc"], grads
        )
        return state, loss

    def _apply_step_impl(self, state):
        """Optimizer step at the grad-accumulation boundary (reference
        ``_take_model_step``, engine.py:1269)."""
        gas = self.gradient_accumulation_steps
        grads = jax.tree.map(lambda g: g / gas, state["grad_acc"])
        state, info = self._apply_update(state, grads)
        state["grad_acc"] = jax.tree.map(jnp.zeros_like, state["grad_acc"])
        return state, info

    def _apply_update(self, state, grads):
        """Unscale/clip/update given already-averaged grads (shared by the
        grad-accumulation path and the pipeline engine's fused batch)."""
        grads, overflow = self.loss_scaler.unscale_and_check(grads, state["loss_scale"])
        return self._apply_update_unscaled(state, grads, overflow)

    def _apply_update_unscaled(self, state, grads, overflow):
        """Clip + optimizer update for ALREADY-unscaled averaged grads
        with the overflow decision made by the caller (the explicit
        comm-exchange path checks finiteness on the pre-quantization
        rows, where an inf is still visible)."""
        if self.zero_rules.cross_replica_active:
            # cross-replica weight-update sharding: pin the averaged
            # grads to the optimizer-state layout so the partitioner
            # computes each replica's 1/dp slice of the update (a local
            # slice of the reduced grads — no extra comm on entry; the
            # updated params all-gather once at the out_shardings pin)
            grads = jax.lax.with_sharding_constraint(
                grads,
                jax.tree.map(self._sh, self._update_specs, is_leaf=lambda x: isinstance(x, P)),
            )
        grad_norm = jnp.zeros((), jnp.float32)
        if self.config.gradient_clipping > 0.0:
            grads, grad_norm = _clip_by_global_norm(grads, self.config.gradient_clipping)
        lr = jnp.asarray(self.lr_schedule(state["global_step"]), jnp.float32)
        upd_kw = {}
        if getattr(self.optimizer, "state_precision", "fp32") in ("8bit", "bf16"):
            # stochastic rounding of the 8-bit Adam state needs fresh
            # bits each step — without them v falls back to nearest
            # rounding and sub-LSB EMA increments are systematically lost
            upd_kw["rng"] = jax.random.fold_in(state["rng"], state["global_step"] + 997_001)
        # fused-update kernel seam (ops/kernels, docs/kernels.md): when
        # armed and the optimizer/state is kernel-eligible, ONE Pallas
        # kernel per leaf does the master-weight read + moment update +
        # param-dtype cast in a single HBM pass, with the overflow skip
        # folded in-producer.  Trace-time static decision; the XLA path
        # below stays the fallback and the numerics ground truth.
        fused = None
        from deepspeed_tpu.ops import kernels as _kernels

        if _kernels.fused_update_armed():
            if _kernels.on_tpu_backend() and self.mesh.devices.size > 1:
                # compiled Mosaic custom calls are opaque to the GSPMD
                # partitioner: on a multi-device mesh the sharded update
                # (cross-replica ZeRO-1, fsdp state) would lose its
                # per-replica-slice contract.  Multi-chip fused updates
                # need the shard_map integration (future arc); keep the
                # partitionable XLA path.  (Off-TPU interpret mode
                # lowers to plain jax ops, which partition fine — the
                # 8-device CPU dryrun tests run the seam.)
                _kernels.warn_once(
                    f"fused-update-multichip-{id(self)}",
                    "kernels: fused_update armed but the mesh spans "
                    f"{self.mesh.devices.size} devices — keeping the "
                    "partitionable XLA update (docs/kernels.md)",
                )
            else:
                from deepspeed_tpu.ops.kernels.fused_update import engine_update

                fused = engine_update(
                    self.optimizer, grads, state["opt_state"], state["params"], lr, overflow
                )
        if fused is not None:
            new_params, new_opt = fused
        else:
            in_producer_skip = getattr(self.optimizer, "supports_skip", False)
            if in_producer_skip:
                # overflow handling happens INSIDE the optimizer's producer
                # pass: updates come out zero and the state keeps its old
                # values.  The alternative — where(overflow, old, new) over
                # the state tree below — re-reads old AND new (state-sized
                # extra HBM traffic; ~26 ms/step at 774M, because the donated
                # output buffer forces `new` to materialize before the select)
                upd_kw["skip"] = overflow
            updates, new_opt = self.optimizer.update(
                grads, state["opt_state"], state["params"], lr=lr, **upd_kw
            )

            if in_producer_skip:
                new_params = jax.tree.map(
                    lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype),
                    state["params"], updates,
                )
            else:
                def apply_or_skip(p, u):
                    return jnp.where(overflow, p, (p.astype(jnp.float32) + u).astype(p.dtype))

                new_params = jax.tree.map(apply_or_skip, state["params"], updates)
                # on overflow, keep the old optimizer state too
                new_opt = jax.tree.map(
                    lambda old, new: jnp.where(overflow, old, new) if hasattr(old, "shape") else new,
                    state["opt_state"],
                    new_opt,
                )
        if self.quantizer is not None:
            # MoQ: fake-quantize weights right after the update
            # (reference _take_model_step :1284-1290); an overflow step is
            # a no-op, so keep the un-quantized (== previous) params then
            qrng = jax.random.fold_in(state["rng"], state["global_step"] + 1_000_003)
            quantized = self.quantizer.quantize_params(new_params, state["global_step"], rng=qrng)
            new_params = jax.tree.map(lambda p, q: jnp.where(overflow, p, q), new_params, quantized)
        state = dict(state)
        state["params"] = new_params
        state["opt_state"] = new_opt
        state["global_step"] = state["global_step"] + jnp.where(overflow, 0, 1)
        state["loss_scale"] = self.loss_scaler.update(state["loss_scale"], overflow)
        return state, {"lr": lr, "grad_norm": grad_norm, "overflow": overflow}

    def _scoped(self, fn):
        """This engine's mesh becomes ambient for the trace (see
        parallel.sequence.scoped_to)."""
        from deepspeed_tpu.parallel.sequence import scoped_to

        return scoped_to(self.mesh, fn)

    def _get_compiled(self, name: str, fn, donate: bool = True, out_shardings=None):
        if name not in self._compiled:
            self._compiled[name] = jax.jit(
                self._scoped(fn),
                donate_argnums=(0,) if donate else (),
                out_shardings=out_shardings,
            )
            self.compilation_count += 1
            if self._sanitizer is not None:
                self._sanitizer.recompile.note(f"engine.{name}", None, owner=id(self))
        return self._compiled[name]

    # ------------------------------------------------------------------
    # ZeRO-Offload step executor (host path)
    # ------------------------------------------------------------------
    def _host_apply_step(self) -> Dict[str, Any]:
        """Optimizer step on host: averaged grads device→host, native CPU
        Adam over fp32 masters (NVMe-pipelined moments when configured),
        bf16 masters host→device.  Replaces the jitted ``_apply_step_impl``
        when ``offload_optimizer`` is enabled."""
        from deepspeed_tpu.runtime.zero.offload import host_unscale_clip_and_check

        gas = self.gradient_accumulation_steps

        if "fetch_grads" not in self._compiled:

            def fetch(state):
                grads = jax.tree.map(lambda g: g / gas, state["grad_acc"])
                state = dict(state)
                state["grad_acc"] = jax.tree.map(jnp.zeros_like, state["grad_acc"])
                return state, grads

            # _scoped: the grad fetch runs under the engine mesh like every
            # other executable (and ds_lint's bare-jit rule stays clean)
            self._compiled["fetch_grads"] = jax.jit(self._scoped(fetch), donate_argnums=(0,))
            # ds_shard Pass 1/2 feed (no-op unless the audit armed it)
            if shard_hooks.armed():
                budget, decisions = shard_hooks.train_budget(self)
                shard_hooks.note_jit(
                    self, "train.offload_drain", self._compiled["fetch_grads"],
                    (self.state,),
                    leaves=shard_hooks.live_param_leaves(self.state["params"]),
                    budget=budget, decisions=decisions,
                )
        self.state, grads = self._compiled["fetch_grads"](self.state)
        # copy=True: device_get may hand back read-only buffers and the
        # host path unscales/clips in place
        g_np = jax.tree.map(lambda g: np.array(jax.device_get(g), np.float32, copy=True), grads)

        scale = float(self.state["loss_scale"].scale)
        leaves = jax.tree.leaves(g_np)
        # every host holds the full (replicated) grads, so the norm/
        # overflow decision is computed identically everywhere — no
        # cross-host exchange needed even in sharded mode
        _, grad_norm, overflow = host_unscale_clip_and_check(
            leaves, scale, self.config.gradient_clipping
        )
        lr = float(self.lr_schedule(self._host_global_step))
        if not (overflow and self.loss_scaler.dynamic):
            step_count = self._host_global_step + 1
            dtype = self.compute_dtype
            if self._offload_shards > 1:
                masters = self._sharded_host_step(g_np, leaves, lr, step_count)
            else:
                masters = self._host_opt.step(
                    jax.tree.unflatten(jax.tree.structure(g_np), leaves), lr, step_count
                )
            self.state["params"] = jax.device_put(
                jax.tree.map(lambda m: np.asarray(m, dtype), masters),
                self._state_shardings["params"],
            )
            self.state["global_step"] = self.state["global_step"] + 1
            self._host_global_step += 1
        self.state["loss_scale"] = self.loss_scaler.update(
            self.state["loss_scale"], jnp.asarray(overflow)
        )
        return {
            "lr": jnp.asarray(lr),
            "grad_norm": jnp.asarray(grad_norm, jnp.float32),
            "overflow": jnp.asarray(overflow),
        }

    # ------------------------------------------------------------------
    # 1-bit Adam frozen phase
    # ------------------------------------------------------------------
    def _sync_onebit_phase(self, global_step: int) -> None:
        """Align the compressed-exchange phase with a tag's step count
        (called before checkpoint restore so state layouts match).  A
        tag at exactly freeze_step is still warm-layout — the phase
        flips lazily at the start of the NEXT train_batch — and loading
        a pre-freeze tag into a frozen engine rolls the layout back."""
        if not self._onebit_exchange_ok:
            return
        if not self._onebit_frozen and global_step > self.optimizer.freeze_step:
            self._enter_onebit_frozen()
        elif self._onebit_frozen and global_step <= self.optimizer.freeze_step:
            self._exit_onebit_frozen()

    def _dp_exchange_axes(self):
        """The explicit (1-bit frozen / quantized-grad) exchange runs
        flat across the WHOLE dp grid — (data × fsdp) when ZeRO shards
        state, so the compressed wire saving covers every data-parallel
        rank (the reference never composes 1-bit with ZeRO; here the
        ring is just wider)."""
        if "fsdp" in self.mesh.axis_names and self.mesh_info.fsdp_world_size > 1:
            return ("data", "fsdp")
        return "data"

    _onebit_exchange_axes = _dp_exchange_axes  # historical name

    def _enter_onebit_frozen(self) -> None:
        n = self.mesh_info.dp_world_size  # exchange rows = full dp grid
        row_spec = dp_rows_spec(self._dp_exchange_axes())
        # NOTE: the frozen layout replicates the momentum (in its int8
        # compressed exchange form — 1 byte/param) and the fp32 variance
        # (the exchange needs the full momentum on every rank to
        # compress it) — ZeRO-1's moment sharding is traded for the
        # 1-bit wire in this phase
        specs = self.optimizer.frozen_specs(row_spec)
        sh = jax.tree.map(self._sh, specs, is_leaf=lambda x: isinstance(x, P))
        self.state["opt_state"] = jax.jit(
            lambda s: self.optimizer.make_frozen_state(s, n), out_shardings=sh
        )(self.state["opt_state"])
        self._state_shardings["opt_state"] = sh
        self._opt_specs = specs
        # the frozen path accumulates into its own (n, Mp) rows buffer —
        # free the params-sized fp32 accumulator
        self.state["grad_acc"] = {}
        self._state_shardings["grad_acc"] = {}
        self._purge_train_executables()
        self._onebit_frozen = True
        self.comm.note(
            "momentum-exchange", "onebit",
            f"1-bit {type(self.optimizer).__name__} compressed-exchange phase",
        )
        log_dist(
            f"1-bit {type(self.optimizer).__name__}: entering compressed-exchange "
            f"phase at step {self._host_global_step} "
            f"(freeze_step={self.optimizer.freeze_step}, dp_ranks={n})"
        )

    def _exit_onebit_frozen(self) -> None:
        """Frozen → warmup layout (pre-freeze checkpoint rollback): the
        values are about to be overwritten by the restore, so fresh
        zero-initialized warm state with the right shapes suffices."""
        params = self.state["params"]
        opt_state = jax.eval_shape(self.optimizer.init, params)
        self._opt_specs = opt_state_specs(opt_state, params, self.zero_rules)
        opt_sh = jax.tree.map(self._sh, self._opt_specs, is_leaf=lambda x: isinstance(x, P))
        self.state["opt_state"] = jax.jit(self.optimizer.init, out_shardings=opt_sh)(params)
        self._state_shardings["opt_state"] = opt_sh
        if not self._lazy_grad_acc:
            grad_sh = jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda x: isinstance(x, P))
            self.state["grad_acc"] = jax.jit(
                lambda p: jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p),
                out_shardings=grad_sh,
            )(params)
            self._state_shardings["grad_acc"] = grad_sh
        self._purge_train_executables()
        self._onebit_frozen = False
        log_dist(
            f"1-bit {type(self.optimizer).__name__}: rolled back to warmup "
            "(pre-freeze) state layout"
        )

    def _purge_train_executables(self) -> None:
        """Drop compiled steps that close over opt-state layout or
        loss-scaler constants (1-bit phase transitions, divergence-guard
        loss-scale-floor changes)."""
        self._compiled = {
            k: v
            for k, v in self._compiled.items()
            if not (isinstance(k, tuple) and k[0] in ("train_batch", "train_batches"))
            and k not in ("micro_step", "apply_step")
        }

    def _frozen_full_step(self, state, stacked):
        """Compiled train step for the compressed phase: per-rank grads
        stay unreduced; only 1-bit momentum crosses the wire."""
        from deepspeed_tpu.runtime.fp16.onebit.adam import pack_flat, pack_rows, unpack_flat

        n = self.mesh_info.dp_world_size  # exchange rows = full dp grid
        axes = self._onebit_exchange_axes()
        gas = self.gradient_accumulation_steps
        mp = state["opt_state"].m_signs.shape[0]
        row_sh = self._sh(dp_rows_spec(axes))
        acc0 = jax.lax.with_sharding_constraint(jnp.zeros((n, mp), jnp.float32), row_sh)

        def body(carry, mb):
            st, acc = carry
            rng = jax.random.fold_in(st["rng"], st["micro_step"])

            def rows_of(x):
                return x.reshape((n, x.shape[0] // n) + x.shape[1:])

            b_rows = jax.tree.map(rows_of, mb)

            def slice_loss(p, b, r):
                return self._compute_loss(p, b, r, st["loss_scale"])

            # independent rng per DP slice — dropout noise must not
            # repeat across the n slices of the global batch
            (_, loss), g = jax.vmap(
                jax.value_and_grad(slice_loss, has_aux=True), in_axes=(None, 0, 0)
            )(st["params"], b_rows, jax.random.split(rng, n))
            g_rows = jax.lax.with_sharding_constraint(pack_rows(g, n, n), row_sh)
            st = dict(st)
            st["micro_step"] = st["micro_step"] + 1
            st["global_samples"] = (
                st["global_samples"]
                + self.train_micro_batch_size_per_gpu * self.mesh_info.dp_world_size
            )
            return (st, acc + g_rows), jnp.mean(loss)

        (state, acc), losses = jax.lax.scan(body, (state, acc0), stacked)
        scale = self.loss_scaler.scale_loss(jnp.float32(1.0), state["loss_scale"])
        g_rows = acc / (gas * scale)
        overflow = ~jnp.isfinite(jnp.sum(g_rows))
        # Per-rank local-gradient norms — the reference's clipping
        # semantics under 1-bit (unfused_optimizer.py:187-226 computes
        # get_grad_norm over the rank's own grads before they fold into
        # the momentum; no full-precision cross-rank reduction, so the
        # wire stays 1-bit).  The scalar row norms do cross ranks (bytes
        # ≈ 4n, noise next to the exchange itself).
        row_norms = jnp.sqrt(jnp.sum(g_rows * g_rows, axis=1))  # (n,)
        grad_norm = jnp.sqrt(jnp.mean(row_norms * row_norms))
        if self.config.gradient_clipping > 0.0:
            clip = jnp.minimum(
                1.0, self.config.gradient_clipping / (row_norms + 1e-6)
            )
            g_rows = g_rows * clip[:, None]
        lr = jnp.asarray(self.lr_schedule(state["global_step"]), jnp.float32)
        p_flat = pack_flat(state["params"], n)
        upd, new_opt = self.optimizer.frozen_apply(
            g_rows, state["opt_state"], p_flat, lr, self.mesh, axes
        )
        state = dict(state)
        state["params"] = unpack_flat(jnp.where(overflow, p_flat, p_flat + upd), state["params"])
        state["opt_state"] = jax.tree.map(
            lambda old, new: jnp.where(overflow, old, new), state["opt_state"], new_opt
        )
        state["global_step"] = state["global_step"] + jnp.where(overflow, 0, 1)
        state["loss_scale"] = self.loss_scaler.update(state["loss_scale"], overflow)
        info = {"lr": lr, "grad_norm": grad_norm, "overflow": overflow}
        return state, jnp.mean(losses), info

    def _save_host_optimizer(self, ckpt_dir: str) -> None:
        """Persist host-resident optimizer state (per-shard npz files)."""
        if self._host_opt is None:
            return
        if self._offload_shards <= 1:
            self._host_opt.save(os.path.join(ckpt_dir, f"host_optimizer_rank{jax.process_index()}.npz"))
            return
        for j, i in enumerate(self._host_shard_ids):
            self._host_opts[j].save(os.path.join(ckpt_dir, f"host_optimizer_shard{i}.npz"))

    def _load_host_optimizer(self, ckpt_dir: str, restored_params, use_files: bool = True) -> None:
        """Restore host optimizer state; if the tag has none (saved by a
        non-offload run) or ``use_files`` is off, rebuild fp32 masters
        from the restored params."""
        if self._host_opt is None:
            return
        exists = lambda p: use_files and os.path.exists(p)

        def warn_if_other_layout(expected: str):
            import glob

            others = glob.glob(os.path.join(ckpt_dir, "host_optimizer_*.npz"))
            if others:
                logger.warning(
                    f"host optimizer state {expected} not found, but the tag has "
                    f"{[os.path.basename(o) for o in others]} — the checkpoint was "
                    "saved under a different offload shard layout (process count / "
                    "DS_OFFLOAD_SHARDS); Adam moments are being RESET from params"
                )

        if self._offload_shards <= 1:
            path = os.path.join(ckpt_dir, f"host_optimizer_rank{jax.process_index()}.npz")
            if exists(path):
                self._host_opt.load(path)
            else:
                if use_files:
                    warn_if_other_layout(os.path.basename(path))
                self._host_opt.load_masters(jax.tree.map(np.asarray, restored_params))
            return
        from deepspeed_tpu.runtime.fp16.onebit.adam import pack_flat

        flat = None
        for j, i in enumerate(self._host_shard_ids):
            path = os.path.join(ckpt_dir, f"host_optimizer_shard{i}.npz")
            if exists(path):
                self._host_opts[j].load(path)
            else:
                if use_files:
                    warn_if_other_layout(os.path.basename(path))
                if flat is None:
                    flat = np.asarray(
                        pack_flat(jax.tree.map(np.asarray, restored_params), self._offload_shards)
                    )
                L = self._offload_slice_len
                self._host_opts[j].load_masters({"flat": flat[i * L : (i + 1) * L]})

    def _sharded_host_step(self, g_np, unscaled_leaves, lr, step_count):
        """Step only this host's flat master slice(s) and reassemble the
        full masters — the multi-host ZeRO-Offload path (each process
        allgather-joins its 1/P slice).  With DS_OFFLOAD_SHARDS in one
        process, every slice is stepped locally (same math, testable)."""
        from deepspeed_tpu.runtime.fp16.onebit.adam import unpack_flat

        P_shards = self._offload_shards
        L = self._offload_slice_len
        flat_g = np.concatenate([np.asarray(l, np.float32).reshape(-1) for l in unscaled_leaves])
        pad = (-flat_g.shape[0]) % P_shards
        if pad:
            flat_g = np.concatenate([flat_g, np.zeros(pad, np.float32)])
        slices = {}
        for j, i in enumerate(self._host_shard_ids):
            mt = self._host_opts[j].step({"flat": flat_g[i * L : (i + 1) * L]}, lr, step_count)
            slices[i] = mt["flat"]
        if jax.process_count() > 1:
            # masters reassembly routes through the comm layer (dense
            # host allgather of fp32 slices; supervision-armed)
            with self._sup_region("offload.masters_allgather"):
                stacked = np.asarray(
                    self.comm.host_allgather(slices[self._host_shard_ids[0]])
                )
            full = stacked.reshape(-1)
        else:
            full = np.concatenate([slices[i] for i in sorted(slices)])
        return unpack_flat(full, self.state["params"])

    # ------------------------------------------------------------------
    # unified comm layer (docs/comm.md)
    # ------------------------------------------------------------------
    def _init_comm_layer(self, config) -> None:
        """Build the strategy-selected comm layer and resolve the
        gradient-exchange strategy ONCE, at trace-decision time: dense
        keeps the GSPMD constraint path untouched; int8 / onebit switch
        ``train_batch`` to the explicit per-rank-rows step
        (:meth:`_comm_full_step`).  The onebit strategy's error-feedback
        residual rows live in ``state['comm']`` and ride checkpoints
        with the rest of the state."""
        from deepspeed_tpu.comm.strategy import STRATEGY_DENSE, STRATEGY_ONEBIT, CommLayer
        from deepspeed_tpu.config.config import CommConfig

        self.comm = CommLayer(
            self.mesh, self.mesh_info, getattr(config, "comm", None) or CommConfig(),
            zero_config=config.zero_config, topology=self.topology,
        )
        # satellite: the previously-unwired reduce_scatter flag is now
        # honored by ZeroShardingRules.grad_spec; warn once when it
        # forces the dense all-reduce path (reference stage2 fallback)
        if (
            config.zero_config.stage >= 2
            and self.mesh_info.fsdp_world_size > 1
            and not config.zero_config.reduce_scatter
        ):
            self.comm.note(
                "zero-grad-reduce", STRATEGY_DENSE,
                "zero_optimization.reduce_scatter=false forces the dense all-reduce path",
            )
            logger.warning(
                "zero_optimization.reduce_scatter=false: gradient reduction stays a "
                "full all-reduce (grads replicated over fsdp) — ~2x the wire bytes "
                "and a params-sized grad buffer per chip (the reference's stage2 "
                "allreduce fallback); drop the flag to restore the psum_scatter path"
            )
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.state["params"]))
        self._comm_n_params = n_params
        n = max(1, self.mesh_info.dp_world_size)
        self._comm_flat_len = -(-n_params // n) * n
        axes = self._dp_exchange_axes()
        want = self.comm.select(4 * n_params, jnp.float32, axes, site="grad-exchange")
        explicit = want != STRATEGY_DENSE
        if explicit:
            blockers = {
                "data-parallel grid must be > 1": self.mesh_info.dp_world_size > 1,
                "pipeline engine unsupported": getattr(self, "_use_grad_acc", True),
                "offload_optimizer unsupported": not self._offload,
                "1-bit optimizer owns its own exchange": not self._onebit_exchange_ok,
            }
            failed = [k for k, ok in blockers.items() if not ok]
            if failed:
                logger.warning(
                    f"comm: '{want}' gradient exchange requested but DISABLED "
                    f"({'; '.join(failed)}); falling back to dense"
                )
                self.comm.note("grad-exchange", STRATEGY_DENSE, f"forced dense: {'; '.join(failed)}")
                want, explicit = STRATEGY_DENSE, False
        self._comm_grad_strategy = want
        self._comm_explicit = explicit
        self.state["comm"] = {}
        self._state_shardings["comm"] = {}
        if explicit:
            # the explicit path accumulates into its own (n, Mp) rows
            # buffer inside the compiled step — free the params-sized
            # fp32 accumulator (as the 1-bit frozen phase does)
            self.state["grad_acc"] = {}
            self._state_shardings["grad_acc"] = {}
            mp = self._comm_flat_len
            if want == STRATEGY_ONEBIT and self.comm.config.error_feedback:
                row_sh = self._sh(dp_rows_spec(axes))
                comm_sh = {"worker_error": row_sh, "server_error": row_sh}
                self.state["comm"] = jax.jit(
                    lambda: {
                        "worker_error": jnp.zeros((n, mp), jnp.float32),
                        "server_error": jnp.zeros((n, mp // n), jnp.float32),
                    },
                    out_shardings=comm_sh,
                )()
                self._state_shardings["comm"] = comm_sh
            log_dist(
                f"comm: '{want}' gradient exchange over {axes} "
                f"(n={n} ranks, {mp} padded coords, "
                f"{'EF residuals in state' if self.state['comm'] else 'stateless'})"
            )
        summ = self.comm_summary()
        self.timeline.set_comm(summ["strategy"], summ["grad_exchange_bytes"])
        if self.telemetry is not None:
            self.telemetry.set_comm(summ)

    def train_step_attribution(self):
        """The compiled train step's per-kernel cost table
        (:class:`~deepspeed_tpu.telemetry.attribution.Attribution`), or
        None before the first compile / when the plane is disabled —
        surfaced by ds_report, bench records, and the perf-sentinel
        roofline artifact."""
        return self.telemetry.attribution() if self.telemetry is not None else None

    def comm_summary(self) -> Dict[str, Any]:
        """Active comm-strategy table + the per-step comm-bytes model
        (docs/comm.md) — surfaced by ds_report and bench.py records."""
        from deepspeed_tpu.comm.strategy import step_comm_bytes

        model = step_comm_bytes(
            self._comm_n_params,
            self.mesh_info.sizes,
            stage=self.zero_stage,
            gas=self.gradient_accumulation_steps,
            strategy=self._comm_grad_strategy,
            reduce_scatter=self.config.zero_config.reduce_scatter,
            topology=self.topology,
        )
        return {
            "strategy": self._comm_grad_strategy,
            "grad_exchange_bytes": model["grad-exchange"],
            "model": model,
            "table": self.comm.table(),
        }

    def _comm_full_step(self, state, stacked):
        """Compiled train step for the explicit compressed gradient
        exchange (comm.strategy int8 / onebit): per-rank gradients stay
        UNREDUCED as (n, Mp) rows accumulated across micro batches; ONE
        strategy-compressed exchange per step replaces the per-micro
        dense psum, then the dense-identical unscaled update applies
        (clipping on the exchanged average — dense semantics, so the
        loss trajectory stays comparable)."""
        from deepspeed_tpu.runtime.fp16.onebit.adam import pack_rows, unpack_flat

        n = self.mesh_info.dp_world_size
        axes = self._dp_exchange_axes()
        gas = self.gradient_accumulation_steps
        mp = self._comm_flat_len
        row_sh = self._sh(dp_rows_spec(axes))
        acc0 = jax.lax.with_sharding_constraint(jnp.zeros((n, mp), jnp.float32), row_sh)

        def body(carry, mb):
            st, acc = carry
            if self.progressive_layer_drop is not None and isinstance(mb, dict):
                from deepspeed_tpu.runtime.progressive_layer_drop import PLD_THETA_KEY

                mb = dict(mb)
                mb[PLD_THETA_KEY] = self.progressive_layer_drop.get_theta(st["global_step"])
            rng = jax.random.fold_in(st["rng"], st["micro_step"])

            def rows_of(x):
                return x.reshape((n, x.shape[0] // n) + x.shape[1:])

            b_rows = jax.tree.map(rows_of, mb)

            def slice_loss(p, b, r):
                return self._compute_loss(p, b, r, st["loss_scale"])

            # independent rng per DP slice (dropout must differ per slice)
            (_, loss), g = jax.vmap(
                jax.value_and_grad(slice_loss, has_aux=True), in_axes=(None, 0, 0)
            )(st["params"], b_rows, jax.random.split(rng, n))
            g_rows = jax.lax.with_sharding_constraint(pack_rows(g, n, n), row_sh)
            st = dict(st)
            st["micro_step"] = st["micro_step"] + 1
            st["global_samples"] = (
                st["global_samples"]
                + self.train_micro_batch_size_per_gpu * self.mesh_info.dp_world_size
            )
            return (st, acc + g_rows), jnp.mean(loss)

        (state, acc), losses = jax.lax.scan(body, (state, acc0), stacked)
        scale = self.loss_scaler.scale_loss(jnp.float32(1.0), state["loss_scale"])
        g_rows = acc / (gas * scale)
        overflow = ~jnp.isfinite(jnp.sum(g_rows))
        # quantizing an inf row would poison every rank's output AND the
        # EF residuals; the overflow flag above already discards the step
        g_rows = jnp.where(jnp.isfinite(g_rows), g_rows, 0.0)
        state = dict(state)
        if self._comm_grad_strategy == "onebit" and self.state["comm"]:
            werr = state["comm"]["worker_error"]
            serr = state["comm"]["server_error"]
            g_mean, new_res = self.comm.exchange_rows(
                g_rows, axes, "onebit", residuals=(werr, serr)
            )
            state["comm"] = {
                "worker_error": jnp.where(overflow, werr, new_res[0]),
                "server_error": jnp.where(overflow, serr, new_res[1]),
            }
        else:
            # int8 stochastic rounding (or EF-less onebit) needs fresh
            # bits each step; fold the step counter so replays differ
            rng = jax.random.fold_in(state["rng"], state["global_step"] + 777_001)
            g_mean, _ = self.comm.exchange_rows(
                g_rows, axes, self._comm_grad_strategy, rng=rng
            )
        grads = unpack_flat(g_mean, state["params"])  # params are fp32 masters
        grads = self.comm.constrain_grads(
            grads,
            jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda x: isinstance(x, P)),
            site="grad-specs",
        )
        state, info = self._apply_update_unscaled(state, grads, overflow)
        return state, jnp.mean(losses), info

    # ------------------------------------------------------------------
    # public training API
    # ------------------------------------------------------------------
    def _stacked_sharding(self, ndim_stacked: int):
        return self._sh(
            stacked_batch_pspec(ndim_stacked, seq_sharded=self.mesh_info.seq_parallel_world_size > 1)
        )

    def _stack_and_place(self, batch: Any) -> Any:
        """Reshape a flat (gas·mb, ...) batch to (gas, mb, ...) and place
        it with the engine's batch sharding.  Batches already processed
        (wrapped in ``_PlacedBatch`` by ``prefetch_loader``) unwrap and
        pass straight through — no shape heuristics."""
        if isinstance(batch, _PlacedBatch):
            return batch.tree
        gas = self.gradient_accumulation_steps
        leaves = jax.tree.leaves(batch)
        if (
            leaves
            and np.ndim(leaves[0]) >= 1
            and not getattr(self, "_batch_mismatch_warned", False)
        ):
            fed = np.shape(leaves[0])[0]
            expect = gas * self.train_micro_batch_size_per_gpu * self.mesh_info.dp_world_size
            if fed != expect:
                # a config/batch mismatch silently changes the effective
                # micro-batch (shape[0] // gas wins below) and every
                # per-chip throughput normalization drifts with it —
                # surface it once; callers that need the hard guarantee
                # pin train_batch_size to the fed shape (see
                # tools/bench_long_context.py)
                self._batch_mismatch_warned = True
                logger.warning(
                    f"train_batch fed {fed} samples but the config triad says "
                    f"train_batch_size = gas({gas}) × micro_bs("
                    f"{self.train_micro_batch_size_per_gpu}) × dp("
                    f"{self.mesh_info.dp_world_size}) = {expect}; proceeding with "
                    f"effective global micro-batch {fed // gas} — per-chip "
                    "throughput normalizations will not match the config"
                )

        def one(x):
            x = np.asarray(x) if not isinstance(x, (jax.Array, np.ndarray)) else x
            mb = x.shape[0] // gas
            x = x.reshape((gas, mb) + x.shape[1:])
            return jax.device_put(x, self._stacked_sharding(np.ndim(x)))

        return jax.tree.map(one, batch)

    def prefetch_loader(self, loader, prefetch_depth: Optional[int] = None):
        """Wrap a host batch iterator so loader pulls and stacking +
        sharded device placement run ahead of the compiled step as a
        two-stage pipeline (runtime/overlap ``DevicePrefetcher``); feed
        the result to ``train_batch``.  ``prefetch_depth`` defaults to
        the ``overlap.prefetch.depth`` config (2 = double buffering);
        with ``overlap.prefetch.enabled = false`` the wrap is a
        synchronous pass-through (A/B knob for measuring the overlap) —
        unless the caller passes ``prefetch_depth`` explicitly, which is
        a direct API request for background prefetch and wins over the
        config default."""
        from deepspeed_tpu.runtime.overlap import DevicePrefetcher, InlineLoader

        place = lambda b: _PlacedBatch(self._stack_and_place(b))  # noqa: E731
        if not self.overlap.prefetch.enabled and prefetch_depth is None:
            return self.register_dataloader(InlineLoader(
                loader, place, timeline=self.timeline, sanitizer=self._sanitizer
            ))
        depth = self.overlap.prefetch.depth if prefetch_depth is None else int(prefetch_depth)
        return self.register_dataloader(DevicePrefetcher(
            loader, depth=depth, place_fn=place, timeline=self.timeline,
            sanitizer=self._sanitizer,
        ))

    def _prepare_batch(self, batch: Any) -> Any:
        def put(x):
            x = np.asarray(x) if not isinstance(x, (jax.Array, np.ndarray)) else x
            sh = self._sh(batch_pspec(np.ndim(x), seq_sharded=self.mesh_info.seq_parallel_world_size > 1))
            return jax.device_put(x, sh)

        return jax.tree.map(put, batch)

    def forward(self, batch: Any) -> jnp.ndarray:
        """Fused forward+backward on one micro-batch; returns the loss.

        Deviation from the reference (engine.py:1089): JAX autodiff cannot
        be split across Python calls, so gradients are produced here and
        folded into the accumulator; ``backward()`` validates ordering.
        """
        if self._onebit_frozen:
            raise RuntimeError(
                "the 1-bit compressed phase runs whole batches (its gradient "
                "accumulator lives inside the compiled step); use train_batch()"
            )
        if self._comm_explicit:
            raise RuntimeError(
                f"comm.strategy '{self._comm_grad_strategy}' runs whole batches "
                "(the per-rank gradient rows live inside the compiled step); "
                "use train_batch()"
            )
        if self._lazy_grad_acc and not self.state["grad_acc"]:
            # the micro API needs the accumulator train_batch's gas==1
            # fused path avoids; allocate it on first use
            acc_sh = jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda x: isinstance(x, P))
            self.state["grad_acc"] = jax.jit(
                lambda p: jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p),
                out_shardings=acc_sh,
            )(self.state["params"])
            self._state_shardings["grad_acc"] = acc_sh
        if self.wall_clock_breakdown:
            self.timers(FORWARD_TIMER).start()
        with self.timeline.phase("data_wait"):
            batch = self._prepare_batch(batch)
        fn = self._get_compiled(
            "micro_step", self._micro_step_impl,
            out_shardings=(self._state_shardings, self._sh(P())),
        )
        san = self._sanitizer
        donated = jax.tree.leaves(self.state) if san is not None else None
        t_compute = time.perf_counter()
        with san.transfer.guard("engine.forward") if san is not None else nullcontext():
            self.state, loss = fn(self.state, batch)
        if san is not None:
            san.donation.note(donated, "engine.forward", step=self._host_global_step)
            self._san_last_batch = ("micro", batch)
        if self.timeline.enabled and self._timeline_fence:
            jax.block_until_ready(loss)
            self.timeline.note("compute", time.perf_counter() - t_compute)
        self._host_micro_step += 1
        self._cached_loss = loss
        self._last_loss = loss  # step()'s divergence check_loss reads this
        if self.wall_clock_breakdown:
            self.timers(FORWARD_TIMER).stop(sync_token=loss)
        return loss

    __call__ = forward

    def backward(self, loss: Any = None, allreduce_gradients: bool = True) -> Any:
        """Grad accumulation already happened in ``forward``; this is the
        ordering checkpoint (and the place a future pipeline engine hooks)."""
        if self._cached_loss is None:
            raise RuntimeError("backward() called before forward()")
        if self.wall_clock_breakdown:
            self.timers(BACKWARD_TIMER).start()
            self.timers(BACKWARD_TIMER).stop()
        loss = self._cached_loss
        self._cached_loss = None
        return loss

    def allreduce_gradients(self, bucket_size: int = MEMORY_OPT_ALLREDUCE_SIZE) -> None:
        """Reference API shim (engine.py:1147).  Gradient reduction is
        in-graph here: ``psum``/``psum_scatter`` over the data/fsdp axes
        are inserted by GSPMD from the grad sharding constraints
        (zero/stages.py) — there is nothing to launch from the host, and
        bucketing/overlap are XLA scheduler decisions."""
        return None

    def step(self) -> None:
        """Apply the optimizer step at the gradient-accumulation boundary
        (reference engine.step, :1318)."""
        if self.wall_clock_breakdown:
            self.timers(STEP_TIMER).start()
        if self.is_gradient_accumulation_boundary():
            if self._offload:
                info = self._host_apply_step()
            else:
                # pin the output state to the declared layout: the
                # cross-replica update computes over dp-sharded state,
                # and without the pin GSPMD would keep the updated
                # params dp-sharded too (sharding drift vs the declared
                # replicated param spec; the pin is where the one
                # updated-params all-gather lands)
                scalar = self._sh(P())
                fn = self._get_compiled(
                    "apply_step", self._apply_step_impl,
                    out_shardings=(self._state_shardings,
                                   {"lr": scalar, "grad_norm": scalar, "overflow": scalar}),
                )
                san = self._sanitizer
                donated = jax.tree.leaves(self.state) if san is not None else None
                with self._sup_region("engine.step"):
                    with san.transfer.guard("engine.step") if san is not None else nullcontext():
                        self.state, info = fn(self.state)
                if san is not None:
                    san.donation.note(donated, "engine.step", step=self._host_global_step)
            overflowed = False
            if self.loss_scaler.dynamic:
                # explicit d2h read: the deliberate once-per-step host
                # sync must not look like an implicit transfer under the
                # sanitizer's guard (and on remote backends device_get
                # batches better than __bool__)
                with self._sup_region("engine.overflow_sync"):
                    overflowed = bool(jax.device_get(info["overflow"]))
                if overflowed:
                    self.skipped_steps += 1
                    log_dist(f"step skipped on overflow; loss scale -> {self.loss_scale}")
                elif not self._offload:
                    self._host_global_step += 1
            elif not self._offload:
                self._host_global_step += 1
            self._maybe_report_progress()
            self._on_step_boundary(overflowed, loss=self._last_loss)
            self.timeline.end_step()
        if self.wall_clock_breakdown:
            self.timers(STEP_TIMER).stop(sync_token=self.state["global_step"])
            self.timers.log([FORWARD_TIMER, BACKWARD_TIMER, STEP_TIMER])

    def train_batch(self, batch: Any) -> jnp.ndarray:
        """One full global batch — all GAS micro-batches + optimizer step in
        a single compiled program (lax.scan over micro-batches).

        ``batch`` leaves must have leading dim ``gas * micro_batch`` (one
        full train_batch worth of per-replica samples) or ``micro_batch``
        (gas==1).  Batches already stacked/placed by
        ``prefetch_loader()`` pass through untouched (no re-put — on
        remote TPU backends ``device_put`` is a synchronous host RPC and
        must stay off the hot path).
        """
        self.tput_timer.start()
        if (
            self._onebit_exchange_ok
            and not self._onebit_frozen
            and self._host_global_step >= self.optimizer.freeze_step
        ):
            self._enter_onebit_frozen()
        san = self._sanitizer
        was_placed = isinstance(batch, _PlacedBatch)
        t_place = time.perf_counter()
        with san.transfer.guard("engine.train_batch.place") if san is not None else nullcontext():
            stacked = self._stack_and_place(batch)
        if not was_placed:
            # prefetched batches had their wait noted by the prefetcher
            self.timeline.note("data_wait", time.perf_counter() - t_place)

        tb_key = (
            "train_batch",
            self._onebit_frozen,
            bool(self.state["grad_acc"]),
            tuple(np.shape(x) for x in jax.tree.leaves(stacked)),
        )
        if tb_key not in self._compiled:
            apply_in_graph = not self._offload
            full_step = self._full_step_fn()

            # AOT compile: the executable's cost_analysis feeds the flops
            # profiler for free (no second trace/compile at profile time).
            # out_shardings pin the output state to the input layout —
            # without them GSPMD may pick different output shardings and
            # the next call would mismatch (plain jit hides that as a
            # silent recompile).
            scalar = self._sh(P())
            if apply_in_graph:
                out_sh = (self._state_shardings, scalar,
                          {"lr": scalar, "grad_norm": scalar, "overflow": scalar})
            else:
                out_sh = (self._state_shardings, scalar)
            with self.timeline.phase("compile"):
                executable = (
                    jax.jit(self._scoped(full_step), donate_argnums=(0,), out_shardings=out_sh)
                    .lower(self.state, stacked)
                    .compile()
                )
            self._compiled[tb_key] = executable
            self.compilation_count += 1
            # ds_shard Pass 1/2 feed (no-op unless the audit armed it)
            shard_hooks.note_train(self, "train.train_batch", executable,
                                   fn=self._scoped(full_step),
                                   args=(self.state, stacked),
                                   out_state_shardings=out_sh[0])
            if san is not None:
                # signature of exactly what was lowered: a recount here
                # names the state/batch leaf whose shape/dtype/sharding
                # drifted since the last executable was built
                san.recompile.note("engine.train_batch", (self.state, stacked), owner=id(self))
            try:
                cost = executable.cost_analysis() or {}
                if isinstance(cost, list):
                    cost = cost[0] if cost else {}
                self._train_step_cost = {k: float(v) for k, v in cost.items() if np.isscalar(v)}
            except Exception:
                self._train_step_cost = {}
            if self.telemetry is not None:
                # the compiled step's cost analysis is the numerator of
                # the live MFU / HBM-GB/s gauges (docs/telemetry.md)
                self.telemetry.set_step_cost(self._train_step_cost)
                # per-kernel cost attribution (docs/telemetry.md
                # §Attribution): one HLO walk per new executable —
                # compile-time only, nothing added to the hot path
                self.telemetry.attribute_compiled(executable, "train_step")
        profile_step = self._host_global_step + 1
        self.flops_profiler.start_step(profile_step)
        donated = jax.tree.leaves(self.state) if san is not None else None
        t_compute = time.perf_counter()
        # supervision: the compiled step is the step-boundary collective
        # (grad psum over the data axis) — the armed deadline plus the
        # peer-death escalation live here (docs/resilience.md)
        with self._sup_region("engine.train_batch"):
            if self._offload:
                with san.transfer.guard("engine.train_batch") if san is not None else nullcontext():
                    self.state, loss = self._compiled[tb_key](self.state, stacked)
                # the host optimizer step is a deliberate host-I/O region
                # (grads device->host, masters host->device) — not guarded
                info = self._host_apply_step()
            else:
                with san.transfer.guard("engine.train_batch") if san is not None else nullcontext():
                    self.state, loss, info = self._compiled[tb_key](self.state, stacked)
        if san is not None:
            san.donation.note(donated, "engine.train_batch", step=self._host_global_step)
            self._san_last_batch = ("stacked", stacked)
        if self.timeline.enabled and self._timeline_fence:
            # fence: XLA dispatch is async — an unfenced delta would only
            # measure Python overhead (ds_lint `unfenced-timing`).  Off
            # (the default without wall_clock_breakdown), no compute note
            # is recorded: host-measurable phases stay honest and the hot
            # path keeps its dispatch pipelining
            jax.block_until_ready(loss)
            self.timeline.note("compute", time.perf_counter() - t_compute)
        self.flops_profiler.end_step(profile_step, cost=self._train_step_cost, sync_token=loss)
        self._last_loss = loss
        self._last_info = info  # lr / grad_norm / overflow of this step
        # host sync on the overflow flag only when dynamic scaling is live
        # (explicit device_get: a deliberate sync, not an implicit
        # transfer — the sanitizer's guard budget stays honest)
        overflowed = False
        if self.loss_scaler.dynamic:
            # the overflow read is where the host actually BLOCKS on the
            # cross-process step (dispatch above is async) — armed too
            with self._sup_region("engine.overflow_sync"):
                overflowed = bool(jax.device_get(info["overflow"]))
            if overflowed:
                self.skipped_steps += 1
                log_dist(f"step skipped on overflow; loss scale -> {self.loss_scale}")
            elif not self._offload:
                self._host_global_step += 1
        elif not self._offload:
            self._host_global_step += 1
        self._host_micro_step += self.gradient_accumulation_steps
        self.tput_timer.stop(sync_token=loss)
        self._maybe_report_progress()
        self._on_step_boundary(overflowed, loss=loss)
        self.timeline.end_step()
        return loss

    def _full_step_fn(self) -> Callable:
        """One full train step as a pure function ``(state, stacked) ->
        (state, loss[, info])`` — the unit ``train_batch`` compiles and
        ``train_batches`` scans.  With offload, the program ends after
        the micro-batch scan (the optimizer step runs on host — ZeRO-
        Offload splits exactly here)."""
        apply_in_graph = not self._offload
        if self._onebit_frozen:
            return self._frozen_full_step
        if self._comm_explicit:
            return self._comm_full_step
        if apply_in_graph and self._use_grad_acc and not self.state["grad_acc"]:
            # gas==1 fused path (no persistent accumulator was
            # allocated): grads flow straight into the update
            def full_step(state, stacked):
                mb = jax.tree.map(lambda x: jnp.squeeze(x, 0), stacked)
                state, loss, grads = self._micro_grads(state, mb)
                state, info = self._apply_update(state, grads)
                return state, loss, info

            return full_step

        def full_step(state, stacked):
            def body(st, mb):
                return self._micro_step_impl(st, mb)

            state, losses = jax.lax.scan(body, state, stacked)
            if apply_in_graph:
                state, info = self._apply_step_impl(state)
                return state, jnp.mean(losses), info
            return state, jnp.mean(losses)

        return full_step

    def train_batches(self, batches, unroll=False) -> np.ndarray:
        """Run N full train steps in ONE compiled program — a
        ``lax.scan`` of the train step over a stacked run of batches.

        TPU-idiomatic driver loop: per-program dispatch costs (host RPC
        latency, argument marshalling — ~10-30 ms/step through remote
        runtimes) amortize over the whole run, the way t5x/pax drive
        entire loops inside one program.  Semantics are identical to
        calling ``train_batch`` N times: same grads, same updates, same
        overflow skipping; per-step losses return as one (N,) array.

        Not available with host offload (the optimizer step leaves the
        graph) or across the 1-bit warmup→frozen transition (the state
        layout changes mid-run) — those fall back to the per-step loop.

        ``unroll``: False = plain ``lax.scan`` (one XLA while loop,
        carry double-buffered per iteration); True = fully unrolled
        (no loop, n× graph); an int k >= 2 = partial unroll (k step
        bodies per while iteration — carry copies amortize 1/k at k×
        graph size); k == 1 is the plain scan, identical to False
        (bench.py's ``DS_TB_UNROLL`` uses the same convention, with
        ``full`` as the full-unroll sentinel).
        """
        batches = list(batches)
        n = len(batches)
        if n == 0:
            return np.zeros((0,), np.float32)
        crosses_freeze = (
            self._onebit_exchange_ok
            and not self._onebit_frozen
            and self._host_global_step + n > getattr(self.optimizer, "freeze_step", 0)
        )
        if self._offload or crosses_freeze or n == 1:
            return np.asarray([float(self.train_batch(b)) for b in batches], np.float32)
        self.tput_timer.start()
        with self.timeline.phase("data_wait"):
            stacked = [self._stack_and_place(b) for b in batches]
            run = jax.tree.map(lambda *xs: jnp.stack(xs), *stacked)
        san = self._sanitizer
        unroll_k = n if unroll is True else max(1, min(int(unroll), n))
        key = (
            "train_batches", n, unroll_k, self._onebit_frozen, bool(self.state["grad_acc"]),
            tuple(np.shape(x) for x in jax.tree.leaves(run)),
        )
        if key not in self._compiled:
            full_step = self._full_step_fn()

            def full_run(state, run):
                def body(st, stk):
                    st, loss, info = full_step(st, stk)
                    return st, (loss, info["overflow"], info["lr"], info["grad_norm"])

                # unroll=n removes the while-loop: no carry double-buffer
                # copies of the big state, at the cost of an n× graph
                state, (losses, ovf, lrs, gns) = jax.lax.scan(
                    body, state, run, unroll=unroll_k
                )
                return state, losses, jnp.sum(ovf.astype(jnp.int32)), lrs[-1], gns[-1]

            scalar = self._sh(P())
            with self.timeline.phase("compile"):
                self._compiled[key] = (
                    jax.jit(
                        self._scoped(full_run), donate_argnums=(0,),
                        out_shardings=(self._state_shardings, scalar, scalar, scalar, scalar),
                    )
                    .lower(self.state, run)
                    .compile()
                )
            self.compilation_count += 1
            if san is not None:
                san.recompile.note("engine.train_batches", (self.state, run), owner=id(self))
        donated = jax.tree.leaves(self.state) if san is not None else None
        t_compute = time.perf_counter()
        with san.transfer.guard("engine.train_batches") if san is not None else nullcontext():
            self.state, losses, ovf_count, last_lr, last_gn = self._compiled[key](self.state, run)
        if san is not None:
            san.donation.note(donated, "engine.train_batches", step=self._host_global_step)
            self._san_last_batch = ("stacked", stacked[-1])
        # explicit d2h reads (materializing losses = the compute fence)
        losses = np.asarray(jax.device_get(losses))
        self.timeline.note("compute", time.perf_counter() - t_compute)
        skipped = int(jax.device_get(ovf_count))
        if self.loss_scaler.dynamic:
            self.skipped_steps += skipped
            self._host_global_step += n - skipped
        else:
            self._host_global_step += n  # matches the per-step loop's host count
        self._host_micro_step += n * self.gradient_accumulation_steps
        # progress reports read these — same dict shape as the per-step
        # loop (lr/grad_norm from the LAST step of the run).  NB the
        # step_per_print/monitor cadence coalesces: boundaries crossed
        # strictly inside the run emit one report at run end
        self._last_loss = losses[-1]
        self._last_info = {"lr": last_lr, "grad_norm": last_gn, "overflow": skipped > 0}
        self.tput_timer.stop(sync_token=losses[-1] if len(losses) else None)
        self._maybe_report_progress()
        # the compiled run only exposes the skip COUNT, not per-step order:
        # a fully-skipped run provably contains n consecutive skips (feed
        # the guard one record per step so n >= threshold trips it within
        # the run); partially-skipped runs reset the streak
        records = n if skipped == n else 1
        guard = getattr(self, "_divergence_guard", None)
        trips_before = guard.trips if guard is not None else 0
        for i in range(records):
            self._on_step_boundary(
                skipped == n, loss=self._last_loss if i == records - 1 else None
            )
            if guard is not None and guard.trips > trips_before:
                break  # one action per detection, not one per threshold-multiple
        self.timeline.end_step(count=n)
        return losses

    def eval_batch(self, batch: Any) -> Any:
        batch = self._prepare_batch(batch)
        if "eval" not in self._compiled:

            def eval_fn(state, b):
                # rng=None ⇒ deterministic eval (model convention)
                _, loss = self._compute_loss(state["params"], b, None, state["loss_scale"])
                return loss

            self._compiled["eval"] = jax.jit(self._scoped(eval_fn))
        return self._compiled["eval"](self.state, batch)

    def predict(self, batch: Any) -> Any:
        """Raw model outputs (inference forward)."""
        batch = self._prepare_batch(batch)
        if "predict" not in self._compiled:

            def pred_fn(state, b):
                cparams = self._materialize_params(state["params"], self.compute_dtype)
                return self._model_fn(cparams, b, None)

            self._compiled["predict"] = jax.jit(self._scoped(pred_fn))
        return self._compiled["predict"](self.state, batch)

    def _maybe_report_progress(self):
        step = self._host_global_step
        if self.quantizer is not None:
            self.quantizer.maybe_log(step)
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(step)
        if step > 0 and step % self.config.steps_per_print == 0:
            log_dist(f"step={step} lr={self.get_lr()[0]:.3e} loss_scale={self.loss_scale:.1f}")
            if self.wall_clock_breakdown and self.timeline.enabled:
                log_dist(self.timeline.format_summary(self.config.steps_per_print))
            tm = self.telemetry
            if tm is not None and (tm.collect or tm.monitor_enabled):
                # loss/lr/loss-scale route through the telemetry
                # registry; the manager forwards the reference
                # Train/Samples/* tags (engine.py:1178-1188, :1356-1382)
                # to the TensorBoard sink unchanged.  The d2h reads are
                # a deliberate report-cadence sync — paid ONLY when a
                # consumer is armed (monitor / sinks / the already-
                # syncing wall_clock_breakdown); the default registry-
                # only path stays transfer-free: samples come from the
                # host step mirror and the loss gauge is skipped.
                sync = tm.monitor_enabled or tm.exports_armed or self.wall_clock_breakdown
                if sync:
                    samples = int(jax.device_get(self.state["global_samples"]))
                    loss = (
                        float(jax.device_get(self._last_loss))
                        if self._last_loss is not None else None
                    )
                else:
                    # micro-step mirror, not global_step: overflow-
                    # skipped steps still CONSUME their samples (the
                    # device global_samples counts them too)
                    samples = (
                        self._host_micro_step
                        * self.config.train_micro_batch_size_per_gpu
                        * self.mesh_info.dp_world_size
                    )
                    loss = None
                tm.publish_train_progress(
                    step=step, samples=samples, loss=loss,
                    lr=float(self.get_lr()[0]), loss_scale=float(self.loss_scale),
                )

    # ------------------------------------------------------------------
    # resilience: preemption + divergence + supervision handling
    # (docs/resilience.md)
    # ------------------------------------------------------------------
    def _note_checkpoint_dir(self, directory: str) -> None:
        """Remember where this run checkpoints (emergency saves and
        divergence rollback target it)."""
        self._resilience_ckpt_dir = os.path.abspath(directory)

    def register_dataloader(self, loader):
        """Register the training loader for resume-cursor round-trips:
        checkpoint saves record its ``state_dict()`` in the client
        state, loads restore it — a restarted job neither replays nor
        skips batches (docs/resilience.md).  ``prefetch_loader`` calls
        this automatically."""
        self._train_loader = loader
        return loader

    def _build_supervisor(self, sv):
        """Construct + start the rank supervisor for the configured side
        channel; None (with a warning) when no channel is reachable."""
        from deepspeed_tpu.resilience.supervision import Supervisor
        from deepspeed_tpu.resilience.supervision import heartbeat as hb

        # the supervision plane is LAUNCHER-scoped, not jax-scoped: a
        # job whose ranks run per-process replicas (no jax.distributed)
        # still has a failure domain, so fall back to the launcher's
        # RANK/WORLD_SIZE env when jax sees a single process
        rank, world = jax.process_index(), jax.process_count()
        if world <= 1:
            rank = int(os.environ.get("RANK", rank))
            world = int(os.environ.get("WORLD_SIZE", world))
        kind = sv.channel
        addr, port = hb.resolve_endpoint()
        if kind == "auto":
            if world > 1 and port:
                kind = "tcp"
            elif sv.beat_dir:
                kind = "file"
            else:
                logger.warning(
                    "resilience.supervision enabled but no side channel is available "
                    "(no DS_SUPERVISION_PORT from the launcher, no supervision.beat_dir); "
                    "supervision stays OFF"
                )
                return None
        if kind == "tcp":
            if not port:
                logger.warning(
                    "resilience.supervision channel 'tcp' needs DS_SUPERVISION_PORT "
                    "(set by launcher/launch.py); supervision stays OFF"
                )
                return None
            channel = hb.TcpBeatChannel(
                rank, world, address=addr, port=port,
                beat_timeout=sv.beat_timeout_seconds,
                connect_grace=sv.connect_grace_seconds,
            )
        else:
            if not sv.beat_dir:
                logger.warning(
                    "resilience.supervision channel 'file' needs supervision.beat_dir; "
                    "supervision stays OFF"
                )
                return None
            channel = hb.FileBeatChannel(
                sv.beat_dir, rank, world, beat_timeout=sv.beat_timeout_seconds
            )
        # telemetry piggyback (docs/telemetry.md): rank-local compact
        # snapshots ride every beat; rank 0 aggregates min/mean/max and
        # flags dead ranks in the same exported stream.  The JSONL
        # aggregate stream needs an explicit telemetry.output_path (no
        # silent files in cwd); the cluster/* gauges always flow.
        metrics_fn = None
        aggregator = None
        tcfg = getattr(self.config, "telemetry", None)
        if tcfg is not None and tcfg.enabled and tcfg.aggregate:
            from deepspeed_tpu import telemetry as _tel

            reg = _tel.get_registry()
            metrics_fn = lambda: (reg.snapshot_compact() or None) if reg.enabled else None
            if rank == 0:
                agg_path = (
                    os.path.join(tcfg.output_path, f"aggregate_rank{rank}.jsonl")
                    if tcfg.output_path else None
                )
                aggregator = _tel.CrossRankAggregator(
                    world, jsonl_path=agg_path, registry=reg,
                    straggler_factor=tcfg.straggler_factor,
                )
        sup = Supervisor(
            rank=rank,
            world_size=world,
            channel=channel,
            beat_interval=sv.beat_interval_seconds,
            sync_timeout=sv.sync_timeout_seconds,
            rescue_grace=sv.rescue_grace_seconds,
            exit_code=sv.exit_code,
            save_dir_fn=lambda: self._resilience_ckpt_dir,
            checksum=self.resilience.checkpoint.checksum,
            metrics_fn=metrics_fn,
            aggregator=aggregator,
        ).start()
        log_dist(
            f"supervision: rank {rank}/{world} armed on the {channel.name} channel "
            f"(beat {sv.beat_interval_seconds:g}s, death deadline "
            f"{sv.beat_timeout_seconds:g}s, sync deadline {sv.sync_timeout_seconds:g}s)"
        )
        return sup

    def _sup_region(self, site: str):
        """Armed-deadline region around one blocking sync.  An exception
        inside the region while a peer is (or is about to be declared)
        dead routes into the rescue path — the collective usually errors
        out milliseconds after the peer dies, before the beat deadline."""
        from contextlib import nullcontext

        sup = getattr(self, "_supervision", None)
        if sup is None:
            return nullcontext()
        return _SupervisedRegion(self, sup, site)

    def _supervision_snapshot(self) -> None:
        """Host snapshot of the portable state + its checkpoint meta at
        a step boundary — what the supervisor commits (pure host I/O)
        if this process must rescue while the main thread is wedged."""
        from deepspeed_tpu.runtime import checkpointing as _ckpt

        sup = self._supervision
        step = self._host_global_step
        client_state = {}
        loader_sd = _ckpt._loader_state(self)
        if loader_sd is not None:
            client_state["__dataloader__"] = loader_sd
        meta = _ckpt._build_meta(self, f"emergency_step{step}", client_state)
        sup.snapshot.update(_ckpt._snapshot_state_to_host(self), meta)

    def _handle_peer_failure(self, pf, fresh_snapshot: bool = True):
        """A peer died: commit a verified emergency tag (rank-local
        ``local_npz`` — no collectives; in DP topologies this rank's
        host snapshot holds the full logical state) and exit with the
        supervision contract code (default 44, "peer-failed-and-saved")
        so the launcher's ``--restarts`` can relaunch-and-resume.  Exits
        1 when no save could be certified."""
        sup = self._supervision
        sup.main_handling = True
        if not sup.claim_rescue("main"):
            # the supervisor thread won the race and is mid-commit; it
            # will os._exit with the right code — don't double-stage the
            # same tag (the loser's StageInFlightError would read as a
            # failed save).  The sleep only ends if the supervisor hangs.
            logger.error("supervision: supervisor thread owns the rescue; waiting for its exit")
            time.sleep(max(30.0, sup.rescue_grace * 4))
            raise SystemExit(1)
        logger.error(
            f"supervision: peer rank {pf.rank} failed ({pf.reason}); committing an "
            f"emergency checkpoint before exiting"
        )
        if fresh_snapshot:
            # we are at a clean step boundary: snapshot the LIVE state
            # (fresher than the last boundary snapshot)
            try:
                self._supervision_snapshot()
            except BaseException as e:  # noqa: BLE001 — fall back to the last one
                logger.warning(f"fresh emergency snapshot failed ({e!r}); using the last boundary snapshot")
        code = sup.rescue_save(reason=f"peer rank {pf.rank} failed: {pf.reason}")
        sup.stop()
        raise SystemExit(code)

    def _on_step_boundary(self, overflowed: bool, loss=None) -> None:
        """Host-side hook after every optimizer-step boundary: fault
        sites and supervision first (a peer death or injected kill at a
        boundary must win over progress reporting), then a pending
        preemption request, then the divergence guard."""
        from deepspeed_tpu.resilience import faults as _faults

        _faults.check("step.boundary")
        sup = getattr(self, "_supervision", None)
        if sup is not None:
            pf = sup.peer_failure
            if pf is not None:
                self._handle_peer_failure(pf)
            if not getattr(self, "_supervision_snapshot_broken", False) and sup.snapshot_due(
                self._host_global_step, self.resilience.supervision.snapshot_interval_steps
            ):
                try:
                    self._supervision_snapshot()
                except Exception as e:  # noqa: BLE001 — e.g. non-addressable shards
                    # state spanning non-addressable devices (multi-host
                    # sharded topologies) cannot be host-snapshotted from
                    # one rank; degrade to no boundary snapshots (rescue
                    # then certifies exit 1, the crash contract) instead
                    # of killing the training loop every step
                    self._supervision_snapshot_broken = True
                    logger.warning(
                        f"supervision: step-boundary snapshot failed ({e!r}); disabling "
                        "boundary snapshots — a rescue on this rank will exit 1 "
                        "(resume from the previous verified tag)"
                    )
        wd = getattr(self, "_watchdog", None)
        if wd is not None and wd.preemption_requested:
            self._handle_preemption()
        san = getattr(self, "_sanitizer", None)
        if san is not None and san.drift.due(self._host_global_step):
            san.drift.check_state(self, step=self._host_global_step)
        guard = getattr(self, "_divergence_guard", None)
        if guard is None:
            return
        from deepspeed_tpu.resilience import faults

        diverged = bool(overflowed) or faults.check_flag("engine.force_overflow")
        if not diverged and self.resilience.divergence.check_loss and loss is not None:
            # opt-in host sync: the only NaN signal without dynamic loss
            # scaling (bf16 default has no overflow flag)
            diverged = not bool(np.isfinite(np.asarray(jax.device_get(loss))))
        action = guard.record(diverged)
        if action is not None:
            if san is not None:
                # name the first non-finite op before the action mutates
                # state (floor recompiles, rollback replaces params)
                san.nanprobe.probe_engine_step(self, self._san_last_batch)
            self._apply_divergence_action(action)

    def _handle_preemption(self) -> None:
        """Emergency checkpoint + exit.  Exit-code contract: the
        configured code (default 43) means "preempted AND saved" — a
        scheduler can requeue and resume blindly; exit 1 means the save
        did not happen (deadline passed or save failed) — treat as a
        crash and resume from the previous tag."""
        wd = self._watchdog
        from deepspeed_tpu.telemetry import get_registry

        get_registry().counter("resilience/preemptions").inc()
        log_dist(
            f"preemption signal ({wd.signal_name}) received; attempting emergency "
            f"checkpoint ({wd.remaining():.0f}s of grace left)"
        )
        if self._resilience_ckpt_dir is None:
            logger.error(
                "preempted but no checkpoint dir is known (no prior save/load and no "
                "'resilience.watchdog.save_dir'); exiting WITHOUT saving"
            )
            raise SystemExit(1)
        if wd.remaining() <= 0:
            logger.error(
                f"preemption grace deadline ({wd.grace_seconds}s) already passed; "
                "exiting WITHOUT saving"
            )
            raise SystemExit(1)
        writer = self._async_writer
        if writer is not None and writer.in_flight:
            # drain-before-exit: an in-flight background commit must land
            # (or provably fail) before the emergency save touches the
            # tree; the budget is capped by the remaining grace window
            log_dist("draining in-flight async checkpoint before the emergency save")
            try:
                writer.drain(timeout=max(1.0, min(writer.drain_timeout_seconds, wd.remaining())))
            except BaseException as e:  # hung drain => cannot certify "saved"
                logger.error(f"drain of in-flight async save failed: {e!r}")
                raise SystemExit(1) from e
        try:
            # synchronous: exit code 43 must certify a COMMITTED tag
            path = self.save_checkpoint(self._resilience_ckpt_dir, async_save=False)
        except BaseException as e:  # a failed save must NOT exit as "saved"
            logger.error(f"emergency checkpoint failed: {e!r}")
            raise SystemExit(1) from e
        log_dist(f"emergency checkpoint saved to {path}; exiting with code {wd.exit_code}")
        raise SystemExit(wd.exit_code)

    def _apply_divergence_action(self, action: str) -> None:
        from deepspeed_tpu.telemetry import get_registry

        get_registry().counter("resilience/divergence_actions", action=action).inc()
        n = self.resilience.divergence.threshold
        if action == C.DIVERGENCE_ACTION_FLOOR:
            old = self.loss_scaler.min_scale
            self.loss_scaler.min_scale = max(old / 2.0, 2.0**-24)
            # the floor is baked into compiled steps as a constant
            self._purge_train_executables()
            logger.warning(
                f"divergence guard: {n} consecutive skipped steps — lowering loss-scale "
                f"floor {old} -> {self.loss_scaler.min_scale} (recompiling train step)"
            )
        elif action == C.DIVERGENCE_ACTION_ROLLBACK:
            if self._resilience_ckpt_dir is None:
                logger.error(
                    f"divergence guard: {n} consecutive skipped steps and action=rollback, "
                    "but no checkpoint dir is known (no prior save/load); cannot roll back"
                )
                return
            logger.warning(
                f"divergence guard: {n} consecutive skipped steps — rolling back to the "
                f"last verified checkpoint under {self._resilience_ckpt_dir}"
            )
            # strict=False even under fail_on_missing: a failed rollback
            # must degrade to the error log below, not crash the step
            path, _ = self.load_checkpoint(self._resilience_ckpt_dir, strict=False)
            if path is None:
                logger.error("divergence rollback found no loadable checkpoint")
        else:
            logger.warning(
                f"divergence guard: {n} consecutive NaN/overflow-skipped steps "
                f"(loss scale {self.loss_scale}) — the run is likely diverging"
            )

    # ------------------------------------------------------------------
    # checkpointing (engine.save_checkpoint, reference :1854)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None, client_state: Optional[dict] = None, save_latest: bool = True, async_save: Optional[bool] = None):
        """``async_save``: None defers to the ``overlap.async_checkpoint``
        config; True/False forces the background/synchronous path for
        this save (see docs/performance.md)."""
        from deepspeed_tpu.runtime.checkpointing import save_checkpoint as _save

        return _save(self, save_dir, tag=tag, client_state=client_state, save_latest=save_latest, async_save=async_save)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None, **kw):
        from deepspeed_tpu.runtime.checkpointing import load_checkpoint as _load

        return _load(self, load_dir, tag=tag, **kw)


class _SupervisedRegion:
    """Armed-deadline region around one of the engine's blocking syncs.

    On a normal exit the deadline disarms.  On an exception, a pending
    (or imminent — the channel gets one beat-timeout to confirm) peer
    death converts the error into the engine's peer-failure rescue:
    commit a verified emergency tag, exit with the supervision contract
    code.  Anything else re-raises untouched.
    """

    def __init__(self, engine, sup, site: str):
        self.engine = engine
        self.sup = sup
        self.site = site
        self._armed = sup.armed(site)

    def __enter__(self):
        self._armed.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._armed.__exit__(exc_type, exc, tb)
        if exc is None or isinstance(exc, SystemExit):
            return False
        if self.sup.main_handling:
            return False
        wait = getattr(self.sup.channel, "beat_timeout", 2.0)
        pf = self.sup.confirm_peer_failure(wait=wait)
        if pf is not None:
            logger.error(
                f"supervision: blocking sync '{self.site}' raised "
                f"{exc_type.__name__} with peer rank {pf.rank} dead; entering rescue"
            )
            # state buffers may be donated into the failed computation:
            # rescue from the last boundary snapshot, not live state
            self.engine._handle_peer_failure(pf, fresh_snapshot=False)
        return False
