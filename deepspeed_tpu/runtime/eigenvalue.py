"""Curvature estimation via power iteration (MoQ's precision gate).

Reference: ``runtime/eigenvalue.py`` (``Eigenvalue`` :7) — estimates the
dominant Hessian eigenvalue per layer with power iteration over
Hessian-vector products, used by MoQ to decide when a layer is "flat
enough" to drop precision (engine.step hook, ``engine.py:1334-1341``).

TPU-native form: HVPs come from ``jax.jvp`` over ``jax.grad`` (forward-
over-reverse) — exact, compiled, no double-backward graph surgery — and
the whole power iteration is one jitted ``lax``-style loop per call.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def _normalize(tree: Any) -> Tuple[Any, jnp.ndarray]:
    sq = sum(jnp.sum(jnp.square(v.astype(jnp.float32))) for v in jax.tree.leaves(tree))
    norm = jnp.sqrt(sq)
    safe = jnp.maximum(norm, 1e-12)
    return jax.tree.map(lambda v: (v / safe).astype(v.dtype), tree), norm


class Eigenvalue:
    """Reference signature subset: verbose, max_iter, tol, stability
    (+ eigenvalue is computed over the whole params tree or a sub-tree)."""

    def __init__(
        self,
        verbose: bool = False,
        max_iter: int = 100,
        tol: float = 1e-2,
        stability: float = 1e-6,
        gas_boundary_resolution: int = 1,
        layer_name: str = "",
        layer_num: int = 0,
    ):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def compute_eigenvalue(
        self,
        loss_fn: Callable[[Any], jnp.ndarray],
        params: Any,
        rng: Optional[jax.Array] = None,
    ) -> float:
        """Dominant eigenvalue of the Hessian of ``loss_fn`` at
        ``params`` by power iteration on exact HVPs."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(rng, len(leaves))
        # tangents must match the primal dtype (bf16/fp16 params included)
        v = jax.tree.unflatten(
            treedef,
            [jax.random.normal(k, l.shape, jnp.float32).astype(l.dtype) for k, l in zip(keys, leaves)],
        )
        v, _ = _normalize(v)
        # cache the jitted HVP per loss_fn — repeated calibration probes
        # (MoQ calls this per layer/boundary) must not recompile each time
        if not hasattr(self, "_hvp_cache"):
            self._hvp_cache = {}
        hvp = self._hvp_cache.get(id(loss_fn))
        if hvp is None:
            grad_fn = jax.grad(lambda p: jnp.asarray(loss_fn(p), jnp.float32))
            # out_shardings=None: the HVP inherits the params' layout;
            # power iteration runs wherever the grads live
            hvp = jax.jit(lambda p, vec: jax.jvp(grad_fn, (p,), (vec,))[1],
                          out_shardings=None)
            self._hvp_cache[id(loss_fn)] = hvp

        eig = 0.0
        for i in range(self.max_iter):
            hv = hvp(params, v)
            v, norm = _normalize(hv)
            new_eig = float(norm)
            if self.verbose:
                logger.info(f"eigenvalue iter {i}: {new_eig:.4e}")
            if eig and abs(new_eig - eig) / (abs(eig) + self.stability) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return max(eig, self.stability)
