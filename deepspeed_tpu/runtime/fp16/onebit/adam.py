"""1-bit Adam.

Re-implements the reference's ``runtime/fp16/onebit/adam.py``
(``OnebitAdam`` :14): Adam with a *warmup phase* of exact updates, after
which the variance term is **frozen** and only the momentum is
communicated — compressed to 1 bit with error feedback (the
``adam_freeze_key`` switch, reference :110-:220; algorithm in
arXiv:2102.02888).

SPMD integration: under GSPMD the gradient allreduce is inserted by the
compiler, so the compression hook lives in the *optimizer*: after the
freeze step, the momentum update is quantized to sign·scale with a
persistent error-feedback residual carried in the optimizer state —
numerically the single-node form of the reference's compressed
collective (``comm/nccl.py:47``; the exchange itself is
``deepspeed_tpu.comm.compressed.compressed_allreduce``, used when the
engine runs the explicit unreduced-gradient path).
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.fused_adam import _map_multi


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any  # frozen after freeze_step
    worker_error: Any  # error-feedback residual per param


class FrozenOnebitAdamState(NamedTuple):
    """Compressed-exchange phase state (engine's frozen train path).

    The synced momentum is stored in its COMPRESSED exchange form —
    int8 signs + per-chunk scales.  This is exact, not an
    approximation: after every exchange the momentum every rank holds
    IS ``sign × chunk-scale`` by construction (phase 3 all-gathers
    exactly these bytes, comm/compressed.py), so storing the
    decompressed fp32 vector was a 4× memory redundancy.  The one
    boundary case — the warm-phase momentum at the freeze step is NOT
    sign-representable — is handled by folding ``β1·(m_warm − m_stored)``
    into every worker-error row, which makes each rank's first
    corrected/exchanged tensor bit-identical to the reference's
    (see :meth:`OnebitAdam.make_frozen_state`).

    The frozen variance is one flat fp32 vector (padded to a multiple
    of the data-axis size), matching the reference's flattened fused
    buffer (onebit/adam.py:141); the error-feedback residuals are
    PER-RANK rows sharded over ``data`` (reference
    worker_error/server_error, comm/nccl.py:47-186)."""

    step: jnp.ndarray
    m_signs: jnp.ndarray  # (Mp,) int8 replicated — synced momentum signs
    m_scales: jnp.ndarray  # (n,) fp32 replicated — per-chunk scales
    v_flat: jnp.ndarray  # (Mp,) replicated — frozen variance
    worker_error: jnp.ndarray  # (n, Mp) sharded over data
    server_error: jnp.ndarray  # (n, Mp // n) sharded over data


def pack_flat(tree: Any, multiple: int) -> jnp.ndarray:
    """Concat ravelled fp32 leaves, zero-padded to a length multiple."""
    leaves = jax.tree.leaves(tree)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])
    pad = (-flat.shape[0]) % multiple
    return jnp.pad(flat, (0, pad)) if pad else flat


def pack_rows(tree: Any, n: int, multiple: int) -> jnp.ndarray:
    """Leaves shaped (n, *shape) → one (n, Mp) fp32 matrix (padded)."""
    leaves = jax.tree.leaves(tree)
    flat = jnp.concatenate([l.reshape(n, -1).astype(jnp.float32) for l in leaves], axis=1)
    pad = (-flat.shape[1]) % multiple
    return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat


def unpack_flat(flat: jnp.ndarray, template: Any) -> Any:
    """Inverse of pack_flat: slice/reshape back to the template's leaves
    (original dtypes restored)."""
    leaves, treedef = jax.tree.flatten(template)
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(np.shape(l))) if np.shape(l) else 1
        out.append(flat[off : off + size].reshape(np.shape(l)).astype(l.dtype))
        off += size
    return jax.tree.unflatten(treedef, out)


class OnebitAdam:
    name = "onebitadam"

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        freeze_step: int = 100000,
        cuda_aware: bool = False,  # accepted for config compat, unused
        comm_backend_name: str = "xla",
        fsdp_size: int = 1,
        **_compat,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = int(freeze_step)

    def init(self, params: Any) -> OnebitAdamState:
        zeros = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            worker_error=zeros(),
        )

    def update(self, grads: Any, state: OnebitAdamState, params: Any, lr: Optional[jnp.ndarray] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        frozen = step > self.freeze_step  # traced bool scalar
        # bias correction for v, clamped at the freeze step (after freeze
        # the frozen v keeps its last correction factor) — makes early
        # freezes numerically sane; →1 for reference-style long warmups
        t_eff = jnp.minimum(step, self.freeze_step).astype(jnp.float32)
        c2 = 1.0 - b2**t_eff

        def one(g, m, v, werr, p):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            # warmup: update variance; frozen: keep it
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * g * g)

            # compressed-momentum path (error feedback): quantize m_new to
            # sign * mean|.|, residual carried forward
            corrected = m_new + werr
            scale = jnp.mean(jnp.abs(corrected))
            m_comp = jnp.where(corrected >= 0, scale, -scale)
            werr_new = corrected - m_comp
            # 1-bit compression cannot represent exact zero (the
            # reference requires a user momentum mask for always-zero
            # coordinates, onebit/adam.py:221-226); gate on v > 0
            # instead: a coordinate that never saw a gradient has no
            # variance, and ±scale/(√0+eps) would be a huge noise update
            m_eff = jnp.where(frozen, m_comp * (v_new > 0), m_new)
            werr_out = jnp.where(frozen, werr_new, werr)

            denom = jnp.sqrt(v_new / c2) + self.eps
            upd = -lr * m_eff / denom
            if self.weight_decay > 0.0:
                upd = upd - lr * self.weight_decay * p.astype(jnp.float32)
            return upd, m_new, v_new, werr_out

        updates, m, v, werr = _map_multi(one, 4, grads, state.exp_avg, state.exp_avg_sq, state.worker_error, params)
        return updates, OnebitAdamState(step=step, exp_avg=m, exp_avg_sq=v, worker_error=werr)

    # ------------------------------------------------------------------
    # compressed-exchange (frozen) phase — used by the engine's frozen
    # train executable (reference onebit/adam.py:110-220 + nccl.py:47)
    # ------------------------------------------------------------------
    def frozen_specs(self, row_spec) -> FrozenOnebitAdamState:
        """PartitionSpecs for the frozen-state layout (the engine maps
        these to NamedShardings): error-feedback rows sharded over the
        exchange grid, everything else replicated."""
        from jax.sharding import PartitionSpec as P

        return FrozenOnebitAdamState(
            step=P(), m_signs=P(), m_scales=P(), v_flat=P(),
            worker_error=row_spec, server_error=row_spec,
        )

    def make_frozen_state(self, state: OnebitAdamState, n_ranks: int) -> FrozenOnebitAdamState:
        """One-time warmup→frozen layout conversion at the freeze step.
        ``n_ranks``: number of exchange rows — the full data-parallel
        world (data × fsdp when ZeRO-composed)."""
        from deepspeed_tpu.comm.collectives import compress_chunks, decompress_chunks

        m_flat = pack_flat(state.exp_avg, n_ranks)
        v_flat = pack_flat(state.exp_avg_sq, n_ranks)
        mp = m_flat.shape[0]
        # Store m compressed (1 byte/param); the representation error of
        # the warm momentum rides into every worker-error row scaled by
        # β1, so each rank's first frozen-phase corrected tensor equals
        # β1·m_warm + (1−β1)·g + werr — the reference's value exactly.
        m_signs, m_scales = compress_chunks(m_flat, n_ranks)
        delta = self.b1 * (m_flat - decompress_chunks(m_signs, m_scales))
        return FrozenOnebitAdamState(
            step=state.step,
            m_signs=m_signs,
            m_scales=m_scales,
            v_flat=v_flat,
            worker_error=jnp.broadcast_to(delta[None, :], (n_ranks, mp)),
            server_error=jnp.zeros((n_ranks, mp // n_ranks), jnp.float32),
        )

    def frozen_apply(
        self,
        g_rows: jnp.ndarray,  # (n, Mp) per-rank UNREDUCED averaged grads
        fstate: FrozenOnebitAdamState,
        p_flat: jnp.ndarray,  # (Mp,) fp32 packed params
        lr,
        mesh,
        axis_name="data",
    ):
        """One compressed-momentum step: every rank folds its LOCAL
        gradient into the synced momentum, the momenta are exchanged
        1-bit with error feedback, and the update uses the frozen
        variance (reference onebit/adam.py:148-205).  ``axis_name`` may
        be a tuple of mesh axes (the ZeRO-composed flat exchange over
        the whole dp grid, comm/compressed.py).  The synced momentum is
        stored/loaded in its compressed exchange form (see
        :class:`FrozenOnebitAdamState`); it is decompressed transiently
        here (fp32 HBM only for the step's lifetime)."""
        from deepspeed_tpu.comm.collectives import (
            compressed_allreduce_compressed_out,
            decompress_chunks,
        )

        step = fstate.step + 1
        m_flat = decompress_chunks(fstate.m_signs, fstate.m_scales)
        m_rows = self.b1 * m_flat[None, :] + (1.0 - self.b1) * g_rows
        m_signs, m_scales, werr, serr = compressed_allreduce_compressed_out(
            m_rows, fstate.worker_error, fstate.server_error, mesh, axis_name
        )
        m_synced = decompress_chunks(m_signs, m_scales)
        c2 = 1.0 - self.b2 ** jnp.float32(self.freeze_step)
        denom = jnp.sqrt(fstate.v_flat / c2) + self.eps
        # v == 0 ⇒ the coordinate never received a gradient (incl. the
        # pack_flat padding): the ±scale sign noise must not become a
        # (scale/eps)-sized update — the reference's momentum-mask
        # requirement (onebit/adam.py:221-226), made automatic
        upd = -lr * (m_synced * (fstate.v_flat > 0)) / denom
        if self.weight_decay > 0.0:
            upd = upd - lr * self.weight_decay * p_flat
        new_state = FrozenOnebitAdamState(
            step=step, m_signs=m_signs, m_scales=m_scales, v_flat=fstate.v_flat,
            worker_error=werr, server_error=serr,
        )
        return upd, new_state

    def frozen_apply_vsharded(
        self,
        g_rows: jnp.ndarray,   # (n, Mp) per-rank unreduced averaged grads
        m_signs: jnp.ndarray,  # (Mp,) int8 replicated
        m_scales: jnp.ndarray, # (n,) fp32 replicated
        v_rows: jnp.ndarray,   # (n, Mp//n) fp32 SHARDED over the grid
        p_rows: jnp.ndarray,   # (n, Mp//n) fp32 SHARDED over the grid
        werr: jnp.ndarray,
        serr: jnp.ndarray,
        lr,
        mesh,
        axis_name="data",
    ):
        """ALTERNATIVE frozen layout: variance + params sharded 1/n over
        the exchange grid, each rank updating only its chunk, with the
        updated params all-gathered for the next forward.

        Implemented to MEASURE the r3 trade-off question (VERDICT weak
        #6), not as the default: the next step's momentum fold-in
        ``b1·m + (1−b1)·g`` needs the FULL synced momentum on every
        rank, so phase 3's 1 B/param allgather can never be dropped —
        sharding v/p therefore strictly ADDS the fp32 param-chunk
        allgather (4 B/param/step) on top of the ~2 B/param the 1-bit
        exchange already moves, i.e. it TRIPLES the wire volume that
        the 1-bit machinery exists to minimize, in exchange for ~8
        B/param/chip less HBM (v 4 B + p 4 B).  The HLO-pinned
        comparison at fsdp ∈ {2,4} lives in
        ``tests/test_onebit.py::test_frozen_variance_layout_wire_bytes``;
        the engine keeps the replicated layout and warns about the HBM
        floor at init (runtime/engine.py).
        """
        from deepspeed_tpu.comm.collectives import (
            compressed_allreduce_compressed_out,
            decompress_chunks,
        )

        m_flat = decompress_chunks(m_signs, m_scales)
        m_rows = self.b1 * m_flat[None, :] + (1.0 - self.b1) * g_rows
        new_signs, new_scales, werr, serr = compressed_allreduce_compressed_out(
            m_rows, werr, serr, mesh, axis_name
        )
        n = m_scales.shape[0]
        # each rank's served chunk of the synced momentum
        m_chunks = (new_signs.reshape(n, -1).astype(jnp.float32) * new_scales[:, None])
        c2 = 1.0 - self.b2 ** jnp.float32(self.freeze_step)
        denom = jnp.sqrt(v_rows / c2) + self.eps
        upd_rows = -lr * (m_chunks * (v_rows > 0)) / denom
        if self.weight_decay > 0.0:
            upd_rows = upd_rows - lr * self.weight_decay * p_rows
        p_rows = p_rows + upd_rows
        # the extra wire this layout costs: every rank needs the full
        # updated params for its next forward
        from jax.sharding import NamedSharding, PartitionSpec as P

        p_full = jax.lax.with_sharding_constraint(
            p_rows.reshape(-1), NamedSharding(mesh, P())
        )
        return p_full, p_rows, new_signs, new_scales, werr, serr
