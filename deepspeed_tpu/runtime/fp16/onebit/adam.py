"""1-bit Adam.

Re-implements the reference's ``runtime/fp16/onebit/adam.py``
(``OnebitAdam`` :14): Adam with a *warmup phase* of exact updates, after
which the variance term is **frozen** and only the momentum is
communicated — compressed to 1 bit with error feedback (the
``adam_freeze_key`` switch, reference :110-:220; algorithm in
arXiv:2102.02888).

SPMD integration: under GSPMD the gradient allreduce is inserted by the
compiler, so the compression hook lives in the *optimizer*: after the
freeze step, the momentum update is quantized to sign·scale with a
persistent error-feedback residual carried in the optimizer state —
numerically the single-node form of the reference's compressed
collective (``comm/nccl.py:47``; the exchange itself is
``deepspeed_tpu.comm.compressed.compressed_allreduce``, used when the
engine runs the explicit unreduced-gradient path).
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import _map_multi


class OnebitAdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any  # frozen after freeze_step
    worker_error: Any  # error-feedback residual per param


class OnebitAdam:
    name = "onebitadam"

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        freeze_step: int = 100000,
        cuda_aware: bool = False,  # accepted for config compat, unused
        comm_backend_name: str = "xla",
        fsdp_size: int = 1,
        **_compat,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = int(freeze_step)

    def init(self, params: Any) -> OnebitAdamState:
        zeros = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return OnebitAdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            worker_error=zeros(),
        )

    def update(self, grads: Any, state: OnebitAdamState, params: Any, lr: Optional[jnp.ndarray] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        frozen = step > self.freeze_step  # traced bool scalar
        # bias correction for v, clamped at the freeze step (after freeze
        # the frozen v keeps its last correction factor) — makes early
        # freezes numerically sane; →1 for reference-style long warmups
        t_eff = jnp.minimum(step, self.freeze_step).astype(jnp.float32)
        c2 = 1.0 - b2**t_eff

        def one(g, m, v, werr, p):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            # warmup: update variance; frozen: keep it
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * g * g)

            # compressed-momentum path (error feedback): quantize m_new to
            # sign * mean|.|, residual carried forward
            corrected = m_new + werr
            scale = jnp.mean(jnp.abs(corrected))
            m_comp = jnp.where(corrected >= 0, scale, -scale)
            werr_new = corrected - m_comp
            m_eff = jnp.where(frozen, m_comp, m_new)
            werr_out = jnp.where(frozen, werr_new, werr)

            denom = jnp.sqrt(v_new / c2) + self.eps
            upd = -lr * m_eff / denom
            if self.weight_decay > 0.0:
                upd = upd - lr * self.weight_decay * p.astype(jnp.float32)
            return upd, m_new, v_new, werr_out

        updates, m, v, werr = _map_multi(one, 4, grads, state.exp_avg, state.exp_avg_sq, state.worker_error, params)
        return updates, OnebitAdamState(step=step, exp_avg=m, exp_avg_sq=v, worker_error=werr)
