"""1-bit LAMB.

Re-implements the reference's ``runtime/fp16/onebit/lamb.py``
(``OnebitLamb`` :11; algorithm in arXiv:2104.06069): LAMB with a warmup
phase, then frozen variance + compressed momentum exchange, with the
trust ratio computed from *frozen-phase* statistics — the reference
tracks per-layer ``scaling_coeff`` from the warmup so the compressed
phase keeps LAMB's layerwise adaptivity without communicating norms.

Two tiers, mirroring ``onebit/adam.py``:

* ``update()`` — single-program fallback: momentum quantized locally
  with error feedback, full-precision allreduce (used when the engine
  cannot run the explicit exchange).
* ``make_frozen_state()`` / ``frozen_apply()`` — the engine's
  compressed-exchange phase: per-rank gradients stay unreduced, only
  1-bit momentum crosses the wire through the comm layer
  (``comm/collectives.py``), and the trust ratio is the warmup-frozen
  per-param ``scaling_coeff`` expanded to a flat coordinate vector —
  LAMB's layerwise adaptivity with zero extra norm traffic.  This is
  the large-batch rung (bert-s512) the 1-bit LAMB paper targets.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.adam.fused_adam import _map_multi


class OnebitLambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    scaling_coeff: Any  # per-param frozen trust ratio (lamb_coeff)


class FrozenOnebitLambState(NamedTuple):
    """Compressed-exchange phase state (see FrozenOnebitAdamState for
    the layout rationale).  ``coeff_flat`` carries the warmup-frozen
    per-param trust ratios expanded per coordinate (padding coords get
    1.0; they are masked by ``v_flat > 0`` anyway)."""

    step: jnp.ndarray
    m_signs: jnp.ndarray  # (Mp,) int8 replicated — synced momentum signs
    m_scales: jnp.ndarray  # (n,) fp32 replicated — per-chunk scales
    v_flat: jnp.ndarray  # (Mp,) replicated — frozen variance
    coeff_flat: jnp.ndarray  # (Mp,) replicated — frozen trust ratios
    worker_error: jnp.ndarray  # (n, Mp) sharded over the exchange grid
    server_error: jnp.ndarray  # (n, Mp // n) sharded over the exchange grid


class OnebitLamb:
    name = "onebitlamb"

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        freeze_step: int = 100000,
        max_coeff: float = 10.0,
        min_coeff: float = 0.01,
        coeff_beta: float = 0.9,
        **_compat,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = int(freeze_step)
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.coeff_beta = coeff_beta

    def init(self, params: Any) -> OnebitLambState:
        zeros = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        ones_scalar = jax.tree.map(lambda p: jnp.ones((), jnp.float32), params)
        return OnebitLambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            worker_error=zeros(),
            scaling_coeff=ones_scalar,
        )

    def update(self, grads: Any, state: OnebitLambState, params: Any, lr: Optional[jnp.ndarray] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        frozen = step > self.freeze_step
        # v bias correction clamped at freeze (see onebit/adam.py)
        t_eff = jnp.minimum(step, self.freeze_step).astype(jnp.float32)
        c2 = 1.0 - b2**t_eff

        def one(g, m, v, werr, coeff, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * g * g)

            # compressed momentum (error feedback), frozen phase only
            corrected = m_new + werr
            scale = jnp.mean(jnp.abs(corrected))
            m_comp = jnp.where(corrected >= 0, scale, -scale)
            m_eff = jnp.where(frozen, m_comp, m_new)
            werr_out = jnp.where(frozen, corrected - m_comp, werr)

            update_dir = m_eff / (jnp.sqrt(v_new / c2) + self.eps)
            if self.weight_decay > 0.0:
                update_dir = update_dir + self.weight_decay * p32

            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(update_dir.reshape(-1))
            fresh = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0),
            )
            # warmup: EMA the coeff (reference's lamb_coeff_freeze);
            # frozen: reuse the frozen coefficient
            coeff_new = jnp.where(frozen, coeff, self.coeff_beta * coeff + (1 - self.coeff_beta) * fresh)
            trust = jnp.where(frozen, coeff, fresh)
            return -lr * trust * update_dir, m_new, v_new, werr_out, coeff_new

        updates, m, v, werr, coeff = _map_multi(
            one, 5, grads, state.exp_avg, state.exp_avg_sq, state.worker_error, state.scaling_coeff, params
        )
        return updates, OnebitLambState(step=step, exp_avg=m, exp_avg_sq=v, worker_error=werr, scaling_coeff=coeff)

    # ------------------------------------------------------------------
    # compressed-exchange (frozen) phase — engine frozen train executable
    # (reference onebit/lamb.py compressed path + comm/nccl.py exchange)
    # ------------------------------------------------------------------
    def frozen_specs(self, row_spec) -> FrozenOnebitLambState:
        """PartitionSpecs for the frozen-state layout (the engine maps
        these to NamedShardings)."""
        from jax.sharding import PartitionSpec as P

        return FrozenOnebitLambState(
            step=P(), m_signs=P(), m_scales=P(), v_flat=P(), coeff_flat=P(),
            worker_error=row_spec, server_error=row_spec,
        )

    def make_frozen_state(self, state: OnebitLambState, n_ranks: int) -> FrozenOnebitLambState:
        """Warmup→frozen layout conversion at the freeze step: momentum
        stored in its compressed exchange form with the representation
        error folded into every worker-error row (scaled by β1 — see
        OnebitAdam.make_frozen_state), variance flat-packed, and the
        per-param EMA trust ratios expanded to one fp32 coordinate
        vector so the frozen update needs no per-layer bookkeeping."""
        from deepspeed_tpu.comm.collectives import compress_chunks, decompress_chunks
        from deepspeed_tpu.runtime.fp16.onebit.adam import pack_flat

        m_flat = pack_flat(state.exp_avg, n_ranks)
        v_flat = pack_flat(state.exp_avg_sq, n_ranks)
        mp = m_flat.shape[0]
        leaves = jax.tree.leaves(state.exp_avg)
        coeffs = jax.tree.leaves(state.scaling_coeff)  # same treedef as exp_avg
        parts = [
            jnp.broadcast_to(c.astype(jnp.float32), (int(np.prod(np.shape(l))) or 1,))
            for c, l in zip(coeffs, leaves)
        ]
        coeff_flat = jnp.concatenate(parts)
        coeff_flat = jnp.pad(
            coeff_flat, (0, mp - coeff_flat.shape[0]), constant_values=1.0
        )
        m_signs, m_scales = compress_chunks(m_flat, n_ranks)
        delta = self.b1 * (m_flat - decompress_chunks(m_signs, m_scales))
        return FrozenOnebitLambState(
            step=state.step,
            m_signs=m_signs,
            m_scales=m_scales,
            v_flat=v_flat,
            coeff_flat=coeff_flat,
            worker_error=jnp.broadcast_to(delta[None, :], (n_ranks, mp)),
            server_error=jnp.zeros((n_ranks, mp // n_ranks), jnp.float32),
        )

    def frozen_apply(
        self,
        g_rows: jnp.ndarray,  # (n, Mp) per-rank UNREDUCED averaged grads
        fstate: FrozenOnebitLambState,
        p_flat: jnp.ndarray,  # (Mp,) fp32 packed params
        lr,
        mesh,
        axis_name="data",
    ):
        """One compressed-momentum LAMB step: local gradient folds into
        the synced momentum, the momenta exchange 1-bit with error
        feedback (comm layer), and the update direction is scaled by the
        frozen per-coordinate trust ratio — no norm collectives."""
        from deepspeed_tpu.comm.collectives import (
            compressed_allreduce_compressed_out,
            decompress_chunks,
        )

        step = fstate.step + 1
        m_flat = decompress_chunks(fstate.m_signs, fstate.m_scales)
        m_rows = self.b1 * m_flat[None, :] + (1.0 - self.b1) * g_rows
        m_signs, m_scales, werr, serr = compressed_allreduce_compressed_out(
            m_rows, fstate.worker_error, fstate.server_error, mesh, axis_name
        )
        m_synced = decompress_chunks(m_signs, m_scales)
        c2 = 1.0 - self.b2 ** jnp.float32(self.freeze_step)
        denom = jnp.sqrt(fstate.v_flat / c2) + self.eps
        # v == 0 ⇒ never-gradded coordinate (incl. pack padding): mask
        # the sign noise (the OnebitAdam momentum-mask rationale)
        update_dir = (m_synced * (fstate.v_flat > 0)) / denom
        if self.weight_decay > 0.0:
            update_dir = update_dir + self.weight_decay * p_flat
        upd = -lr * fstate.coeff_flat * update_dir
        new_state = FrozenOnebitLambState(
            step=step, m_signs=m_signs, m_scales=m_scales, v_flat=fstate.v_flat,
            coeff_flat=fstate.coeff_flat, worker_error=werr, server_error=serr,
        )
        return upd, new_state
