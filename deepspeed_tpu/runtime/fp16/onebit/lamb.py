"""1-bit LAMB.

Re-implements the reference's ``runtime/fp16/onebit/lamb.py``
(``OnebitLamb`` :11; algorithm in arXiv:2104.06069): LAMB with a warmup
phase, then frozen variance + compressed momentum exchange, with the
trust ratio computed from *frozen-phase* statistics — the reference
tracks per-layer ``scaling_coeff`` from the warmup so the compressed
phase keeps LAMB's layerwise adaptivity without communicating norms.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.fused_adam import _map_multi


class OnebitLambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any
    worker_error: Any
    scaling_coeff: Any  # per-param frozen trust ratio (lamb_coeff)


class OnebitLamb:
    name = "onebitlamb"

    def __init__(
        self,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        freeze_step: int = 100000,
        max_coeff: float = 10.0,
        min_coeff: float = 0.01,
        coeff_beta: float = 0.9,
        **_compat,
    ):
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = int(freeze_step)
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.coeff_beta = coeff_beta

    def init(self, params: Any) -> OnebitLambState:
        zeros = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        ones_scalar = jax.tree.map(lambda p: jnp.ones((), jnp.float32), params)
        return OnebitLambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=zeros(),
            exp_avg_sq=zeros(),
            worker_error=zeros(),
            scaling_coeff=ones_scalar,
        )

    def update(self, grads: Any, state: OnebitLambState, params: Any, lr: Optional[jnp.ndarray] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.b1, self.b2
        frozen = step > self.freeze_step
        # v bias correction clamped at freeze (see onebit/adam.py)
        t_eff = jnp.minimum(step, self.freeze_step).astype(jnp.float32)
        c2 = 1.0 - b2**t_eff

        def one(g, m, v, werr, coeff, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * g * g)

            # compressed momentum (error feedback), frozen phase only
            corrected = m_new + werr
            scale = jnp.mean(jnp.abs(corrected))
            m_comp = jnp.where(corrected >= 0, scale, -scale)
            m_eff = jnp.where(frozen, m_comp, m_new)
            werr_out = jnp.where(frozen, corrected - m_comp, werr)

            update_dir = m_eff / (jnp.sqrt(v_new / c2) + self.eps)
            if self.weight_decay > 0.0:
                update_dir = update_dir + self.weight_decay * p32

            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(update_dir.reshape(-1))
            fresh = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.float32(1.0),
            )
            # warmup: EMA the coeff (reference's lamb_coeff_freeze);
            # frozen: reuse the frozen coefficient
            coeff_new = jnp.where(frozen, coeff, self.coeff_beta * coeff + (1 - self.coeff_beta) * fresh)
            trust = jnp.where(frozen, coeff, fresh)
            return -lr * trust * update_dir, m_new, v_new, werr_out, coeff_new

        updates, m, v, werr, coeff = _map_multi(
            one, 5, grads, state.exp_avg, state.exp_avg_sq, state.worker_error, state.scaling_coeff, params
        )
        return updates, OnebitLambState(step=step, exp_avg=m, exp_avg_sq=v, worker_error=werr, scaling_coeff=coeff)
