"""Loss scaling for fp16-compat mode.

Functional re-design of the reference's ``runtime/fp16/loss_scaler.py``
(``LossScaler`` :56, ``DynamicLossScaler`` :79): scaler state is a small
pytree carried through the jitted train step, and the overflow-check /
scale-update logic runs as traced ``jnp.where`` — no Python-side branch,
so a skipped step costs nothing extra on device.

bf16 (the TPU-native default) does not need loss scaling; the static
scaler with scale=1 is used so the train-step graph is identical.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.config.config import Fp16Config


class LossScaleState(NamedTuple):
    scale: jnp.ndarray  # f32 scalar
    good_steps: jnp.ndarray  # i32 scalar — consecutive overflow-free steps
    hysteresis_left: jnp.ndarray  # i32 scalar
    overflow: jnp.ndarray  # bool scalar — last step overflowed


class LossScaler:
    """Static or dynamic; ``dynamic=False, init_scale=1`` = no-op scaler."""

    def __init__(
        self,
        dynamic: bool = False,
        init_scale: float = 2.0**32,
        scale_factor: float = 2.0,
        scale_window: int = 1000,
        min_scale: float = 1.0,
        hysteresis: int = 2,
    ):
        self.dynamic = dynamic
        self.init_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.hysteresis = int(hysteresis)

    @classmethod
    def from_config(cls, cfg: Fp16Config) -> "LossScaler":
        if not cfg.enabled:
            return cls(dynamic=False, init_scale=1.0)
        if cfg.dynamic_loss_scale:
            return cls(
                dynamic=True,
                init_scale=2.0**cfg.initial_scale_power,
                scale_window=cfg.loss_scale_window,
                min_scale=cfg.min_loss_scale,
                hysteresis=cfg.hysteresis,
            )
        return cls(dynamic=False, init_scale=cfg.loss_scale)

    def init(self) -> LossScaleState:
        return LossScaleState(
            scale=jnp.asarray(self.init_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis_left=jnp.asarray(self.hysteresis, jnp.int32),
            overflow=jnp.zeros((), jnp.bool_),
        )

    def scale_loss(self, loss: jnp.ndarray, state: LossScaleState) -> jnp.ndarray:
        return loss * state.scale.astype(loss.dtype)

    def unscale_and_check(self, grads: Any, state: LossScaleState) -> Tuple[Any, jnp.ndarray]:
        """Unscale grads; return (grads, overflow) — overflow is the
        reference's ``CheckOverflow`` (runtime/utils.py:84) as one fused
        reduction."""
        inv = 1.0 / state.scale

        def unscale(g):
            return (g.astype(jnp.float32) * inv).astype(g.dtype)

        grads = jax.tree.map(unscale, grads)
        if not self.dynamic:
            return grads, jnp.zeros((), jnp.bool_)
        finite = jnp.asarray(True)
        for g in jax.tree.leaves(grads):
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        return grads, jnp.logical_not(finite)

    def update(self, state: LossScaleState, overflow: jnp.ndarray) -> LossScaleState:
        """Dynamic scale update (reference loss_scaler.py:132-172):
        overflow → cut scale (respecting hysteresis) and reset window;
        ``scale_window`` clean steps → double scale."""
        if not self.dynamic:
            return state._replace(overflow=overflow)
        hysteresis_left = jnp.where(overflow, jnp.maximum(state.hysteresis_left - 1, 0), state.hysteresis_left)
        should_cut = jnp.logical_and(overflow, hysteresis_left <= 0)
        new_scale = jnp.where(
            should_cut,
            jnp.maximum(state.scale / self.scale_factor, self.min_scale),
            state.scale,
        )
        hysteresis_left = jnp.where(should_cut, self.hysteresis, hysteresis_left)
        good = jnp.where(overflow, 0, state.good_steps + 1)
        grow = jnp.logical_and(jnp.logical_not(overflow), good >= self.scale_window)
        new_scale = jnp.where(grow, new_scale * self.scale_factor, new_scale)
        good = jnp.where(grow, 0, good)
        return LossScaleState(scale=new_scale, good_steps=good, hysteresis_left=hysteresis_left, overflow=overflow)

    @property
    def loss_scale(self) -> float:
        return self.init_scale


# Reference-compat aliases
DynamicLossScaler = LossScaler
