"""ZeRO-Infinity parameter offload — train models whose params exceed HBM.

Reference capability being reproduced: ``AsyncPartitionedParameterSwapper``
(``runtime/swap_tensor/partitioned_param_swapper.py:36``) + ZeRO-3 param
partitioning let one 32GB GPU train 13B params by keeping fp16 params on
CPU/NVMe and fetching each submodule's params just in time
(``docs/_pages/features.md:116``).

TPU-native form: the reference hooks ``nn.Module`` forward/backward to
swap eager tensors; under XLA the unit of streaming is instead a **layer
group** of the model's stacked block params, and the train step becomes
five small compiled programs orchestrated from host:

    embed → [group fwd] × G → head(+vjp) → [group vjp] × G → embed bwd

HBM holds: resident params (embeddings/head), ONE group's params, the
G+1 boundary activations, and one group's grads — never the full model.
Masters + Adam moments live on host (``HostOffloadOptimizer``; moments
optionally on NVMe through the kernel-AIO engine); with
``offload_param.device == "nvme"`` the bf16 group params themselves
stage through NVMe with one-group-ahead prefetch (``AsyncTensorSwapper``
over the same AIO engine), so host RAM holds fp32 masters and HBM holds
one group — the single-chip >HBM capability row.

The model advertises its streaming structure via
``model_fn.stream_spec`` (see ``models/gpt2.py``).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.mesh import MeshInfo, batch_pspec
from deepspeed_tpu.runtime.zero.offload import (
    HostOffloadOptimizer,
    _flatten_with_paths,
    host_unscale_clip_and_check,
)
from deepspeed_tpu.utils.logging import log_dist, logger


@dataclasses.dataclass
class StreamSpec:
    """Layer-streaming structure a model exposes for param offload.

    ``blocks_key``: params subtree whose leaves are stacked on a leading
    layer dim.  ``embed(resident, tokens) -> x``;
    ``group(gblocks, x, rngs, deterministic) -> x``;
    ``head_loss(resident, x, batch) -> loss``.
    """

    n_layer: int
    blocks_key: str
    embed: Callable
    group: Callable
    head_loss: Callable
    deterministic: bool = True
    supported: bool = True


class ZeroInfinityEngine:
    """Streaming train executor for ``offload_param.enabled`` models.

    API mirrors the core engine where it matters: ``train_batch``,
    ``eval_batch``, ``save_checkpoint`` / ``load_checkpoint``,
    ``global_steps``.  Unsupported combos raise at init, not at step N.
    """

    @staticmethod
    def streamable(model, config, mesh_info, optimizer=None) -> Optional[str]:
        """None if this (model, config, mesh) combo can stream; else the
        reason it can't — ``initialize()`` falls back to the in-HBM
        engine (with a warning) rather than crashing configs that
        worked before the streaming path existed."""
        spec = getattr(model, "stream_spec", None)
        if spec is None:
            return "model exposes no stream_spec"
        if not spec.supported:
            return "model config is not streamable (MoE blocks)"
        if config.fp16.enabled:
            return "requires bf16 (no dynamic loss scale on the host path)"
        if mesh_info.model_parallel_world_size > 1:
            return "model (TP) sharding of streamed params is not implemented"
        if optimizer is not None:
            return "client optimizer objects are unsupported (host Adam owns the update)"
        name = (config.optimizer.name or "adamw").lower()
        if name not in ("adam", "adamw"):
            return f"host step supports Adam/AdamW, got '{config.optimizer.name}'"
        return None

    @staticmethod
    def check_fallback_fits(params, config, mesh_info, reason: str) -> None:
        """``offload_param`` was requested but this combo can't stream
        (``reason``).  The fallback to the in-HBM engine is only safe if
        the model actually FITS per device — for a >HBM model it would
        OOM at step time with no mention of why streaming refused.
        Estimate the fallback engine's resident bytes and refuse early,
        carrying the streamable-reason.  HBM budget: real device
        ``memory_stats()['bytes_limit']`` (override with
        ``DS_TPU_HBM_BYTES``); unknown budget (CPU backend) skips the
        check."""
        hbm = os.environ.get("DS_TPU_HBM_BYTES")
        if hbm is None:
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
                hbm = stats.get("bytes_limit")
            except Exception:  # noqa: BLE001 — stats are backend-optional
                hbm = None
        if hbm is None:
            return
        n = sum(int(np.size(l)) for l in jax.tree.leaves(params))
        dt = 2 if (config.bf16.enabled or config.fp16.enabled) else 4
        zc = config.zero_config
        pg_shards = max(1, mesh_info.fsdp_world_size) if zc.stage >= 3 else 1
        opt_dev = 0 if zc.offload_optimizer.enabled else 12  # fp32 master+m+v
        opt_shards = max(1, mesh_info.fsdp_world_size) if zc.stage >= 1 else 1
        # grads accumulate in fp32 on device regardless of compute dtype
        # (and stay on device even with offload_optimizer) — counting
        # them at compute width under-estimated bf16 runs by 2 B/param
        per_dev = n * ((dt + 4) / pg_shards + opt_dev / opt_shards)
        if per_dev > 0.9 * float(hbm):
            raise RuntimeError(
                f"offload_param requested but this combination cannot stream "
                f"({reason}); the in-HBM fallback would keep "
                f"~{per_dev / 1e9:.1f} GB/device resident of {float(hbm) / 1e9:.1f} GB "
                "HBM and OOM at step time. Fix the streaming blocker instead."
            )

    def __init__(self, model, params, config, mesh, lr_scheduler=None):
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec: StreamSpec = model.stream_spec
        if not spec.supported:
            raise NotImplementedError("offload_param: this model config is not streamable (MoE blocks)")
        if config.fp16.enabled:
            raise NotImplementedError("offload_param requires bf16 (no dynamic loss scale on the host path)")
        self.config = config
        self.spec = spec
        self.mesh = mesh
        self.mesh_info = MeshInfo.from_mesh(mesh)
        if self.mesh_info.model_parallel_world_size > 1:
            raise NotImplementedError(
                "offload_param streams layer groups over data/fsdp axes only "
                "(model-axis TP sharding of streamed params is not implemented)"
            )
        self.compute_dtype = jnp.bfloat16 if config.bf16.enabled else jnp.float32

        # -- comm layer (docs/comm.md): the streaming engine's exchanges
        # are GSPMD reduce-scatters (group_bwd out_shardings) and the
        # host-side flag/partial allgathers; quantized strategies do not
        # apply to the host-resident optimizer path, so everything here
        # is recorded dense
        from deepspeed_tpu.comm.strategy import STRATEGY_DENSE, CommLayer
        from deepspeed_tpu.config.config import CommConfig

        self.comm = CommLayer(
            mesh, self.mesh_info, getattr(config, "comm", None) or CommConfig(),
            zero_config=config.zero_config,
        )
        self.comm.note(
            "group-grad-reduce", STRATEGY_DENSE,
            "GSPMD reduce-scatter over fsdp (+ psum over data) from group_bwd out_shardings",
        )
        self.comm.note(
            "offload-host-sync", STRATEGY_DENSE,
            "host process_allgather for grad-norm partials and checkpoint flags",
        )
        if getattr(config, "comm", None) is not None and config.comm.strategy not in ("dense", "auto"):
            from deepspeed_tpu.utils.logging import logger as _logger

            _logger.warning(
                f"comm.strategy '{config.comm.strategy}' is not supported by the "
                "streaming ZeRO-Infinity engine (host-resident optimizer); staying dense"
            )

        zc = config.zero_config
        # layers per HBM-resident group: offload_param.buffer_count, or
        # the largest divisor of n_layer below it (so any model depth
        # works with the default)
        want = max(1, int(getattr(zc.offload_param, "buffer_count", 1) or 1))
        gl = max(d for d in range(1, min(want, spec.n_layer) + 1) if spec.n_layer % d == 0)
        self.group_layers = gl
        self.n_groups = spec.n_layer // gl

        # -- host-resident state ------------------------------------------
        params = jax.tree.map(lambda p: np.asarray(p, np.float32), params)
        # Multi-host master sharding (reference ``stage3.py:2633-2686`` +
        # ``partitioned_param_swapper.py:36`` — ZeRO-Infinity swaps each
        # DP rank's PARTITION, never the whole model): the stacked-blocks
        # fp32 masters + Adam moments live 1/H per HOST along the fsdp
        # axis.  Each process keeps only the master rows covering its
        # local devices' fsdp shards; group uploads assemble the global
        # array from the process-local slices and group grads drain back
        # shard-local, so host RAM and NVMe bytes both scale 1/H.  When
        # fsdp sits inside one host (or fsdp == 1) the local range is the
        # whole axis and behavior is the replicated-masters path.
        blocks_full = params[spec.blocks_key]
        bflat = _flatten_with_paths(blocks_full)
        self._blocks_gshapes = [tuple(np.shape(v)) for _, v in bflat]
        self._blocks_tdef = jax.tree.structure(blocks_full)
        self._setup_host_partition(mesh)
        params = dict(params)
        params[spec.blocks_key] = jax.tree.unflatten(
            self._blocks_tdef,
            [self._leaf_to_local(v, gs) for (_, v), gs in zip(bflat, self._blocks_gshapes)],
        )
        # flat-leaf classification for the distributed grad norm: each
        # block leaf carries its fsdp-sharded dim (None = replicated)
        bdims = {
            k: self._sharded_dim((gl,) + gs[1:])
            for (k, _), gs in zip(bflat, self._blocks_gshapes)
        }
        _prefix = f"{spec.blocks_key}/"
        self._flat_leaf_kinds = [
            ("block", bdims[k[len(_prefix):]]) if k.startswith(_prefix) else ("resident", None)
            for k, _ in _flatten_with_paths(params)
        ]
        opt_cfg = dict(config.optimizer.params or {})
        opt_name = (config.optimizer.name or "adamw").lower()
        if opt_name not in ("adam", "adamw"):
            raise ValueError(f"offload_param supports Adam/AdamW, got '{config.optimizer.name}'")
        nvme_dir = None
        if (zc.offload_optimizer.enabled and zc.offload_optimizer.device == "nvme") or (
            zc.offload_param.enabled and zc.offload_param.device == "nvme"
        ):
            nvme_dir = zc.offload_param.nvme_path or zc.offload_optimizer.nvme_path or "/tmp/ds_tpu_nvme"
            if jax.process_count() > 1:
                # on a real multi-host job the same path names each
                # host's LOCAL disk; the rank suffix additionally keeps
                # co-located test processes from clobbering each other
                nvme_dir = os.path.join(nvme_dir, f"rank{jax.process_index()}")
        self._host_opt = HostOffloadOptimizer(
            params,
            lr=opt_cfg.get("lr", 1e-3),
            betas=tuple(opt_cfg.get("betas", (0.9, 0.999))),
            eps=opt_cfg.get("eps", 1e-8),
            weight_decay=opt_cfg.get("weight_decay", 0.0),
            adamw_mode=opt_name == "adamw",
            nvme_swap_dir=os.path.join(nvme_dir, "moments") if (
                nvme_dir and zc.offload_optimizer.enabled and zc.offload_optimizer.device == "nvme"
            ) else None,
            aio_config=config.aio,
        )
        self._treedef = jax.tree.structure(params)
        # Host param views alias the optimizer's MASTER arrays by
        # construction (masters_tree() unflattens the very ndarrays
        # opt.step mutates in place) — the per-group write-back hook
        # fires mid-step and must see each group's freshly-updated rows,
        # so the aliasing is load-bearing, not an accident of
        # ascontiguousarray happening to return its input.
        self._params_host = self._host_opt.masters_tree()
        self._blocks_host = self._params_host[spec.blocks_key]
        self._resident_host = {
            k: v for k, v in self._params_host.items() if k != spec.blocks_key
        }

        # -- NVMe param staging (ZeRO-Infinity proper) ---------------------
        self._param_swapper = None
        if zc.offload_param.enabled and zc.offload_param.device == "nvme":
            from deepspeed_tpu.runtime.swap.async_swapper import AsyncTensorSwapper

            self._param_swapper = AsyncTensorSwapper(
                os.path.join(nvme_dir, "params"), aio_config=config.aio
            )
            self._swap_out_all_groups()
            log_dist(
                f"ZeRO-Infinity param offload: {self.n_groups} "
                f"{np.dtype(self._stage_np_dtype).name} layer-group files on NVMe "
                f"at {nvme_dir} (kernel AIO), one group resident in HBM at a time"
            )
        else:
            log_dist(
                f"ZeRO-Offload param streaming: params host-resident, "
                f"{self.group_layers} layer(s)/group × {self.n_groups} groups through HBM"
            )

        # -- schedules / bookkeeping --------------------------------------
        from deepspeed_tpu.runtime.lr_schedules import get_lr_schedule

        if callable(lr_scheduler):
            self.lr_schedule = lr_scheduler
        elif config.scheduler.type:
            self.lr_schedule = get_lr_schedule(config.scheduler.type, config.scheduler.params)
        else:
            base_lr = opt_cfg.get("lr", 1e-3)
            self.lr_schedule = lambda step: base_lr
        self.client_lr_scheduler = None
        self.optimizer = self._host_opt
        self.global_steps = 0
        self.skipped_steps = 0
        self._compiled: Dict[str, Any] = {}
        # batch rows shard over the whole DP world (data × fsdp), the
        # same convention as the in-HBM engine (sharding/layout.py,
        # re-exported through comm.mesh)
        self._batch_sh = NamedSharding(mesh, batch_pspec(1))
        # ZeRO-3 × ZeRO-Infinity composition (reference stage3.py:2633-2686
        # + partitioned_param_swapper.py:36 swap per-rank *partitions*):
        # each uploaded group is SHARDED over the fsdp axis — per-device
        # HBM holds group/fsdp param bytes; GSPMD all-gathers shards
        # inside the group programs and reduce-scatters group grads back
        # to the same 1/P layout (out_shardings below).  Shardings are
        # built from GLOBAL group shapes — the host slices are 1/H.
        self._group_gshapes = [(gl,) + gs[1:] for gs in self._blocks_gshapes]
        self._group_shardings = jax.tree.unflatten(
            self._blocks_tdef,
            [NamedSharding(mesh, self._fsdp_leaf_spec(gs)) for gs in self._group_gshapes],
        )
        log_dist(
            f"ZeRO-Infinity engine: {spec.n_layer} layers in {self.n_groups} groups, "
            f"micro_bs={config.train_micro_batch_size_per_gpu} gas={config.gradient_accumulation_steps} "
            f"dp={self.mesh_info.dp_world_size}"
        )

    # ------------------------------------------------------------------
    # host <-> device staging
    # ------------------------------------------------------------------
    def _fsdp_leaf_spec(self, shape):
        """fsdp PartitionSpec for one stacked-block leaf ``(gl, ...)``:
        shard the largest trailing dim divisible by the fsdp size (the
        leading stacked-layer dim stays whole — group_layers may be
        smaller than the axis); replicate when nothing divides.
        Resolved through the partition-rule engine's layout helper."""
        from deepspeed_tpu.sharding.layout import fsdp_trailing_spec

        return fsdp_trailing_spec(shape, self.mesh_info.fsdp_world_size)

    def _sharded_dim(self, group_shape) -> Optional[int]:
        """Index of the fsdp-sharded dim of one group leaf, or None."""
        for i, s in enumerate(self._fsdp_leaf_spec(group_shape)):
            if s == "fsdp":
                return i
        return None

    def _setup_host_partition(self, mesh) -> None:
        """Locate this host on the fsdp axis: the contiguous range of
        fsdp parts its local devices cover (masters / moments / NVMe
        bytes are kept ONLY for that range), and the sub-range it OWNS
        for grad-norm accounting (a part is owned by the lowest process
        index holding it, so every part is counted exactly once
        globally)."""
        me = jax.process_index()
        P = self.mesh_info.fsdp_world_size
        axis_i = list(mesh.axis_names).index("fsdp")
        owner: Dict[int, int] = {}
        local = set()
        for coord, dev in np.ndenumerate(mesh.devices):
            f = int(coord[axis_i])
            pi = int(dev.process_index)
            owner[f] = min(owner.get(f, pi), pi)
            if pi == me:
                local.add(f)
        parts = sorted(local)
        if parts != list(range(parts[0], parts[-1] + 1)):
            raise NotImplementedError(
                "offload_param: this host's fsdp shards are non-contiguous "
                f"on the mesh ({parts}); arrange the mesh so each host "
                "covers a contiguous fsdp range"
            )
        owned = sorted(f for f in parts if owner[f] == me)
        if owned and owned != list(range(owned[0], owned[-1] + 1)):
            raise NotImplementedError(
                f"offload_param: non-contiguous owned fsdp range {owned}"
            )
        self._part_local = (parts[0], parts[-1] + 1)
        self._part_owned = (owned[0], owned[-1] + 1) if owned else (0, 0)
        self._masters_sharded = (self._part_local[1] - self._part_local[0]) < P
        if self._masters_sharded:
            log_dist(
                f"ZeRO-Infinity multi-host: masters sharded 1/{P} per fsdp "
                f"part, this host keeps parts [{parts[0]}, {parts[-1] + 1})"
            )

    def _leaf_to_local(self, arr: np.ndarray, gshape) -> np.ndarray:
        """This host's slice of one full stacked-blocks leaf (the whole
        leaf when masters are not sharded across hosts)."""
        d = self._sharded_dim((self.group_layers,) + tuple(gshape[1:]))
        if d is None or not self._masters_sharded:
            return arr
        plo, phi = self._part_local
        per = gshape[d] // self.mesh_info.fsdp_world_size
        sl = [slice(None)] * len(gshape)
        sl[d] = slice(plo * per, phi * per)
        return np.ascontiguousarray(arr[tuple(sl)])

    @staticmethod
    def _to_local_np(garr, dtype=np.float32) -> np.ndarray:
        """Host copy of the process-local region of a (possibly
        multi-host) device array: the bounding box of this process's
        addressable shards — the full array single-process, this host's
        fsdp slice for sharded group grads."""
        if jax.process_count() == 1:
            return np.asarray(garr, dtype)
        shape = garr.shape
        boxes, lo, hi = [], list(shape), [0] * len(shape)
        for sh in garr.addressable_shards:
            b = []
            for i, sl in enumerate(sh.index):
                start = 0 if sl.start is None else int(sl.start)
                stop = shape[i] if sl.stop is None else int(sl.stop)
                b.append((start, stop))
                lo[i] = min(lo[i], start)
                hi[i] = max(hi[i], stop)
            boxes.append(b)
        out = np.empty([h - l for l, h in zip(lo, hi)], dtype)
        for sh, b in zip(garr.addressable_shards, boxes):
            dest = tuple(slice(s - l, e - l) for (s, e), l in zip(b, lo))
            out[dest] = np.asarray(sh.data, dtype)
        return out

    def _drain_group(self, tree) -> Any:
        """Group grads device→host, keeping only this host's local
        region of each leaf (matches the 1/H master slices)."""
        leaves = [self._to_local_np(l) for l in jax.tree.leaves(tree)]
        return jax.tree.unflatten(self._blocks_tdef, leaves)

    def _clip_and_check_global(self, grad_flat: List[np.ndarray]):
        """Global grad-norm clip + overflow check over host-sharded
        grads.  Each fsdp part is counted by exactly one process (its
        lowest-indexed holder) and the replicated resident leaves by
        process 0; the per-host partial sums meet in one tiny
        process_allgather.  Single-process: the numpy fast path."""
        clip = self.config.gradient_clipping
        if jax.process_count() == 1:
            _, norm, overflow = host_unscale_clip_and_check(grad_flat, 1.0, clip)
            return norm, overflow
        me = jax.process_index()
        plo, phi = self._part_local
        olo, ohi = self._part_owned
        sq, overflow = 0.0, False
        for (kind, d), g in zip(self._flat_leaf_kinds, grad_flat):
            if not np.all(np.isfinite(g)):
                overflow = True
            if kind == "resident" or d is None:
                if me == 0:
                    sq += float(np.sum(np.square(g, dtype=np.float64)))
            elif ohi > olo:
                per = g.shape[d] // (phi - plo)
                sl = [slice(None)] * g.ndim
                sl[d] = slice((olo - plo) * per, (ohi - plo) * per)
                sq += float(np.sum(np.square(g[tuple(sl)], dtype=np.float64)))
        from deepspeed_tpu.comm.collectives import host_allgather

        vec = np.asarray(
            host_allgather(np.asarray([sq, 1.0 if overflow else 0.0], np.float32))
        ).reshape(jax.process_count(), 2)
        norm = float(np.sqrt(vec[:, 0].sum()))
        overflow = bool(vec[:, 1].max() > 0)
        if clip > 0.0 and np.isfinite(norm) and norm > clip:
            factor = clip / (norm + 1e-6)
            for g in grad_flat:
                g *= factor
        return norm, overflow

    def _group_slice_host(self, g: int) -> Any:
        lo = g * self.group_layers
        return jax.tree.map(lambda a: a[lo : lo + self.group_layers], self._blocks_host)

    def _group_key(self, g: int) -> str:
        return f"group{g:04d}"

    @property
    def _stage_np_dtype(self):
        """NVMe staging dtype — the COMPUTE dtype, so a pure-fp32 config
        stages fp32 (no silent truncation to bf16)."""
        import ml_dtypes

        return ml_dtypes.bfloat16 if self.compute_dtype == jnp.bfloat16 else np.float32

    def _issue_group_swap_out(self, g: int) -> None:
        """Start the async NVMe write of group ``g``'s compute-dtype
        params (sourced from the just-updated master rows).  The write
        rides the swapper's dedicated write handle; a next-step read of
        the same group synchronizes it first (read-after-write hazard
        handled inside AsyncTensorSwapper)."""
        dt = self._stage_np_dtype
        flat = np.concatenate([
            np.asarray(l, dt).view(np.uint8).reshape(-1)
            for l in jax.tree.leaves(self._group_slice_host(g))
        ])
        self._param_swapper.swap_out(self._group_key(g), flat, async_op=True)

    def _swap_out_all_groups(self) -> None:
        """Write every group's compute-dtype params to NVMe and wait
        (init and checkpoint-load; the per-step path issues groups
        incrementally from the optimizer-step hook instead)."""
        for g in range(self.n_groups):
            self._issue_group_swap_out(g)
        self._param_swapper.synchronize_writes()

    def _upload_group(self, g: int) -> Any:
        """compute-dtype group params → device (from NVMe when staged)."""
        return self._finish_upload(g, self._issue_swap_in(g))

    def _issue_swap_in(self, g) -> Optional[np.ndarray]:
        """Start the async NVMe read of group ``g``'s staged bytes.
        Returns the in-flight host buffer (valid after the next
        ``_finish_upload``), or None when params live in host memory
        (no disk hop to hide — device_put happens at finish time).

        One read is kept in flight at a time: ``synchronize()`` waits on
        ALL pending aio ops, so issuing deeper would make finishing
        group g also wait for g+2's bytes."""
        if g is None or not (0 <= g < self.n_groups) or self._param_swapper is None:
            return None
        return self._param_swapper.swap_in(self._group_key(g), async_op=True)

    def _finish_upload(self, g: int, flat: Optional[np.ndarray]) -> Any:
        """Complete group ``g``'s upload: wait for its NVMe bytes (if
        staged) and hand them to the device (device_put is async — the
        H2D copy itself overlaps with whatever compute is in flight)."""
        host = self._group_slice_host(g)
        if self._param_swapper is None:
            return self._put_group(host)
        if flat is None:
            flat = self._param_swapper.swap_in(self._group_key(g), async_op=True)
        # wait for THIS read only — other groups' write-backs keep
        # overlapping this group's upload + compute
        self._param_swapper.synchronize_reads()
        dt = self._stage_np_dtype
        itemsize = np.dtype(dt).itemsize
        leaves, treedef = jax.tree.flatten(host)
        out, off = [], 0
        for l in leaves:
            nb = l.size * itemsize
            out.append(flat[off : off + nb].view(dt).reshape(l.shape))
            off += nb
        return self._put_group(jax.tree.unflatten(treedef, out))

    def _put_group(self, host_tree) -> Any:
        """One group's compute-dtype params → device, each device
        receiving only its 1/P fsdp slice.  Multi-host, the global array
        is assembled from each process's LOCAL 1/H master slice
        (``make_array_from_process_local_data``) — no host ever
        materializes a full group.  Casting happens on HOST (ml_dtypes);
        staging a full group on one device first would transiently break
        the per-device HBM bound the fsdp composition provides."""
        dt = self._stage_np_dtype
        if jax.process_count() == 1:
            return jax.device_put(
                jax.tree.map(lambda a: np.asarray(a, dt), host_tree),
                self._group_shardings,
            )
        out = [
            jax.make_array_from_process_local_data(sh, np.asarray(a, dt), tuple(gs))
            for a, sh, gs in zip(
                jax.tree.leaves(host_tree),
                jax.tree.leaves(self._group_shardings),
                self._group_gshapes,
            )
        ]
        return jax.tree.unflatten(self._blocks_tdef, out)

    @staticmethod
    def _start_host_copy(tree) -> None:
        """Kick off the D2H transfer of every leaf (best effort — some
        backends/tunnels don't expose copy_to_host_async)."""
        for leaf in jax.tree.leaves(tree):
            try:
                leaf.copy_to_host_async()
            except Exception:
                return

    def _upload_resident(self) -> Any:
        from deepspeed_tpu.sharding.layout import replicated_sharding

        # explicit replicated sharding: under multi-process execution
        # every host holds identical resident params and device_put
        # places each process's addressable shards (a bare device_put
        # would commit to one local device and break the global mesh)
        return jax.device_put(
            jax.tree.map(lambda a: jnp.asarray(a, self.compute_dtype), self._resident_host),
            replicated_sharding(self.mesh),
        )

    # ------------------------------------------------------------------
    # compiled stage programs (shapes identical across groups — one
    # compile each, reused G times per step)
    # ------------------------------------------------------------------
    def _programs(self):
        if self._compiled:
            return self._compiled
        spec = self.spec

        def embed(res, tokens):
            return spec.embed(res, tokens)

        def group_fwd(gp, x, rngs):
            return spec.group(gp, x, rngs, spec.deterministic)

        def head(res, x, batch):
            def f(res_, x_):
                return spec.head_loss(res_, x_, batch)

            loss, vjp = jax.vjp(f, res, x)
            d_res, dx = vjp(jnp.float32(1.0).astype(loss.dtype))
            return loss, d_res, dx

        def group_bwd(gp, x, rngs, dy):
            def f(gp_, x_):
                return spec.group(gp_, x_, rngs, spec.deterministic)

            _, vjp = jax.vjp(f, gp, x)
            dgp, dx = vjp(dy)
            return dgp, dx

        def embed_bwd(res, tokens, dx0):
            def f(res_):
                return spec.embed(res_, tokens)

            _, vjp = jax.vjp(f, res)
            (d_res,) = vjp(dx0)
            return d_res

        # eval variants: deterministic blocks (dropout OFF regardless of
        # training mode) and a forward-only head (no logits-cotangent)
        def group_eval(gp, x, rngs):
            return spec.group(gp, x, rngs, True)

        def head_eval(res, x, batch):
            return spec.head_loss(res, x, batch)

        from deepspeed_tpu.parallel.sequence import scoped_to

        mesh = self.mesh  # ambient mesh for traces
        self._compiled = {
            "embed": jax.jit(scoped_to(mesh, embed)),
            "group_fwd": jax.jit(scoped_to(mesh, group_fwd)),
            "head": jax.jit(scoped_to(mesh, head)),
            # group grads leave in the groups' own 1/P fsdp layout —
            # GSPMD lowers the grad reduction to a reduce-scatter over
            # fsdp (+ psum over data) instead of a full allreduce
            "group_bwd": jax.jit(
                scoped_to(mesh, group_bwd), donate_argnums=(3,),
                out_shardings=(self._group_shardings, self._batch_sh),
            ),
            "embed_bwd": jax.jit(scoped_to(mesh, embed_bwd), donate_argnums=(2,)),
            "group_eval": jax.jit(scoped_to(mesh, group_eval)),
            "head_eval": jax.jit(scoped_to(mesh, head_eval)),
        }
        return self._compiled

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------
    def _layer_rngs(self, step: int, micro: int):
        key = jax.random.fold_in(jax.random.PRNGKey(self.config.seed), step * 1000 + micro)
        return jax.random.split(key, self.spec.n_layer).reshape(self.n_groups, self.group_layers, 2)

    def train_batch(self, batch: Any, timing: Optional[dict] = None) -> jnp.ndarray:
        """One training step.  ``timing``: pass a dict to run this step
        SERIALIZED (block_until_ready after every phase) and receive a
        wall-clock decomposition — upload_s (host→device incl. NVMe
        read waits), fwd_s / bwd_s (chip compute), drain_s (device→host
        grad pulls), opt_s (host Adam + NVMe write issuance).  The
        serialized step is slower than a normal pipelined step (the
        overlaps are deliberately removed so each phase is attributable);
        use normal steps for throughput numbers."""
        import time as _time

        progs = self._programs()
        gas = self.config.gradient_accumulation_steps
        mb = self.config.train_micro_batch_size_per_gpu * self.mesh_info.dp_world_size
        batch = {k: np.asarray(v) for k, v in batch.items()}
        n_rows = next(iter(batch.values())).shape[0]
        if n_rows != mb * gas:
            raise ValueError(f"batch rows {n_rows} != micro_bs*dp*gas {mb * gas}")

        if timing is not None:
            timing.update({k: 0.0 for k in ("upload_s", "fwd_s", "bwd_s", "drain_s", "opt_s")})

        def _phase(key, fn, *a, **kw):
            if timing is None:
                return fn(*a, **kw)
            t0 = _time.time()
            out = fn(*a, **kw)
            jax.block_until_ready(out)
            timing[key] += _time.time() - t0
            return out

        res_dev = _phase("upload_s", self._upload_resident)
        grad_acc: Optional[List[np.ndarray]] = None
        losses = []
        for micro in range(gas):
            rows = slice(micro * mb, (micro + 1) * mb)
            mbatch = {
                k: jax.device_put(v[rows], self._batch_sh) for k, v in batch.items()
            }
            rngs = self._layer_rngs(self.global_steps, micro)
            tokens = mbatch["input_ids"]

            # ---- forward sweep: keep only the group BOUNDARY activations.
            # Pipeline: finish group g's upload, immediately issue the
            # NVMe read for g+1, then dispatch g's compute — the next
            # read and H2D ride under the current group's compute.
            xs = [_phase("fwd_s", progs["embed"], res_dev, tokens)]
            inflight = self._issue_swap_in(0)
            for g in range(self.n_groups):
                g_dev = _phase("upload_s", self._finish_upload, g, inflight)
                inflight = self._issue_swap_in(g + 1) if g + 1 < self.n_groups else None
                xs.append(_phase("fwd_s", progs["group_fwd"], g_dev, xs[-1], rngs[g]))

            loss, d_res, dx = _phase("fwd_s", progs["head"], res_dev, xs[-1], mbatch)
            losses.append(loss)

            # ---- backward sweep: re-upload groups in reverse, vjp each.
            # Group grads drain to host one group behind compute (async
            # D2H started at dispatch, converted next iteration), so HBM
            # holds at most TWO groups' grads — never the model's.
            micro_grads: List[Any] = [None] * self.n_groups
            inflight = self._issue_swap_in(self.n_groups - 1)
            pend_g, pend_dgp = None, None
            _drain = self._drain_group

            for g in range(self.n_groups - 1, -1, -1):
                g_dev = _phase("upload_s", self._finish_upload, g, inflight)
                inflight = self._issue_swap_in(g - 1) if g > 0 else None
                dgp, dx = _phase("bwd_s", progs["group_bwd"], g_dev, xs[g], rngs[g], dx)
                self._start_host_copy(dgp)
                if pend_g is not None:
                    micro_grads[pend_g] = _phase("drain_s", _drain, pend_dgp)
                pend_g, pend_dgp = g, dgp
            # dispatch the embed backward BEFORE draining the last
            # group's grads — the host-side conversion below blocks on
            # D2H and would otherwise idle the device
            d_res_embed = _phase("bwd_s", progs["embed_bwd"], res_dev, tokens, dx)
            if pend_g is not None:
                micro_grads[pend_g] = _phase("drain_s", _drain, pend_dgp)
            pend_dgp = None

            # ---- host grad accumulation (resident grads sum embed+head)
            d_res_total = _phase(
                "drain_s",
                lambda: jax.tree.map(
                    lambda a, b: np.asarray(a, np.float32) + np.asarray(b, np.float32),
                    jax.device_get(d_res), jax.device_get(d_res_embed),
                ),
            )
            blocks_grads = jax.tree.map(
                lambda *gs: np.concatenate([np.asarray(g, np.float32) for g in gs], axis=0),
                *micro_grads,
            )
            full = dict(d_res_total)
            full[self.spec.blocks_key] = blocks_grads
            flat = [np.asarray(l, np.float32) for l in jax.tree.leaves(full)]
            if grad_acc is None:
                grad_acc = flat
            else:
                for a, g_ in zip(grad_acc, flat):
                    a += g_

        for a in grad_acc:
            a /= gas
        grad_norm, overflow = self._clip_and_check_global(grad_acc)
        lr = float(self.lr_schedule(self.global_steps))
        if not overflow:
            grads_tree = jax.tree.unflatten(self._treedef, grad_acc)
            # NVMe path: step the stacked blocks group-major and start
            # each group's write-back the moment its master rows land —
            # the writes overlap the remaining groups' CPU Adam and the
            # next step's forward uploads instead of serializing at the
            # step boundary (was: _swap_out_all_groups + global wait,
            # ~model-size synchronous writes per step)
            swap = self._param_swapper is not None
            gl = self.group_layers
            masters = _phase(
                "opt_s",
                lambda: self._host_opt.step(
                    grads_tree, lr, self.global_steps + 1,
                    row_groups=[(g * gl, (g + 1) * gl) for g in range(self.n_groups)] if swap else None,
                    row_group_prefix=f"{self.spec.blocks_key}/" if swap else "",
                    on_group=self._issue_group_swap_out if swap else None,
                ),
            )
            self._params_host = masters
            self._blocks_host = masters[self.spec.blocks_key]
            self._resident_host = {k: v for k, v in masters.items() if k != self.spec.blocks_key}
            self.global_steps += 1
        else:
            self.skipped_steps += 1
            logger.warning("offload_param step skipped on non-finite grads")
        self._last_info = {"lr": lr, "grad_norm": grad_norm, "overflow": overflow}
        # telemetry (docs/telemetry.md): the streaming engine has no
        # StepTimeline — publish its step counters/gauges directly
        from deepspeed_tpu.telemetry import get_registry

        reg = get_registry()
        if reg.enabled:
            reg.counter("zinf/steps", engine="offload").inc()
            reg.gauge("zinf/lr", engine="offload").set(lr)
            if overflow:
                reg.counter("zinf/overflow_skips", engine="offload").inc()
            if timing is not None:
                for key, v in timing.items():
                    reg.gauge(f"zinf/{key}", engine="offload").set(v)
        return jnp.mean(jnp.stack(losses))

    def eval_batch(self, batch: Any) -> jnp.ndarray:
        progs = self._programs()
        batch = {k: jax.device_put(np.asarray(v), self._batch_sh) for k, v in batch.items()}
        res_dev = self._upload_resident()
        x = progs["embed"](res_dev, batch["input_ids"])
        rngs = self._layer_rngs(0, 0)
        inflight = self._issue_swap_in(0)
        for g in range(self.n_groups):
            g_dev = self._finish_upload(g, inflight)
            inflight = self._issue_swap_in(g + 1) if g + 1 < self.n_groups else None
            x = progs["group_eval"](g_dev, x, rngs[g])
        return progs["head_eval"](res_dev, x, batch)

    # ------------------------------------------------------------------
    # checkpointing (host masters are the source of truth)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None, client_state: Optional[dict] = None, save_latest: bool = True):
        tag = tag or f"global_step{self.global_steps}"
        path = os.path.join(os.path.abspath(save_dir), str(tag))
        os.makedirs(path, exist_ok=True)
        # Each process writes its OWN file — its full masters when
        # replicated, its 1/H fsdp slice when multi-host-sharded — so
        # per-host local disks work (no shared-FS assumption) and ranks
        # never race on one filename.  The barrier between phases is a
        # flag ALLGATHER, not sync_global_devices: every rank reaches it
        # even after a local write failure, so a failing rank surfaces
        # as a raised error on ALL ranks instead of a deadlock.
        def _sync_ok(ok: bool, what: str, cause=None) -> None:
            if jax.process_count() > 1:
                from deepspeed_tpu.comm.collectives import host_allgather

                flags = np.asarray(
                    host_allgather(np.float32(0.0 if ok else 1.0))
                ).reshape(-1)
                if flags.max() > 0:
                    raise RuntimeError(
                        f"checkpoint {what} write failed on rank(s) "
                        f"{np.nonzero(flags)[0].tolist()}"
                    ) from cause
            elif not ok:
                raise RuntimeError(f"checkpoint {what} write failed") from cause

        err = None
        try:
            self._host_opt.save(
                os.path.join(path, f"host_optimizer_rank{jax.process_index()}.npz")
            )
        except Exception as e:  # noqa: BLE001 — must still reach the barrier
            err = e
        _sync_ok(err is None, "optimizer-state", err)
        meta_err = None
        if jax.process_index() == 0:
            # rank 0 writes meta + the latest tag only after all opt
            # files are durable; everyone leaves only once those exist
            try:
                meta = {
                    "tag": str(tag), "global_step": self.global_steps,
                    "skipped_steps": self.skipped_steps, "client_state": client_state or {},
                    "engine": "zero_infinity_param_offload",
                    "process_count": jax.process_count(),
                    "masters_sharded": self._masters_sharded,
                }
                from deepspeed_tpu.resilience.atomic import atomic_write_text

                atomic_write_text(os.path.join(path, "meta.json"), json.dumps(meta, indent=2))
                if save_latest:
                    atomic_write_text(os.path.join(os.path.abspath(save_dir), "latest"), str(tag))
            except Exception as e:  # noqa: BLE001
                meta_err = e
        _sync_ok(meta_err is None, "meta/latest", meta_err)
        log_dist(f"saved ZeRO-Infinity checkpoint {path}")
        return path

    def _reassemble_host_state(self, path: str, meta: dict):
        """Reassemble the FULL host masters/moments from every saved
        rank's npz and re-slice for THIS engine's fsdp partition — the
        "resharding-compatible" topology relaxation: a sharded-master
        checkpoint restores at any process count, as long as all of the
        saving job's per-rank files are reachable (shared filesystem).
        Returns None when some rank file is missing (the caller raises
        the strict topology error then).

        Assumes the saving mesh gave each rank a contiguous, ascending
        fsdp range (the only layout ``_setup_host_partition`` accepts),
        so rank-order concatenation along each leaf's sharded dim
        recovers the full axis."""
        S = int(meta.get("process_count", 1))
        saved_sharded = bool(meta.get("masters_sharded", False))
        # replicated-masters saves: every rank file holds the SAME full
        # state, so rank 0's alone suffices (and avoids loading S
        # identical copies into host RAM)
        need = S if (saved_sharded and S > 1) else 1
        files = [os.path.join(path, f"host_optimizer_rank{r}.npz") for r in range(need)]
        if not all(os.path.exists(f) for f in files):
            return None
        datas = []
        for f in files:
            with np.load(f) as z:
                datas.append({k.replace("::", "/"): z[k] for k in z.files})
        plo, phi = self._part_local
        P = self.mesh_info.fsdp_world_size
        # _flat_leaf_kinds is aligned with the host optimizer's flat key
        # order (both come from _flatten_with_paths of the same tree)
        kinds = dict(zip(self._host_opt.keys, self._flat_leaf_kinds))
        out = {}
        for k in self._host_opt.keys:
            kind, d = kinds[k]
            for pfx in ("master", "m", "v"):
                key = f"{pfx}/{k}"
                if kind != "block" or d is None or not saved_sharded or S == 1:
                    full = datas[0][key]
                else:
                    full = np.concatenate([dd[key] for dd in datas], axis=d)
                if kind == "block" and d is not None and self._masters_sharded:
                    if full.shape[d] % P:
                        raise ValueError(
                            f"resharding-compatible restore: leaf '{k}' dim {d} "
                            f"({full.shape[d]}) is not divisible by fsdp={P}"
                        )
                    per = full.shape[d] // P
                    sl = [slice(None)] * full.ndim
                    sl[d] = slice(plo * per, phi * per)
                    full = np.ascontiguousarray(full[tuple(sl)])
                out[key] = full
        return out

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None, **_kw):
        load_dir = os.path.abspath(load_dir)
        if tag is None:
            latest = os.path.join(load_dir, "latest")
            if not os.path.exists(latest):
                return None, {}
            with open(latest) as f:
                tag = f.read().strip()
        path = os.path.join(load_dir, str(tag))
        # topology validation BEFORE any state is replaced: loading a
        # mismatched slice layout would corrupt the masters and only
        # raise afterwards (review finding r5)
        meta = {}
        meta_path = os.path.join(path, "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
        topo_mismatch = "masters_sharded" in meta and (
            bool(meta["masters_sharded"]) != self._masters_sharded
            or (self._masters_sharded and int(meta.get("process_count", 1)) != jax.process_count())
        )
        if topo_mismatch:
            # resharding-compatible (not identical) topology contract:
            # with every saved rank's file present, reassemble the full
            # masters and re-slice for this engine's partition
            data = self._reassemble_host_state(path, meta)
            if data is None:
                raise ValueError(
                    f"ZeRO-Infinity checkpoint {path} was saved with "
                    f"masters_sharded={meta['masters_sharded']} over "
                    f"{meta.get('process_count', 1)} processes; this engine has "
                    f"masters_sharded={self._masters_sharded} over "
                    f"{jax.process_count()} — and not all "
                    f"{meta.get('process_count', 1)} per-rank files are reachable, "
                    "so the fsdp axis cannot be resharded. Restore with a "
                    "matching topology or from a shared filesystem."
                )
            log_dist(
                f"ZeRO-Infinity: resharding host masters from "
                f"{meta.get('process_count', 1)} saved rank file(s) to this "
                f"topology (fsdp parts [{self._part_local[0]}, {self._part_local[1]}))"
            )
            self._host_opt.load_state_dict(data)
        else:
            # prefer this process's own file (per-host local disks); the
            # rank-0 file is equivalent on a shared filesystem ONLY when
            # masters are replicated — a sharded-master checkpoint holds a
            # different 1/H slice per rank
            opt_path = os.path.join(path, f"host_optimizer_rank{jax.process_index()}.npz")
            if not os.path.exists(opt_path):
                if self._masters_sharded:
                    raise FileNotFoundError(
                        f"ZeRO-Infinity checkpoint {path} has no file for rank "
                        f"{jax.process_index()} and masters are host-sharded "
                        "(each rank's slice differs; the rank-0 file is not a "
                        "substitute). Restore with the same process topology."
                    )
                opt_path = os.path.join(path, "host_optimizer_rank0.npz")
            if not os.path.exists(opt_path):
                logger.warning(f"ZeRO-Infinity checkpoint {path} not found")
                return None, {}
            self._host_opt.load(opt_path)
        masters = self._host_opt.masters_tree()
        self._params_host = masters
        self._blocks_host = masters[self.spec.blocks_key]
        self._resident_host = {k: v for k, v in masters.items() if k != self.spec.blocks_key}
        if self._param_swapper is not None:
            self._swap_out_all_groups()
        self.global_steps = int(meta.get("global_step", 0))
        self.skipped_steps = int(meta.get("skipped_steps", 0))
        log_dist(f"loaded ZeRO-Infinity checkpoint {path} (global_step={self.global_steps})")
        return path, meta.get("client_state", {})

    # -- API-compat shims ----------------------------------------------
    @property
    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def get_lr(self):
        return [float(self.lr_schedule(self.global_steps))]
