"""ZeRO memory estimators.

Reference: ``runtime/zero/stage2.py`` ``estimate_zero2_model_states_mem_needs``
(:2019) and the stage-3 equivalents — quick planning calculators that
print per-device memory needs for a model size × world size × offload
combination before anyone burns chips finding out empirically.

TPU memory model (bf16 compute, fp32 masters — matching this engine):

* stage 0:  device = 4N (fp32 params) + 4N (grads acc) + 8N (Adam m+v)
* stage 1:  optimizer states sharded over fsdp → 8N/W
* stage 2:  + grads sharded → 4N/W
* stage 3:  + params sharded → 4N/W (gather-on-use working set extra)
* offload_optimizer: masters+moments to host → device keeps 2N (bf16
  params) + grads; host gets 12N
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np


def _count(model_params) -> int:
    if isinstance(model_params, (int, np.integer)):
        return int(model_params)
    import jax

    return sum(int(np.prod(np.shape(p))) for p in jax.tree.leaves(model_params))


def _fmt_gb(n_bytes: float) -> str:
    return f"{n_bytes / 2**30:.2f}GB"


def estimate_zero2_model_states_mem_needs(
    total_params: Any,
    num_gpus_per_node: int = 1,
    num_nodes: int = 1,
    cpu_offload: bool = True,
    additional_buffer_factor: float = 1.5,
) -> Tuple[float, float]:
    """Returns (cpu_mem, device_mem) bytes per device for ZeRO-2
    (reference signature preserved; "gpu" = chip)."""
    N = _count(total_params)
    W = max(1, num_gpus_per_node * num_nodes)
    if cpu_offload:
        device = 2 * N + 4 * N / W  # bf16 params + fp32 grad shard
        cpu = 12 * N * additional_buffer_factor  # masters + m + v
    else:
        device = 4 * N + 4 * N / W + 8 * N / W  # fp32 params + grad/opt shards
        cpu = 4 * N * additional_buffer_factor  # host init copy
    return cpu, device


def estimate_zero3_model_states_mem_needs(
    total_params: Any,
    largest_layer_params: int = 0,
    num_gpus_per_node: int = 1,
    num_nodes: int = 1,
    cpu_offload: bool = True,
    cpu_offload_params: bool = False,
    zero_init: bool = True,
    additional_buffer_factor: float = 1.5,
) -> Tuple[float, float, float]:
    """Returns (cpu_mem, device_mem, largest_layer_mem) bytes per device
    for ZeRO-3."""
    N = _count(total_params)
    L = int(largest_layer_params)
    W = max(1, num_gpus_per_node * num_nodes)
    largest = 4 * L  # gathered working set (bf16 fwd+bwd pair)
    if cpu_offload:
        device = (2 * N + 4 * N) / W + largest
        cpu = 12 * N * additional_buffer_factor
    else:
        device = (4 * N + 4 * N + 8 * N) / W + largest
        cpu = (4 * N if not zero_init else 4 * N / W) * additional_buffer_factor
    if cpu_offload_params:
        device = 4 * N / W + largest
        cpu = (12 * N + 2 * N) * additional_buffer_factor
    return cpu, device, largest


def _print_table(rows, header):
    widths = [max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))]
    line = "  ".join(str(h).ljust(w) for h, w in zip(header, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))


def estimate_zero2_model_states_mem_needs_all_live(
    model_params: Any, num_gpus_per_node: int = 1, num_nodes: int = 1, additional_buffer_factor: float = 1.5
) -> None:
    """Reference ``estimate_zero2_model_states_mem_needs_all_live``:
    prints the offload matrix for a live params pytree (or a param
    count)."""
    N = _count(model_params)
    print(f"Estimated memory needed for params={N / 1e6:.0f}M, ZeRO-2, "
          f"{num_nodes} node(s) x {num_gpus_per_node} chip(s)")
    rows = []
    for offload in (True, False):
        cpu, dev = estimate_zero2_model_states_mem_needs(
            N, num_gpus_per_node, num_nodes, cpu_offload=offload, additional_buffer_factor=additional_buffer_factor
        )
        rows.append([_fmt_gb(cpu), _fmt_gb(dev), f"offload_optimizer={'cpu' if offload else 'none'}"])
    _print_table(rows, ["host mem", "per-chip mem", "options"])


def estimate_zero3_model_states_mem_needs_all_live(
    model_params: Any,
    largest_layer_params: int = 0,
    num_gpus_per_node: int = 1,
    num_nodes: int = 1,
    additional_buffer_factor: float = 1.5,
) -> None:
    N = _count(model_params)
    print(f"Estimated memory needed for params={N / 1e6:.0f}M, ZeRO-3, "
          f"{num_nodes} node(s) x {num_gpus_per_node} chip(s)")
    rows = []
    for offload, offload_params in ((False, False), (True, False), (True, True)):
        cpu, dev, live = estimate_zero3_model_states_mem_needs(
            N, largest_layer_params, num_gpus_per_node, num_nodes,
            cpu_offload=offload, cpu_offload_params=offload_params,
            additional_buffer_factor=additional_buffer_factor,
        )
        opt = "none" if not offload else ("cpu" if not offload_params else "cpu+params")
        rows.append([_fmt_gb(cpu), _fmt_gb(dev), _fmt_gb(live), f"offload={opt}"])
    _print_table(rows, ["host mem", "per-chip mem", "gathered layer", "options"])
