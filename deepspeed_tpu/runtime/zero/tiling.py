"""TiledLinear — split huge linears into tiles.

Reference: ``runtime/zero/tiling.py`` (``TiledLinear`` :27): a linear too
large for one allocation is split into ``in_splits × out_splits`` tiles
so ZeRO-3 can partition/gather them independently (and activation memory
amortizes per tile).

TPU-native form: the same tiling as a parameter-layout choice — tiles
are separate leaves of the param pytree (so ZeRO sharding rules treat
each independently) and the apply function contracts them tile-by-tile
under ``jax.checkpoint``-compatible code.  For most models plain
PartitionSpec sharding of one big weight is better (GSPMD slices it);
TiledLinear remains for reference parity and for weights exceeding a
single shard's HBM.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def split_dim(total: int, splits: int) -> List[int]:
    """Near-even split sizes (reference uses torch chunk semantics)."""
    base, rem = divmod(total, splits)
    return [base + (1 if i < rem else 0) for i in range(splits)]


def init_tiled_linear(
    in_features: int,
    out_features: int,
    in_splits: int = 1,
    out_splits: int = 1,
    bias: bool = True,
    seed: int = 0,
    std: float = 0.02,
) -> Dict[str, Any]:
    """Param tree: ``tile_{i}_{j}_w`` of shape (in_i, out_j) + per-out
    ``bias_{j}``."""
    rng = np.random.default_rng(seed)
    in_sizes = split_dim(in_features, in_splits)
    out_sizes = split_dim(out_features, out_splits)
    params: Dict[str, Any] = {}
    for i, ni in enumerate(in_sizes):
        for j, nj in enumerate(out_sizes):
            params[f"tile_{i}_{j}_w"] = (rng.standard_normal((ni, nj)) * std).astype(np.float32)
    if bias:
        for j, nj in enumerate(out_sizes):
            params[f"bias_{j}"] = np.zeros(nj, np.float32)
    return params


def tiled_linear(params: Dict[str, Any], x: jnp.ndarray) -> jnp.ndarray:
    """``x @ W + b`` computed tile-by-tile; numerically identical to the
    dense linear assembled from the tiles.  The tiling structure is
    recovered from the param keys/shapes (pure-weight pytree, grad-safe)."""
    in_splits = 1 + max(int(k.split("_")[1]) for k in params if k.startswith("tile_"))
    out_splits = 1 + max(int(k.split("_")[2]) for k in params if k.startswith("tile_"))
    has_bias = "bias_0" in params
    in_sizes = [params[f"tile_{i}_0_w"].shape[0] for i in range(in_splits)]
    offsets = np.cumsum([0] + in_sizes)
    outs = []
    for j in range(out_splits):
        acc = None
        for i in range(in_splits):
            xi = x[..., offsets[i] : offsets[i + 1]]
            w = params[f"tile_{i}_{j}_w"]
            part = xi @ w.astype(xi.dtype)
            acc = part if acc is None else acc + part
        if has_bias:
            acc = acc + params[f"bias_{j}"].astype(acc.dtype)
        outs.append(acc)
    return jnp.concatenate(outs, axis=-1)


class TiledLinear:
    """Stateful wrapper mirroring the reference module surface."""

    def __init__(self, in_features: int, out_features: int, in_splits: int = 1, out_splits: int = 1, bias: bool = True, seed: int = 0):
        if in_splits < 1 or out_splits < 1:
            raise ValueError("in_splits/out_splits must be >= 1")
        self.in_features = in_features
        self.out_features = out_features
        self.in_splits = in_splits
        self.out_splits = out_splits
        self.params = init_tiled_linear(in_features, out_features, in_splits, out_splits, bias=bias, seed=seed)

    def __call__(self, x) -> jnp.ndarray:
        return tiled_linear(jax.tree.map(jnp.asarray, self.params), jnp.asarray(x))

    def copy_params_from(self, weight: np.ndarray, bias: Optional[np.ndarray] = None) -> None:
        """Load from a dense (in, out) weight (reference
        ``copy_params_from`` takes the fused linear)."""
        weight = np.asarray(weight, np.float32)
        assert weight.shape == (self.in_features, self.out_features)
        in_sizes = split_dim(self.in_features, self.in_splits)
        out_sizes = split_dim(self.out_features, self.out_splits)
        io = np.cumsum([0] + in_sizes)
        oo = np.cumsum([0] + out_sizes)
        for i in range(self.in_splits):
            for j in range(self.out_splits):
                self.params[f"tile_{i}_{j}_w"] = np.ascontiguousarray(
                    weight[io[i] : io[i + 1], oo[j] : oo[j + 1]]
                )
        if bias is not None:
            for j in range(self.out_splits):
                self.params[f"bias_{j}"] = np.ascontiguousarray(np.asarray(bias, np.float32)[oo[j] : oo[j + 1]])
