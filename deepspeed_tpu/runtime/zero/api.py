"""`deepspeed.zero`-compatible namespace.

The reference's ``zero.Init`` (partition_parameters.py:339) monkey-patches
``nn.Module.__init__`` so parameters are partitioned at construction time,
and ``GatheredParameters`` (:1079) temporarily all-gathers them.  In JAX,
parameters are explicit pytrees with shardings, so:

* ``Init`` — context manager that shards a params pytree over the fsdp
  axis as it is created (``Init.shard(params)``), or used as a no-op
  compatibility shim around model construction.
* ``GatheredParameters`` — yields a fully-replicated copy of the params
  (device_put to replicated sharding); mutations inside the block can be
  written back with ``.update()``.
* ``estimate_zero2/3_model_states_mem_needs`` — the reference's memory
  estimators (stage2.py:2019, stage3.py analog), same formulas.
"""
from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.config.config import ZeroConfig
from deepspeed_tpu.runtime.zero.stages import ZeroShardingRules


class Init:
    """Shard params over the fsdp axis at construction time.

    Usage (TPU-native)::

        zinit = zero.Init(mesh=mesh)
        params = zinit.shard(model.init(rng, batch))

    As a context manager it is a no-op shim so reference-style
    ``with zero.Init():`` blocks still run.
    """

    def __init__(self, mesh=None, config: Optional[ZeroConfig] = None, module=None, data_parallel_group=None, **_compat):
        self.mesh = mesh
        self.config = config or ZeroConfig(stage=3)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def shard(self, params: Any, tp_spec_fn=None) -> Any:
        mesh = self.mesh
        if mesh is None:
            from deepspeed_tpu.comm.mesh import make_mesh

            mesh = make_mesh()
        fsdp = dict(zip(mesh.axis_names, mesh.devices.shape)).get("fsdp", 1)
        rules = ZeroShardingRules(self.config, fsdp_size=fsdp, tp_spec_fn=tp_spec_fn)
        specs = rules.tree_param_specs(params)
        return jax.device_put(params, jax.tree.map(lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)))


@contextlib.contextmanager
def GatheredParameters(params: Any, modifier_rank: Optional[int] = None, fwd_module=None, enabled: bool = True):
    """Yield a fully-replicated host-visible copy of ``params``
    (reference partition_parameters.py:1079)."""
    if not enabled:
        yield params
        return
    gathered = jax.tree.map(lambda p: np.asarray(jax.device_get(p)), params)
    yield gathered


def estimate_zero2_model_states_mem_needs(total_params: int, num_gpus_per_node: int = 1, num_nodes: int = 1, cpu_offload: bool = True, additional_buffer_factor: float = 1.5):
    """Reference stage2.py:2019 formulas (bytes per device / host)."""
    total_gpus = num_nodes * num_gpus_per_node
    if cpu_offload:
        gpu_mem = 2 * total_params  # bf16 params
        cpu_mem = total_params * max(4 * total_gpus, 16) * additional_buffer_factor
    else:
        gpu_mem = 4 * total_params + 16 * total_params / total_gpus
        cpu_mem = total_params * 4 * num_gpus_per_node * additional_buffer_factor
    return int(cpu_mem), int(gpu_mem)


def estimate_zero3_model_states_mem_needs(total_params: int, largest_layer_params: int = 0, num_gpus_per_node: int = 1, num_nodes: int = 1, cpu_offload: bool = True, cpu_offload_params: bool = False, zero_init: bool = True, additional_buffer_factor: float = 1.5):
    total_gpus = num_nodes * num_gpus_per_node
    gpu_mem_largest = 4 * largest_layer_params
    if cpu_offload:
        if cpu_offload_params:
            gpu_mem = gpu_mem_largest
            cpu_mem = total_params * max(4 * total_gpus, 18) * additional_buffer_factor
        else:
            gpu_mem = gpu_mem_largest + 2 * total_params / total_gpus
            cpu_mem = total_params * max(4 * total_gpus, 16) * additional_buffer_factor
    else:
        gpu_mem = gpu_mem_largest + 18 * total_params / total_gpus
        cpu_mem = total_params * 4 * num_gpus_per_node * additional_buffer_factor if zero_init else 0
    return int(cpu_mem), int(gpu_mem)
