"""ZeRO-Offload / ZeRO-Infinity — host-resident optimizer.

Reference behavior being reproduced (SURVEY.md §2.5):

* **ZeRO-Offload (CPU)**: grads stream to pinned host fp32 buffers
  (``stage2.py:898-1023``), the optimizer step runs on host cores via the
  AVX ``DeepSpeedCPUAdam`` (``engine.py:776-780``), updated fp16 params
  copy back to the device.
* **ZeRO-Infinity (NVMe)**: optimizer moments additionally live on NVMe,
  streamed around each sub-group's update by the double-buffered
  ``PipelinedOptimizerSwapper`` (``pipelined_optimizer_swapper.py:60``).

TPU-native form: the engine keeps **bf16 params in HBM**; fp32 masters +
Adam moments live in host RAM (``device: cpu``) with moments optionally
on local SSD (``device: nvme``).  Each optimizer step: averaged fp32
grads device→host, per-leaf host Adam (C++ OpenMP kernel,
``csrc/adam/cpu_adam.cpp``) pipelined against NVMe moment prefetch/
write-back, then masters cast bf16 and host→device.  The jitted train
step is untouched — offload only swaps the step executor.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import log_dist, logger


def _flatten_with_paths(tree: Any) -> List[Tuple[str, Any]]:
    import jax

    out = []

    def visit(path, leaf):
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
        out.append((key, leaf))

    jax.tree_util.tree_map_with_path(visit, tree)
    return out


class HostOffloadOptimizer:
    """Owns fp32 masters + moments on host; steps them with the native
    CPU Adam; optionally swaps moments to NVMe."""

    def __init__(
        self,
        params: Any,
        lr: float = 1e-3,
        betas=(0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        adamw_mode: bool = True,
        nvme_swap_dir: Optional[str] = None,
        aio_config=None,
        pipeline: bool = True,
    ):
        import jax

        self._treedef = jax.tree.structure(params)
        flat = _flatten_with_paths(params)
        self.keys = [k for k, _ in flat]
        self.masters: List[np.ndarray] = [
            np.ascontiguousarray(np.asarray(v), np.float32) for _, v in flat
        ]
        self.opt = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay, adamw_mode=adamw_mode)
        self.swapper = None
        if nvme_swap_dir is not None:
            from deepspeed_tpu.runtime.swap.optimizer_swapper import PipelinedOptimizerSwapper

            self.swapper = PipelinedOptimizerSwapper(
                nvme_swap_dir, [m.shape for m in self.masters], aio_config=aio_config, pipeline=pipeline
            )
            log_dist(f"ZeRO-Infinity: {len(self.masters)} moment groups on NVMe at {nvme_swap_dir}")
        else:
            self._m = [np.zeros_like(m) for m in self.masters]
            self._v = [np.zeros_like(m) for m in self.masters]
            host_gb = sum(m.nbytes for m in self.masters) * 3 / 1e9
            log_dist(f"ZeRO-Offload: fp32 masters+moments on host ({host_gb:.2f} GB)")

    @property
    def uses_native_kernel(self) -> bool:
        return self.opt.uses_native

    def step(
        self,
        grads: Any,
        lr: float,
        step_count: int,
        row_groups=None,
        row_group_prefix: str = "",
        on_group=None,
    ) -> Any:
        """``grads``: pytree of host fp32 arrays matching the params
        structure.  Updates masters in place; returns the masters tree.

        ``row_groups``: optional list of ``(lo, hi)`` leading-dim row
        ranges over the leaves whose key starts with
        ``row_group_prefix`` (the streaming engine's stacked blocks).
        When given, those leaves step group-major and ``on_group(g)``
        fires the moment range ``g``'s rows are updated across ALL
        selected leaves — letting the caller overlap per-group NVMe
        write-back with the remainder of the optimizer step (the
        reference's pipelined swap pattern,
        ``pipelined_optimizer_swapper.py:60``).  Ignored when moments
        are themselves NVMe-swapped (group-major order would re-read
        every leaf's moments once per group)."""
        import jax

        gflat = [np.asarray(g, np.float32) for _, g in _flatten_with_paths(grads)]
        assert len(gflat) == len(self.masters)
        n = len(self.masters)
        grouped = row_groups is not None and self.swapper is None
        sel = (
            [i for i in range(n) if self.keys[i].startswith(row_group_prefix)]
            if grouped else []
        )
        rest = [i for i in range(n) if i not in set(sel)] if grouped else range(n)
        for i in rest:
            if self.swapper is not None:
                if i + 1 < n:
                    self.swapper.prefetch(i + 1)  # overlap next group's read
                bufs = self.swapper.get(i)
                m, v = bufs["m"], bufs["v"]
            else:
                m, v = self._m[i], self._v[i]
            self.opt.step(self.masters[i], gflat[i], m, v, step_count, lr=lr)
            if self.swapper is not None:
                self.swapper.put(i)  # async write-back while next group steps
        if grouped:
            for g, (lo, hi) in enumerate(row_groups):
                for i in sel:
                    # leading-dim slices of contiguous arrays stay
                    # contiguous — the native kernel steps them in place
                    self.opt.step(
                        self.masters[i][lo:hi], gflat[i][lo:hi],
                        self._m[i][lo:hi], self._v[i][lo:hi], step_count, lr=lr,
                    )
                if on_group is not None:
                    on_group(g)
        elif row_groups is not None and on_group is not None:
            for g in range(len(row_groups)):
                on_group(g)
        if self.swapper is not None:
            self.swapper.flush()
        return jax.tree.unflatten(self._treedef, self.masters)

    def masters_tree(self) -> Any:
        import jax

        return jax.tree.unflatten(self._treedef, self.masters)

    def load_masters(self, params: Any) -> None:
        flat = [np.ascontiguousarray(np.asarray(v), np.float32) for _, v in _flatten_with_paths(params)]
        assert len(flat) == len(self.masters)
        self.masters = flat

    # -- checkpoint support ------------------------------------------------
    def state_dict(self) -> Dict[str, np.ndarray]:
        out = {}
        for i, k in enumerate(self.keys):
            out[f"master/{k}"] = self.masters[i]
            if self.swapper is not None:
                bufs = self.swapper.get(i)
                out[f"m/{k}"], out[f"v/{k}"] = bufs["m"], bufs["v"]
            else:
                out[f"m/{k}"], out[f"v/{k}"] = self._m[i], self._v[i]
        return out

    def save(self, path: str) -> None:
        np.savez(path, **{k.replace("/", "::"): v for k, v in self.state_dict().items()})

    def load(self, path: str) -> None:
        with np.load(path) as z:
            self.load_state_dict({k.replace("::", "/"): z[k] for k in z.files})

    def load_state_dict(self, data: Dict[str, np.ndarray]) -> None:
        """Install ``master/ m/ v/``-keyed arrays (the :meth:`state_dict`
        layout) — the entry point the resharding-compatible restore
        feeds reassembled-and-resliced state through."""
        for i, k in enumerate(self.keys):
            want = self.masters[i].shape
            got = np.shape(data[f"master/{k}"])
            if tuple(got) != tuple(want):
                raise ValueError(
                    f"host optimizer leaf '{k}': checkpoint shape {tuple(got)} != "
                    f"engine shape {tuple(want)}"
                )
            self.masters[i] = np.ascontiguousarray(data[f"master/{k}"], np.float32)
            m, v = data[f"m/{k}"], data[f"v/{k}"]
            if self.swapper is not None:
                self.swapper.load_group(i, m, v)
            else:
                self._m[i] = np.ascontiguousarray(m, np.float32)
                self._v[i] = np.ascontiguousarray(v, np.float32)


def host_unscale_clip_and_check(
    grads_flat: List[np.ndarray], scale: float, clip: float
) -> Tuple[List[np.ndarray], float, bool]:
    """Host-side unscale + global-norm clip + overflow check (the jitted
    path's ``unscale_and_check`` + ``_clip_by_global_norm`` equivalents,
    numpy because the step executor runs on host in offload mode)."""
    inv = 1.0 / scale
    overflow = False
    sq = 0.0
    for g in grads_flat:
        g *= inv
        if not np.all(np.isfinite(g)):
            overflow = True
        sq += float(np.sum(np.square(g, dtype=np.float64)))
    norm = float(np.sqrt(sq))
    if clip > 0.0 and np.isfinite(norm) and norm > clip:
        factor = clip / (norm + 1e-6)
        for g in grads_flat:
            g *= factor
    return grads_flat, norm, overflow
