"""ZeRO stages 1–3 as SPMD sharding rules.

The reference implements ZeRO with three optimizer-wrapper classes that
intercept autograd (``runtime/zero/stage1.py``, ``stage2.py:70``,
``stage3.py:595``) and hand-roll partitioning, bucketed reduce-scatter,
gather-on-use hooks and prefetching.  On TPU, every one of those moving
parts is a *sharding annotation* compiled by GSPMD (SURVEY.md §7 design
stance):

* **Stage 1** — optimizer state sharded over the ``fsdp`` axis.  XLA
  partitions the weight-update computation across ranks and all-gathers
  updated params ("automatic cross-replica sharding of weight update",
  the ZeRO-1 insight, arXiv:2004.13336).
* **Stage 2** — + gradients constrained to ``fsdp``-sharded: the grad
  psum becomes a reduce-scatter (the reference's bucketed async
  ``average_tensor`` path, stage2.py:780, for free — XLA buckets and
  overlaps collectives itself).
* **Stage 3** — + parameters sharded over ``fsdp``; GSPMD inserts
  all-gathers *just in time* at each use site and frees gathered
  buffers after last use, which is exactly the reference's
  fetch/release/prefetch coordinator (stage3.py:169-533) as a compiler
  schedule.  Small params stay replicated via the persistence threshold
  (stage3.py:1416 semantics).

The rules compose with tensor-parallel PartitionSpecs: fsdp is placed on
the largest dimension not already consumed by ``model``/other axes.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.config.config import ZeroConfig
from deepspeed_tpu.sharding.layout import DEFAULT_LAYOUT
from deepspeed_tpu.sharding.update import (
    add_mesh_axis,
    add_update_axis,
    spec_tuple as _spec_tuple,
)


def add_fsdp_axis(
    shape: Sequence[int],
    base_spec: Optional[P],
    fsdp_size: int,
    min_size: int = 0,
) -> P:
    """Add the ``fsdp`` axis to a param's PartitionSpec.

    Picks the largest dim that (a) is not already sharded by another axis
    and (b) is divisible by ``fsdp_size``.  Params smaller than
    ``min_size`` elements (the ZeRO-3 persistence threshold,
    stage3.py:1416) or with no divisible dim stay as-is (replicated over
    fsdp) — matching the reference's ``persistent_parameters`` behavior.
    (Thin wrapper over the axis-placement primitive in sharding/update.py.)
    """
    return add_mesh_axis(shape, base_spec, DEFAULT_LAYOUT.fsdp_axis, fsdp_size, min_size=min_size)


class ZeroShardingRules:
    """Produces PartitionSpecs for params / grads / optimizer state for a
    given ZeRO stage.  ``tp_spec_fn(path, shape)`` supplies the
    tensor-parallel base spec (the ``model`` axis) if any — in practice
    the partition-rule engine's adapter
    (:meth:`deepspeed_tpu.sharding.rules.PartitionRules.tp_spec_fn`).

    ``data_size`` enables **cross-replica weight-update sharding**
    (arXiv:2004.13336; ``zero_optimization.cross_replica_weight_update``,
    default on): optimizer state — and with it the update computation —
    shards across the pure ``data`` axis too, so stage 1 on a pure-DP
    mesh cuts per-replica update FLOPs and optimizer-state bytes ~dp×
    for one updated-params all-gather per step."""

    def __init__(self, zero_config: ZeroConfig, fsdp_size: int, tp_spec_fn=None, data_size: int = 1):
        self.config = zero_config
        self.stage = zero_config.stage
        self.fsdp_size = fsdp_size
        self.data_size = data_size
        self.tp_spec_fn = tp_spec_fn or (lambda path, shape: None)
        # paths stored flat-padded in engine state (see plan_flat)
        self.flat_paths: set = set()

    @property
    def cross_replica_active(self) -> bool:
        """Whether optimizer state shards across the pure data axis."""
        return (
            self.stage >= 1
            and self.data_size > 1
            and getattr(self.config, "cross_replica_weight_update", True)
        )

    # -- flat-fallback plan ------------------------------------------------
    def plan_flat(self, params: Any) -> dict:
        """Choose leaves that dimension-wise sharding cannot cover — no
        axis divisible by ``fsdp_size`` and no tensor-parallel spec — and
        return ``{path: (shape, size, padded_size)}`` for them.

        The engine stores those leaves (params / grads / optimizer state)
        as zero-padded 1-D fp32 vectors sharded over ``fsdp``, the JAX
        analog of the reference's flattened contiguous partitions
        (``stage2.py:432``, ``partition_parameters.py:688``): every
        element shards 1/W regardless of tensor shape.
        """
        plan: dict = {}
        if self.fsdp_size <= 1 or self.stage < 1:
            self.flat_paths = set()
            return plan
        threshold = self.config.param_persistence_threshold if self.stage >= 3 else 0

        def visit(path, leaf):
            p = _path_str(path)
            shape = tuple(np.shape(leaf))
            n = int(np.prod(shape)) if shape else 1
            if not shape or n < max(self.fsdp_size, threshold):
                return
            if self.tp_spec_fn(p, shape) is not None:
                return
            spec = add_fsdp_axis(shape, None, self.fsdp_size)
            if any(a == "fsdp" for a in _spec_tuple(spec, len(shape))):
                return  # dim-shardable: the normal path covers it
            padded = -(-n // self.fsdp_size) * self.fsdp_size
            plan[p] = (shape, n, padded)

        jax.tree_util.tree_map_with_path(visit, params)
        self.flat_paths = set(plan)
        return plan

    def _flat_spec(self) -> P:
        """Spec of a flat-padded 1-D state leaf (sharded over fsdp)."""
        from deepspeed_tpu.sharding.layout import dp_rows_spec

        return dp_rows_spec(DEFAULT_LAYOUT.fsdp_axis)

    # -- params ------------------------------------------------------------
    def param_spec(self, path, shape) -> P:
        if path in self.flat_paths:
            return self._flat_spec() if self.stage >= 3 else P()
        base = self.tp_spec_fn(path, shape)
        if self.stage >= 3 and self.fsdp_size > 1:
            return add_fsdp_axis(shape, base, self.fsdp_size, min_size=self.config.param_persistence_threshold)
        return base if base is not None else P()

    # -- grads -------------------------------------------------------------
    def grad_spec(self, path, shape) -> P:
        # zero_optimization.reduce_scatter = false (reference stage2.py's
        # allreduce fallback): grads stay replicated over fsdp, so GSPMD
        # emits a full all-reduce instead of a psum_scatter — ~2x the
        # wire and a params-sized grad buffer per chip; the engine warns
        # once and the comm layer records the forced-dense decision
        if path in self.flat_paths:
            return self._flat_spec() if self.stage >= 2 and self.config.reduce_scatter else P()
        base = self.tp_spec_fn(path, shape)
        if self.stage >= 2 and self.fsdp_size > 1 and self.config.reduce_scatter:
            # stage 3 grads are sharded the same way as the param so the
            # reduce-scatter lands at the owner (partition_parameters.py:934)
            min_size = self.config.param_persistence_threshold if self.stage >= 3 else 0
            return add_fsdp_axis(shape, base, self.fsdp_size, min_size=min_size)
        return base if base is not None else P()

    # -- optimizer state ---------------------------------------------------
    def opt_spec(self, path, shape) -> P:
        if path in self.flat_paths:
            # flat leaves keep their fsdp-only layout (their padded size
            # is a function of fsdp_size alone — see plan_flat); the
            # cross-replica win on these rare awkward leaves is not
            # worth a second padding geometry
            return self._flat_spec()
        base = self.tp_spec_fn(path, shape)
        spec = base if base is not None else P()
        if self.stage >= 1 and self.fsdp_size > 1:
            min_size = self.config.param_persistence_threshold if self.stage >= 3 else 0
            spec = add_fsdp_axis(shape, base, self.fsdp_size, min_size=min_size)
        if self.cross_replica_active:
            # cross-replica weight-update sharding: the update math
            # follows the optimizer-state placement, so extending the
            # state across ``data`` shards the update ~dp× (the params
            # all-gather back at the constraint in the engine's update)
            spec = add_update_axis(
                shape, spec, DEFAULT_LAYOUT.data_axis, self.data_size,
                fsdp_axis=DEFAULT_LAYOUT.fsdp_axis, fsdp_size=self.fsdp_size,
            )
        return spec

    # -- pytree helpers ----------------------------------------------------
    def tree_param_specs(self, params: Any) -> Any:
        return _tree_specs_with_paths(params, self.param_spec)

    def tree_grad_specs(self, params: Any) -> Any:
        return _tree_specs_with_paths(params, self.grad_spec)

    def tree_opt_specs_like(self, params: Any) -> Any:
        """Specs for one params-shaped slot of optimizer state (m or v)."""
        return _tree_specs_with_paths(params, self.opt_spec)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _tree_specs_with_paths(tree: Any, spec_fn) -> Any:
    return jax.tree_util.tree_map_with_path(lambda path, leaf: spec_fn(_path_str(path), leaf.shape), tree)


def map_param_shaped_subtrees(tree: Any, ref: Any, fn, default=None) -> Any:
    """Apply ``fn`` (a tree transform) to every subtree of ``tree`` whose
    structure and leaf shapes match ``ref`` (the params tree); everything
    else is left as-is, or replaced by ``default(leaf)`` when given.

    The shape-matching-within-structure trick is how optimizer-state m/v
    mirrors (AdamState's exp_avg/exp_avg_sq follow the params treedef)
    are located without knowing the optimizer's state schema.
    """
    ref_struct = jax.tree.structure(ref)
    ref_leaves = jax.tree.leaves(ref)

    def convert(node):
        try:
            if jax.tree.structure(node) == ref_struct:
                leaves = jax.tree.leaves(node)
                if all(
                    hasattr(l, "shape") and tuple(l.shape) == tuple(np.shape(p))
                    for l, p in zip(leaves, ref_leaves)
                ):
                    return fn(node)
        except Exception:
            pass
        if hasattr(node, "shape"):  # array leaf not matching params
            return node if default is None else default(node)
        if isinstance(node, (list, tuple)):
            converted = [convert(c) for c in node]
            return type(node)(converted) if not hasattr(node, "_fields") else type(node)(*converted)
        if isinstance(node, dict):
            return {k: convert(v) for k, v in node.items()}
        return node if default is None else default(node)

    return convert(tree)


def opt_state_specs(opt_state: Any, params: Any, rules: ZeroShardingRules) -> Any:
    """Specs for an arbitrary optimizer-state pytree: leaves whose shape
    matches a param get that param's opt spec; scalars are replicated."""
    opt_spec_tree = rules.tree_opt_specs_like(params)
    return map_param_shaped_subtrees(
        opt_state, params, lambda node: opt_spec_tree, default=lambda leaf: P()
    )


def zero_step_comm_model(
    n_params: int,
    fsdp: int,
    stage: int,
    gas: int = 1,
    param_bytes: int = 2,
    grad_bytes: int = 4,
    reduce_scatter: bool = True,
) -> dict:
    """First-order per-train-step collective-byte model for a ZeRO step
    over the ``fsdp`` axis (the reference's perf-critical allgather tail,
    stage2.py:1489; its bucket knobs tune exactly this traffic).

    Ring-traffic convention matches utils/hlo.py: an all-gather of a
    full-size result counts its result bytes once; a reduce-scatter
    counts its (sharded) result bytes once.  Stage 3 gathers the bf16
    params once in forward and once in the (remat) backward per micro
    batch; grads reduce-scatter once per micro batch at stage >= 2,
    all-reduce (2x) at stage <= 1 — or always, when the
    ``zero_optimization.reduce_scatter`` flag forces the dense
    all-reduce fallback.  Validated against compiled-HLO byte counts in
    tests/test_zero_comm.py; the strategy-dependent grad-exchange
    extension lives in comm/strategy.py:step_comm_bytes.
    """
    if fsdp <= 1:
        return {"all-gather": 0, "reduce-scatter": 0, "all-reduce": 0, "total": 0}
    ag = 2 * n_params * param_bytes * gas if stage >= 3 else 0
    rs = n_params // fsdp * grad_bytes * gas if stage >= 2 and reduce_scatter else 0
    ar = 2 * n_params * grad_bytes * gas if (stage < 2 or not reduce_scatter) else 0
    return {"all-gather": ag, "reduce-scatter": rs, "all-reduce": ar, "total": ag + rs + ar}
