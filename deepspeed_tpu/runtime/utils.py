"""Runtime helper utilities.

TPU-native analog of the reference's ``runtime/utils.py`` (SURVEY.md
§2.1): the partitioning helpers (`partition_uniform` reference
runtime/utils.py:352, `partition_balanced` :418) are pure logic and keep
the same contract — they drive pipeline layer placement.  The tensor
helpers (`clip_grad_norm_`, `CheckOverflow`, runtime/utils.py:84-269)
become jnp reductions; memory reporting maps to
``jax.local_devices()[...].memory_stats()`` instead of the torch
allocator.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

try:  # jax optional so pure-logic helpers stay importable anywhere
    import jax
    import jax.numpy as jnp
except Exception:  # pragma: no cover
    jax = None
    jnp = None


# ---------------------------------------------------------------------------
# partitioning (pure logic; drives pipeline layer placement)
# ---------------------------------------------------------------------------

def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries that split ``num_items`` into ``num_parts`` near-equal
    contiguous chunks.  Returns ``num_parts + 1`` boundaries; chunk ``p``
    is ``[parts[p], parts[p+1])``.  (Reference runtime/utils.py:352.)"""
    parts = [0] * (num_parts + 1)
    if num_items <= num_parts:
        for p in range(num_parts + 1):
            parts[p] = min(p, num_items)
        return parts
    chunksize = num_items // num_parts
    residual = num_items % num_parts
    for p in range(1, num_parts + 1):
        parts[p] = min(chunksize * p, num_items)
    # distribute the remainder one item at a time to the earliest chunks
    for p in range(1, residual + 1):
        for q in range(p, num_parts + 1):
            parts[q] += 1
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    """Inclusive prefix sum (reference runtime/utils.py:406)."""
    out = []
    total = 0.0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_balanced(weights: Sequence[float], num_parts: int, eps: float = 1e-3) -> List[int]:
    """Boundaries that split weighted items into ``num_parts`` contiguous
    chunks minimizing the max chunk weight (binary search over the
    bottleneck, reference runtime/utils.py:418).  Same return convention
    as :func:`partition_uniform`."""
    num_items = len(weights)
    if num_items <= num_parts:
        return partition_uniform(num_items, num_parts)

    prefix = prefix_sum_inc(weights)

    def can_pack(limit: float) -> Optional[List[int]]:
        """Greedy: pack as many items per chunk as fit under ``limit``."""
        parts = [0]
        for _ in range(num_parts):
            start = parts[-1]
            if start == num_items:  # all items placed; trailing chunks empty
                parts.append(start)
                continue
            base = prefix[start - 1] if start > 0 else 0.0
            # furthest end with sum(start..end) <= limit
            end = start
            while end < num_items and prefix[end] - base <= limit:
                end += 1
            if end == start:  # single item exceeds limit
                return None
            parts.append(end)
        return parts if parts[-1] == num_items else None

    lo = max(weights)
    hi = prefix[-1]
    while hi - lo > eps * max(1.0, hi):
        mid = (lo + hi) / 2
        if can_pack(mid) is not None:
            hi = mid
        else:
            lo = mid
    parts = can_pack(hi)
    assert parts is not None
    return parts


# ---------------------------------------------------------------------------
# numeric helpers (jnp)
# ---------------------------------------------------------------------------

def global_norm(tree: Any):
    """L2 norm over a pytree (reference get_grad_norm, runtime/utils.py:211)."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(tree)]
    return jnp.sqrt(sum(leaves)) if leaves else jnp.zeros((), jnp.float32)


def clip_grad_norm(tree: Any, max_norm: float):
    """Global-norm gradient clipping; returns (clipped_tree, pre_clip_norm)."""
    norm = global_norm(tree)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * factor).astype(g.dtype), tree), norm


def has_inf_or_nan(x) -> Any:
    """Reference ``CheckOverflow._has_inf_or_nan`` (runtime/utils.py:150)."""
    s = jnp.sum(x.astype(jnp.float32))
    return jnp.logical_not(jnp.isfinite(s))


def count_parameters(tree: Any) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(tree) if hasattr(l, "shape")))


# ---------------------------------------------------------------------------
# memory reporting (reference see_memory_usage, runtime/utils.py:588)
# ---------------------------------------------------------------------------

def device_memory_stats(device=None) -> Dict[str, int]:
    if jax is None:
        return {}
    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    return dict(stats) if stats else {}


def see_memory_usage(message: str, force: bool = False) -> None:
    from deepspeed_tpu.utils.logging import logger

    stats = device_memory_stats()
    if stats:
        used = stats.get("bytes_in_use", 0) / 2**30
        peak = stats.get("peak_bytes_in_use", 0) / 2**30
        logger.info(f"{message} | device mem: {used:.2f}GB (peak {peak:.2f}GB)")
    else:
        try:
            import psutil

            vm = psutil.virtual_memory()
            logger.info(f"{message} | host mem used: {vm.percent}%")
        except Exception:
            logger.info(message)


def call_to_str(base: str, *args, **kwargs) -> str:
    """``name(arg, kw=val)`` pretty printer (reference runtime/utils.py:633)."""
    name = f"{base}("
    if args:
        name += ", ".join(repr(a) for a in args)
        if kwargs:
            name += ", "
    if kwargs:
        name += ", ".join(f"{k}={v!r}" for k, v in kwargs.items())
    name += ")"
    return name


def memory_status(msg: str = "", print_rank: int = 0) -> Dict[str, int]:
    """Reference ``memory_status`` (runtime/utils.py:546) — the pipeline
    engine's per-stage memory print; same device-stats source as
    ``see_memory_usage``."""
    import jax

    from deepspeed_tpu.utils.logging import logger

    stats = device_memory_stats()
    if jax.process_index() == print_rank:
        used = stats.get("bytes_in_use", 0) / 2**30
        peak = stats.get("peak_bytes_in_use", 0) / 2**30
        logger.info(f"memory_status {msg}: in_use={used:.2f}GB peak={peak:.2f}GB")
    return stats
