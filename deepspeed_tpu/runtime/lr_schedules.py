"""Learning-rate schedules.

Re-implements the reference's ``runtime/lr_schedules.py`` schedule zoo —
``LRRangeTest`` (:301), ``OneCycle`` (:408), ``WarmupLR`` (:677),
``WarmupDecayLR`` (:761) — as *pure functions of the step count*
(optax-style schedules), which is the XLA-friendly formulation: the lr
becomes a traced scalar inside the jitted train step instead of mutable
Python state.  A thin stateful wrapper preserves the reference's
``step()/get_lr()/state_dict()`` object API.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp

LR_SCHEDULE_REGISTRY: Dict[str, Callable[..., Callable]] = {}

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]


def _register(name: str):
    def deco(fn):
        LR_SCHEDULE_REGISTRY[name.lower()] = fn
        return fn

    return deco


@_register(LR_RANGE_TEST)
def lr_range_test(
    lr_range_test_min_lr: float = 1e-3,
    lr_range_test_step_size: int = 2000,
    lr_range_test_step_rate: float = 1.0,
    lr_range_test_staircase: bool = False,
    **_ignored,
) -> Callable:
    """LR range ("LR finder") sweep: lr = min_lr * (1 + rate * interval)
    (reference lr_schedules.py:301-406)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


@_register(ONE_CYCLE)
def one_cycle(
    cycle_min_lr: float,
    cycle_max_lr: float,
    decay_lr_rate: float = 0.0,
    cycle_first_step_size: int = 2000,
    cycle_second_step_size: Optional[int] = None,
    cycle_first_stair_count: int = 0,
    cycle_second_stair_count: Optional[int] = None,
    decay_step_size: int = 0,
    cycle_momentum: bool = True,
    cycle_min_mom: float = 0.8,
    cycle_max_mom: float = 0.9,
    decay_mom_rate: float = 0.0,
    **_ignored,
) -> Callable:
    """1cycle policy (reference lr_schedules.py:408-675): linear ramp
    min→max over the first leg, max→min over the second, then post-cycle
    decay of the min lr.  Returns lr; momentum companion via
    ``one_cycle_momentum`` below."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        first = jnp.asarray(cycle_first_step_size, jnp.float32)
        in_first = step < first
        up = cycle_min_lr + (cycle_max_lr - cycle_min_lr) * (step / first)
        down_frac = jnp.clip((step - first) / jnp.asarray(second, jnp.float32), 0.0, 1.0)
        down = cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac
        in_cycle = step < total_cycle
        post = step - total_cycle
        if decay_step_size > 0:
            decay_intervals = jnp.floor(post / decay_step_size)
        else:
            decay_intervals = post
        decayed = cycle_min_lr / (1.0 + decay_lr_rate * jnp.maximum(decay_intervals, 0.0))
        return jnp.where(in_first, up, jnp.where(in_cycle, down, decayed))

    return schedule


def one_cycle_momentum(
    cycle_min_mom: float = 0.8,
    cycle_max_mom: float = 0.9,
    decay_mom_rate: float = 0.0,
    cycle_first_step_size: int = 2000,
    cycle_second_step_size: Optional[int] = None,
    decay_step_size: int = 0,
    **_ignored,
) -> Callable:
    """Momentum leg of 1cycle: moves inversely to lr (max→min→max)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        first = jnp.asarray(cycle_first_step_size, jnp.float32)
        in_first = step < first
        down = cycle_max_mom - (cycle_max_mom - cycle_min_mom) * (step / first)
        up_frac = jnp.clip((step - first) / jnp.asarray(second, jnp.float32), 0.0, 1.0)
        up = cycle_min_mom + (cycle_max_mom - cycle_min_mom) * up_frac
        in_cycle = step < total_cycle
        post = jnp.maximum(step - total_cycle, 0.0)
        if decay_step_size > 0:
            decay_intervals = jnp.floor(post / decay_step_size)
        else:
            decay_intervals = post
        decayed = cycle_max_mom * (1.0 + decay_mom_rate * decay_intervals)
        return jnp.where(in_first, down, jnp.where(in_cycle, up, decayed))

    return schedule


@_register(WARMUP_LR)
def warmup_lr(
    warmup_min_lr: float = 0.0,
    warmup_max_lr: float = 0.001,
    warmup_num_steps: int = 1000,
    warmup_type: str = "log",
    **_ignored,
) -> Callable:
    """Warmup then hold (reference lr_schedules.py:677-759).  The
    reference's default warmup is logarithmic (``log``); ``linear`` also
    supported."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        n = jnp.asarray(max(warmup_num_steps, 1), jnp.float32)
        if warmup_type == "log":
            # log(1+step)/log(1+n) ramp, as in the reference (:736)
            frac = jnp.log1p(jnp.minimum(step, n)) / jnp.log1p(n)
        else:
            frac = jnp.minimum(step, n) / n
        lr = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * frac
        return jnp.where(step >= n, warmup_max_lr, lr)

    return schedule


@_register(WARMUP_DECAY_LR)
def warmup_decay_lr(
    total_num_steps: int,
    warmup_min_lr: float = 0.0,
    warmup_max_lr: float = 0.001,
    warmup_num_steps: int = 1000,
    warmup_type: str = "log",
    **_ignored,
) -> Callable:
    """Warmup then linear decay to zero over ``total_num_steps``
    (reference lr_schedules.py:761-809)."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        n = jnp.asarray(max(warmup_num_steps, 1), jnp.float32)
        total = jnp.asarray(max(total_num_steps, 1), jnp.float32)
        decay = jnp.clip((total - step) / jnp.maximum(total - n, 1.0), 0.0, 1.0)
        return jnp.where(step < n, base(step), warmup_max_lr * decay)

    return schedule


def get_lr_schedule(name: str, params: Dict[str, Any]) -> Callable:
    """Resolve a scheduler config block to a schedule function."""
    key = name.lower()
    if key not in LR_SCHEDULE_REGISTRY:
        raise ValueError(f"Unknown lr schedule '{name}'; valid: {VALID_LR_SCHEDULES}")
    return LR_SCHEDULE_REGISTRY[key](**params)


class LRScheduler:
    """Stateful wrapper preserving the reference object API
    (``step()``, ``get_lr()``, ``state_dict()``/``load_state_dict()``)."""

    def __init__(self, schedule_fn: Callable, last_batch_iteration: int = -1):
        self.schedule_fn = schedule_fn
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self) -> List[float]:
        return [float(self.schedule_fn(max(self.last_batch_iteration, 0)))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]


def add_tuning_arguments(parser):
    """Reference ``add_tuning_arguments`` (lr_schedules.py:54-240): the
    argparse group exposing every schedule knob so recipes can override
    the JSON config from the command line."""
    def str2bool(v: str) -> bool:
        if v.lower() in ("true", "1", "yes", "y"):
            return True
        if v.lower() in ("false", "0", "no", "n"):
            return False
        raise ValueError(f"expected a boolean, got {v!r}")

    # All defaults are None so override_lr_schedule_params only applies
    # flags the user actually passed (argparse defaults must never
    # clobber JSON-configured values).
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None, help="LR schedule for training")
    # LRRangeTest
    group.add_argument("--lr_range_test_min_lr", type=float, default=None)
    group.add_argument("--lr_range_test_step_rate", type=float, default=None)
    group.add_argument("--lr_range_test_step_size", type=int, default=None)
    group.add_argument("--lr_range_test_staircase", type=str2bool, default=None)
    # OneCycle
    group.add_argument("--cycle_first_step_size", type=int, default=None)
    group.add_argument("--cycle_first_stair_count", type=int, default=None)
    group.add_argument("--cycle_second_step_size", type=int, default=None)
    group.add_argument("--cycle_second_stair_count", type=int, default=None)
    group.add_argument("--decay_step_size", type=int, default=None)
    group.add_argument("--cycle_min_lr", type=float, default=None)
    group.add_argument("--cycle_max_lr", type=float, default=None)
    group.add_argument("--decay_lr_rate", type=float, default=None)
    group.add_argument("--cycle_momentum", type=str2bool, default=None)
    group.add_argument("--cycle_min_mom", type=float, default=None)
    group.add_argument("--cycle_max_mom", type=float, default=None)
    group.add_argument("--decay_mom_rate", type=float, default=None)
    # Warmup
    group.add_argument("--warmup_min_lr", type=float, default=None)
    group.add_argument("--warmup_max_lr", type=float, default=None)
    group.add_argument("--warmup_num_steps", type=int, default=None)
    group.add_argument("--warmup_type", type=str, default=None)
    return parser


def parse_arguments():
    import argparse

    parser = argparse.ArgumentParser()
    return add_tuning_arguments(parser).parse_known_args()


def override_lr_schedule_params(args, params: Dict[str, Any]) -> Dict[str, Any]:
    """Fold CLI overrides into a scheduler params dict (reference
    override_*_params helpers)."""
    out = dict(params)
    for key in list(vars(args)):
        val = getattr(args, key)
        if key in (
            "lr_range_test_min_lr", "lr_range_test_step_rate", "lr_range_test_step_size",
            "lr_range_test_staircase", "cycle_first_step_size", "cycle_second_step_size",
            "decay_step_size", "cycle_min_lr", "cycle_max_lr", "decay_lr_rate",
            "cycle_min_mom", "cycle_max_mom", "decay_mom_rate",
            "warmup_min_lr", "warmup_max_lr", "warmup_num_steps", "warmup_type",
        ) and val is not None:
            out[key] = val
    return out
