"""Data loading.

Analog of the reference's ``runtime/dataloader.py``
(``DeepSpeedDataLoader`` :33 with ``DistributedSampler``;
``RepeatingLoader`` :10).  On TPU the "distributed sampler" story changes:
within one process, SPMD sharding of the batch across the (data, fsdp)
mesh axes replaces per-rank samplers; across hosts, each process loads its
``jax.process_index()`` slice and the engine assembles a global array.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np


class RepeatingLoader:
    """Wrap an iterator to auto-restart at StopIteration (reference :10)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)

    def __len__(self):
        return len(self.loader)


class DeepSpeedDataLoader:
    """Batches an indexable dataset of pytrees/arrays.

    ``dataset`` may be: a dict/tuple of equal-length numpy arrays, or a
    sequence of per-example pytrees (collated by stacking).  Yields
    host numpy batches of size ``batch_size`` (the per-process batch =
    micro_batch × local share of the DP world); the engine device_puts
    them with the right sharding.
    """

    def __init__(
        self,
        dataset: Any,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
        collate_fn: Optional[Callable] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
    ):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.process_index = process_index if process_index is not None else jax.process_index()
        self.process_count = process_count if process_count is not None else jax.process_count()
        self.epoch = 0
        # resumable-cursor state (docs/resilience.md): the checkpoint
        # client_state records (epoch, cursor, seed) so a restarted job
        # neither replays nor skips batches.  The cursor counts batches
        # YIELDED in the current iteration; load_state_dict arms a skip
        # for the next __iter__.
        self._cursor = 0
        self._start = 0

        # Columnar = dict (or tuple) of equal-length arrays, one row per
        # example.  A *list* is always treated as a sequence of per-example
        # pytrees — a list of equal-shape arrays is ambiguous, and rows win.
        self._columnar = isinstance(dataset, (dict, tuple)) and all(
            isinstance(x, np.ndarray) for x in jax.tree.leaves(dataset)
        )
        if self._columnar:
            lengths = {len(x) for x in jax.tree.leaves(dataset)}
            if len(lengths) != 1:
                raise ValueError(f"columnar dataset has unequal column lengths: {sorted(lengths)}")
            self._n = lengths.pop()
        else:
            self._n = len(dataset)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def state_dict(self) -> dict:
        """Resume cursor: epoch + batches yielded this iteration + the
        shuffle seed (the epoch-derived RNG key is ``seed + epoch``, so
        (seed, epoch) IS the shuffle RNG state)."""
        return {"epoch": int(self.epoch), "cursor": int(self._cursor), "seed": int(self.seed)}

    def load_state_dict(self, sd: dict) -> None:
        """Restore a cursor saved by :meth:`state_dict`: the next
        ``__iter__`` recreates the same permutation and skips the
        already-consumed batches — no replays, no skips."""
        self.epoch = int(sd.get("epoch", 0))
        self.seed = int(sd.get("seed", self.seed))
        self._start = int(sd.get("cursor", 0))
        self._cursor = self._start

    def __len__(self) -> int:
        per_proc = self._n // self.process_count
        if self.drop_last:
            return per_proc // self.batch_size
        return math.ceil(per_proc / self.batch_size)

    def __iter__(self) -> Iterator[Any]:
        # eager prologue (the cursor reset must happen at iter() time,
        # not first next(): wrappers read the cursor between the two)
        start, self._start = self._start, 0
        self._cursor = start
        idx = np.arange(self._n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(idx)
        # contiguous per-process shard (DistributedSampler semantics)
        per_proc = self._n // self.process_count
        idx = idx[self.process_index * per_proc : (self.process_index + 1) * per_proc]
        return self._generate(idx, start)

    def _generate(self, idx: np.ndarray, start: int) -> Iterator[Any]:
        n_batches = len(self)
        for b in range(start, n_batches):
            sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
            if len(sel) == 0:
                return
            if self._columnar:
                batch = jax.tree.map(lambda col: col[sel], self.dataset)
            else:
                examples = [self.dataset[int(i)] for i in sel]
                if self.collate_fn is not None:
                    batch = self.collate_fn(examples)
                else:
                    batch = jax.tree.map(lambda *xs: np.stack(xs), *examples)
            # cursor advances at hand-off: a checkpoint taken after this
            # batch's step must resume at b + 1
            self._cursor = b + 1
            yield batch


class ResumableWrapperMixin:
    """Consumed-cursor bookkeeping shared by loader wrappers that pull
    AHEAD of training (``DevicePrefetchLoader`` here, the overlap
    ``DevicePrefetcher``): the checkpointable cursor is the inner
    loader's cursor at iteration start plus the batches actually handed
    to training — never the inner loader's own cursor, which runs ahead
    by up to the prefetch depth.  Wrappers call :meth:`_capture_base`
    right after ``iter(self.loader)`` (the inner loader's eager
    prologue has applied any resume skip by then) and bump ``_served``
    at each yield."""

    _served = 0
    _base_state: Optional[dict] = None

    def _capture_base(self) -> None:
        fn = getattr(self.loader, "state_dict", None)
        self._base_state = dict(fn()) if fn is not None else None
        self._served = 0

    def state_dict(self) -> Optional[dict]:
        """None when the wrapped loader has no state protocol (the
        checkpoint then simply carries no resume cursor)."""
        if self._base_state is not None:
            base = dict(self._base_state)
            base["cursor"] = int(base.get("cursor", 0)) + self._served
            return base
        fn = getattr(self.loader, "state_dict", None)
        return dict(fn()) if fn is not None else None

    def load_state_dict(self, sd: dict) -> None:
        fn = getattr(self.loader, "load_state_dict", None)
        if fn is None:
            return
        fn(sd)
        self._served = 0
        self._base_state = None


class DevicePrefetchLoader(ResumableWrapperMixin):
    """Wraps any batch iterator with ahead-of-time ``jax.device_put``.

    NOTE: ``engine.prefetch_loader`` now routes through the two-stage
    pipelined ``runtime.overlap.DevicePrefetcher`` (load and place
    overlap each other AND the step); this single-worker wrapper stays
    for direct users of the plain ``device_put`` path.

    The engine's compiled step dispatches asynchronously; what serializes
    a remote/tunneled TPU is the per-step host→device input transfer.
    Keeping ``prefetch_depth`` batches in flight overlaps the next
    transfers with the current step — the JAX-native equivalent of the
    reference dataloader's pinned-memory + non-blocking H2D copies.

    ``sharding``: optional pytree/str of shardings passed to
    ``device_put`` (defaults to the engine's batch placement when driven
    through ``engine.train_batch``, which treats already-device-resident
    arrays as a no-op).
    """

    def __init__(self, loader: Iterable, prefetch_depth: int = 2, sharding=None, transform=None):
        self.loader = loader
        self.prefetch_depth = max(1, int(prefetch_depth))
        self.sharding = sharding
        # optional host-side transform + placement combo (e.g. the
        # engine's stack-micro-batches + shard put); overrides the
        # default device_put when given
        self.transform = transform

    def __iter__(self):
        it = iter(self.loader)
        self._capture_base()
        return self._pipeline(it)

    def _pipeline(self, it):
        import collections
        from concurrent.futures import ThreadPoolExecutor

        import jax

        def put(batch):
            if self.transform is not None:
                return self.transform(batch)
            if self.sharding is not None:
                return jax.device_put(batch, self.sharding)
            return jax.device_put(batch)

        # device_put is a synchronous host call on remote/tunneled
        # backends — run it in a worker thread so transfers overlap the
        # compiled step instead of serializing with it
        queue = collections.deque()
        with ThreadPoolExecutor(max_workers=1) as pool:
            try:
                for _ in range(self.prefetch_depth):
                    queue.append(pool.submit(put, next(it)))
            except StopIteration:
                pass
            while queue:
                out = queue.popleft()
                try:
                    queue.append(pool.submit(put, next(it)))
                except StopIteration:
                    pass
                result = out.result()
                self._served += 1
                yield result

    def __len__(self):
        try:
            return len(self.loader)
        except TypeError:
            raise TypeError("wrapped loader is a generator with no len()") from None
