"""Pipeline schedules as pure instruction streams.

Behavioral re-implementation of the reference's
``runtime/pipe/schedule.py`` (PipeSchedule :6, TrainSchedule :182 — the
1F1B interleave, InferenceSchedule :129, DataParallelSchedule :292, and
the instruction dataclasses :336-476).

On TPU the hot path does **not** interpret these instructions rank by
rank — the whole pipeline step is one compiled XLA program
(``runtime/pipe/engine.py``) and XLA's scheduler overlaps the
``collective_permute`` transfers with compute.  The schedules are kept
as pure logic because (a) they document and pin the execution semantics
the compiled program must be equivalent to, (b) they are used to compute
buffer counts / bubble estimates, and (c) the reference's
schedule-sequence tests carry over verbatim (SURVEY.md §4).
"""
from __future__ import annotations

from abc import ABC, abstractmethod

from deepspeed_tpu.runtime.utils import call_to_str


def _is_even(x: int) -> bool:
    return x % 2 == 0


def _is_odd(x: int) -> bool:
    return x % 2 != 0


class PipeInstruction:
    """Atomic action a pipeline stage executes in one schedule step.

    Keyword args are stored as attributes (reference schedule.py:336)."""

    def __init__(self, **kwargs):
        self.name = self.__class__.__name__
        self.kwargs = kwargs
        for key, val in kwargs.items():
            setattr(self, key, val)

    def __repr__(self):
        return call_to_str(self.name, **self.kwargs)

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((self.name, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    """Optimizer update + zero grads (after Reduce*Grads)."""


class ReduceGrads(PipeInstruction):
    """Data-parallel gradient reduction within the stage."""


class ReduceTiedGrads(PipeInstruction):
    """All-reduce grads of tied modules across the stages that own them."""


class BufferOpInstruction(PipeInstruction):
    """Instruction operating on one of the stage's pipeline buffers."""

    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    """Load a micro-batch into ``buffer_id`` (first/last stages)."""


class ForwardPass(BufferOpInstruction):
    """Compute a forward pass on the activation in ``buffer_id``."""


class BackwardPass(BufferOpInstruction):
    """Compute a backward pass for the activation in ``buffer_id``."""


class SendActivation(BufferOpInstruction):
    """Send activations in ``buffer_id`` to the next stage."""


class RecvActivation(BufferOpInstruction):
    """Receive activations from the previous stage into ``buffer_id``."""


class SendGrad(BufferOpInstruction):
    """Send input-activation grads in ``buffer_id`` to the previous stage."""


class RecvGrad(BufferOpInstruction):
    """Receive output-activation grads into ``buffer_id``."""


class PipeSchedule(ABC):
    """Generates, per schedule step, the instruction list one stage runs.

    Steps are atomic: a barrier may be placed between successive yielded
    lists without deadlock (reference schedule.py:6-42)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @abstractmethod
    def steps(self):
        """Yield a list of :class:`PipeInstruction` per step."""

    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    def _valid_micro_batch(self, micro_batch_id: int) -> bool:
        return 0 <= micro_batch_id < self.micro_batches

    def _valid_stage(self, stage_id: int) -> bool:
        return 0 <= stage_id < self.stages

    @property
    def stage(self) -> int:
        return self.stage_id

    @property
    def num_stages(self) -> int:
        return self.stages

    @property
    def num_micro_batches(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    @property
    def is_last_stage(self) -> bool:
        return self.stage_id == self.stages - 1

    def _buffer_idx(self, micro_batch_id: int) -> int:
        assert self._valid_micro_batch(micro_batch_id)
        return micro_batch_id % self.num_pipe_buffers()

    def __iter__(self):
        return iter(self.steps())

    def bubble_fraction(self) -> float:
        """Idle fraction of the schedule — (S-1)/(M+S-1) for 1F1B/GPipe."""
        m, s = self.micro_batches, self.stages
        return (s - 1) / (m + s - 1) if m + s > 1 else 0.0


class InferenceSchedule(PipeSchedule):
    """Forward-only pipelining with two alternating buffers
    (reference schedule.py:129-180)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            cmds = []
            micro_batch_id = step_id - self.stage_id

            if _is_even(self.stage_id):
                recv_buf = step_id % 2
                send_buf = (step_id + 1) % 2
            else:
                recv_buf = (step_id + 1) % 2
                send_buf = step_id % 2

            if (self.is_first_stage or self.is_last_stage) and self._valid_micro_batch(micro_batch_id):
                cmds.append(LoadMicroBatch(recv_buf))

            # Even stages send first, odd stages receive first: pairs up
            # sends/recvs without deadlock under synchronous transports.
            if _is_even(self.stage_id):
                if self._valid_stage(self.next_stage) and self._valid_micro_batch(micro_batch_id - 1):
                    cmds.append(SendActivation(send_buf))
                if self._valid_stage(self.prev_stage) and self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(recv_buf))
            else:
                if self._valid_stage(self.prev_stage) and self._valid_micro_batch(micro_batch_id):
                    cmds.append(RecvActivation(recv_buf))
                if self._valid_stage(self.next_stage) and self._valid_micro_batch(micro_batch_id - 1):
                    cmds.append(SendActivation(send_buf))

            if self._valid_micro_batch(micro_batch_id):
                cmds.append(ForwardPass(recv_buf))

            yield cmds

    def num_pipe_buffers(self) -> int:
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B interleave (reference schedule.py:182-290): each stage
    alternates forward and backward steps, with earlier stages running
    more warm-up forwards; steady state holds ≤ ``num_pipe_buffers``
    in-flight micro-batches."""

    def steps(self):
        prev_micro_batch_id = -1
        total_steps = 2 * (self.micro_batches + self.stages - 1)
        for step_id in range(total_steps):
            micro_batch_id, is_forward = self._step_to_micro_batch(step_id)

            cmds = []
            curr_buffer = prev_buffer = None
            if self._valid_micro_batch(prev_micro_batch_id):
                prev_buffer = self._buffer_idx(prev_micro_batch_id)
            if self._valid_micro_batch(micro_batch_id):
                curr_buffer = self._buffer_idx(micro_batch_id)

            # Activation / gradient exchange.  On forward steps a stage
            # receives the activation it is about to consume and returns
            # the grad it produced on the previous (backward) step; on
            # backward steps it ships the previous forward's activation
            # downstream and receives the grad it is about to consume.
            if is_forward:
                if curr_buffer is not None and self._valid_stage(self.prev_stage):
                    cmds.append(RecvActivation(curr_buffer))
                if prev_buffer is not None and self._valid_stage(self.prev_stage):
                    cmds.append(SendGrad(prev_buffer))
            else:
                if prev_buffer is not None and self._valid_stage(self.next_stage):
                    cmds.append(SendActivation(prev_buffer))
                if curr_buffer is not None and self._valid_stage(self.next_stage):
                    cmds.append(RecvGrad(curr_buffer))

            if (self.is_first_stage or self.is_last_stage) and is_forward and curr_buffer is not None:
                cmds.append(LoadMicroBatch(curr_buffer))

            if curr_buffer is not None:
                cmds.append(ForwardPass(curr_buffer) if is_forward else BackwardPass(curr_buffer))

            if step_id == total_steps - 1:
                cmds.append(ReduceTiedGrads())
                cmds.append(ReduceGrads())
                cmds.append(OptimizerStep())

            prev_micro_batch_id = micro_batch_id
            yield cmds

    def num_pipe_buffers(self) -> int:
        buffers = min(self.stages - self.stage_id + 1, self.micro_batches)
        return max(2, buffers)

    def _step_to_micro_batch(self, step_id: int):
        """Map a schedule step to (micro_batch_id, is_forward).  Even
        steps are forwards on even stages / backwards on odd stages and
        vice versa — the parity trick that staggers neighbors so their
        sends/recvs pair up (reference schedule.py:249-290)."""
        if _is_even(step_id) and _is_even(self.stage_id):
            return self._even_step_forward_id(step_id), True
        if _is_odd(step_id) and _is_odd(self.stage_id):
            return self._odd_step_forward_id(step_id), True
        if _is_even(step_id) and _is_odd(self.stage_id):
            return self._even_step_backward_id(step_id), False
        if _is_odd(step_id) and _is_even(self.stage_id):
            return self._odd_step_backward_id(step_id), False
        raise AssertionError("unreachable")

    def _even_step_forward_id(self, step_id: int) -> int:
        return step_id // 2 - self.stage_id // 2

    def _odd_step_forward_id(self, step_id: int) -> int:
        return (step_id - 1) // 2 - self.stage_id // 2

    def _even_step_backward_id(self, step_id: int) -> int:
        return step_id // 2 - self.stages + (self.stage_id + 1) // 2

    def _odd_step_backward_id(self, step_id: int) -> int:
        return (step_id - 1) // 2 - self.stages + 1 + self.stage_id // 2


class DataParallelSchedule(PipeSchedule):
    """Plain gradient-accumulation data parallelism expressed as a
    pipeline schedule (reference schedule.py:292-320)."""

    def steps(self):
        for step_id in range(self.micro_batches):
            cmds = [
                LoadMicroBatch(buffer_id=0),
                ForwardPass(buffer_id=0),
                BackwardPass(buffer_id=0),
            ]
            if step_id == self.micro_batches - 1:
                cmds.extend([ReduceGrads(), OptimizerStep()])
            yield cmds

    def num_pipe_buffers(self) -> int:
        return 1
