"""Pipeline parallelism (reference ``deepspeed/runtime/pipe/``)."""
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
from deepspeed_tpu.runtime.pipe import schedule

__all__ = ["PipelineModule", "LayerSpec", "TiedLayerSpec", "schedule"]
