"""Pipeline module: the model expressed as a list of layers.

Behavioral analog of the reference's ``runtime/pipe/module.py``
(``PipelineModule`` :87, ``LayerSpec`` :25, ``TiedLayerSpec`` :73,
``_partition_layers`` :355).  Differences forced (and enabled) by the
TPU/XLA execution model:

* A "layer" is functional: an object with ``init(rng) -> params`` and
  ``apply(params, x, rng=None) -> x`` (or ``__call__``), or a plain
  stateless callable ``f(x)``.  No module mutation, no hooks.
* The repeated transformer blocks (the *body*) must be homogeneous —
  identical param structure — so they can be **stacked** into leaves of
  shape ``[L, ...]`` sharded ``P('pipe')`` over the mesh and executed as
  a compiled ``scan``/``ppermute`` pipeline (engine.py here).  This is
  what lets XLA overlap stage compute with inter-stage transfers instead
  of interpreting send/recv instructions rank-by-rank.
* Leading layers before the body (embedding, reshapes) and trailing
  layers after it (final norm, LM head) are executed replicated over the
  ``pipe`` axis, sharded over ``data``/``model`` axes as usual.  Weight
  tying (``TiedLayerSpec``, e.g. embedding ⇄ LM head) therefore needs
  **no** tied-weight grad all-reduce (reference pipe/module.py:412-425):
  tied layers simply share one params entry.
* Every process builds the full (sharded) model — under GSPMD there is
  no per-rank construction; ``zero.Init``-style scoped construction is
  unnecessary because params are sharded from birth by ``jax.jit``
  output shardings.
"""
from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.utils import partition_balanced, partition_uniform
from deepspeed_tpu.utils.logging import logger


class LayerSpec:
    """Lazy layer description: ``typename(*args, **kwargs)`` built at
    engine-init time (reference pipe/module.py:25-70)."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs
        if not callable(typename):
            raise RuntimeError("LayerSpec requires a callable typename")

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """A layer whose parameters are shared with every other tied layer of
    the same ``key`` (reference pipe/module.py:73-85).  ``forward_fn``
    optionally overrides how the shared params are applied at this site
    (e.g. embedding weights reused as the LM head)."""

    def __init__(self, key: str, typename: Callable, *module_args, forward_fn=None, **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


class _BuiltLayer:
    """Normalized (init, apply) pair for one layer position."""

    def __init__(self, obj: Any, tied_key: Optional[str] = None, forward_fn=None, name: str = ""):
        self.obj = obj
        self.tied_key = tied_key
        self.forward_fn = forward_fn
        self.name = name or type(obj).__name__
        self.has_params = hasattr(obj, "init")
        if forward_fn is not None:
            self._fn = forward_fn
        elif self.has_params:
            self._fn = getattr(obj, "apply", None) or obj
        else:
            self._fn = obj
        self._accepts_rng = _accepts_rng(self._fn)

    def init(self, rng) -> Any:
        return self.obj.init(rng) if self.has_params else None

    def apply(self, params: Any, x: Any, rng=None) -> Any:
        if not self.has_params and self.forward_fn is None:
            return self._fn(x)
        if self._accepts_rng:
            return self._fn(params, x, rng=rng)
        return self._fn(params, x)


def _accepts_rng(fn) -> bool:
    """Determined once at build time (never by catching TypeError from
    inside the layer body, which would mask real layer bugs)."""
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return True
    params = sig.parameters
    if "rng" in params:
        return True
    return any(p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values())


class PipelineModule:
    """The model-as-layer-list for pipeline-parallel execution.

    Args:
        layers: sequence of :class:`LayerSpec` / layer objects / callables.
        loss_fn: ``loss_fn(outputs, labels) -> scalar``.
        num_stages: pipeline stages; defaults to the mesh's ``pipe`` axis
            size when the engine adopts the module.
        partition_method: 'uniform' | 'parameters' | 'type:<regex>' —
            stage-boundary balancing (reference ``_partition_layers``,
            pipe/module.py:355).  On TPU stage boundaries additionally
            require the homogeneous body to split evenly, so the
            partition is advisory: it is computed, logged, and used for
            checkpoint layer naming.
        activation_checkpoint_interval: remat every N layers (0 = off).
        seed_layers: give each layer a distinct init RNG stream.
    """

    def __init__(
        self,
        layers: Sequence[Any],
        num_stages: Optional[int] = None,
        topology=None,
        loss_fn: Optional[Callable] = None,
        seed_layers: bool = False,
        partition_method: str = "parameters",
        activation_checkpoint_interval: int = 0,
        base_seed: int = 1234,
    ):
        self.specs = list(layers)
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.seed_layers = seed_layers
        self.base_seed = base_seed
        self._topology = topology
        if topology is not None and num_stages is None:
            num_stages = topology.get_dim("pipe")
        self.num_stages = num_stages
        self.parts: Optional[List[int]] = None

        self._layers: List[_BuiltLayer] = [self._build_one(i, s) for i, s in enumerate(self.specs)]
        self._classify_body()

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def _build_one(self, idx: int, spec: Any) -> _BuiltLayer:
        if isinstance(spec, TiedLayerSpec):
            layer = _BuiltLayer(spec.build(), tied_key=spec.key, forward_fn=spec.forward_fn,
                                name=f"{idx}:{spec.typename.__name__}")
        elif isinstance(spec, LayerSpec):
            layer = _BuiltLayer(spec.build(), name=f"{idx}:{spec.typename.__name__}")
        else:
            layer = _BuiltLayer(spec, name=f"{idx}:{type(spec).__name__}")
        # Homogeneity key: stacked body layers must share BEHAVIOR, not
        # just param structure — same class built with the same args.
        if isinstance(spec, LayerSpec):
            layer.homo_key = (spec.typename, repr(spec.module_args), repr(sorted(spec.module_kwargs.items())))
        else:
            layer.homo_key = (type(spec), repr(sorted(getattr(spec, "__dict__", {}).items())))
        return layer

    def _classify_body(self) -> None:
        """Find the maximal run of homogeneous parametered layers — the
        pipelined body.  Everything before runs replicated pre-pipeline,
        everything after post-pipeline."""
        runs: List[Tuple[int, int]] = []  # (start, length)
        i = 0
        n = len(self._layers)
        while i < n:
            l = self._layers[i]
            if not l.has_params or l.tied_key is not None:
                i += 1
                continue
            j = i
            key = l.homo_key
            while (
                j < n
                and self._layers[j].has_params
                and self._layers[j].tied_key is None
                and self._layers[j].homo_key == key
            ):
                j += 1
            runs.append((i, j - i))
            i = j
        if runs:
            start, length = max(runs, key=lambda r: r[1])
        else:
            start, length = len(self._layers), 0
        self.body_start = start
        self.body_len = length
        self.pre_ids = list(range(0, start))
        self.body_ids = list(range(start, start + length))
        self.post_ids = list(range(start + length, n))

    def build_params(self, rng) -> Dict[str, Any]:
        """Initialize the full param tree::

            {"pre": {idx: p}, "blocks": stacked [L, ...] leaves,
             "post": {idx: p}, "tied": {key: p}}
        """
        params: Dict[str, Any] = {"pre": {}, "blocks": None, "post": {}, "tied": {}}

        def layer_rng(i):
            return jax.random.fold_in(rng, i if self.seed_layers else 0)

        for section, ids in (("pre", self.pre_ids), ("post", self.post_ids)):
            for i in ids:
                layer = self._layers[i]
                if layer.tied_key is not None:
                    if layer.tied_key not in params["tied"]:
                        params["tied"][layer.tied_key] = layer.init(layer_rng(i))
                elif layer.has_params:
                    params[section][str(i)] = layer.init(layer_rng(i))

        if self.body_ids:
            per_layer = [self._layers[i].init(layer_rng(i)) for i in self.body_ids]
            treedef = jax.tree.structure(per_layer[0])
            for p in per_layer[1:]:
                if jax.tree.structure(p) != treedef:
                    raise ValueError("pipeline body layers must have identical param structure")
            params["blocks"] = jax.tree.map(lambda *ls: jnp.stack(ls), *per_layer)
        return params

    # ------------------------------------------------------------------
    # application
    # ------------------------------------------------------------------
    def _apply_section(self, params: Dict[str, Any], ids: List[int], section: str, x, rng):
        for i in ids:
            layer = self._layers[i]
            if layer.tied_key is not None:
                p = params["tied"][layer.tied_key]
            elif layer.has_params:
                p = params[section][str(i)]
            else:
                p = None
            x = layer.apply(p, x, rng=None if rng is None else jax.random.fold_in(rng, i))
        return x

    def apply_pre(self, params, x, rng=None):
        return self._apply_section(params, self.pre_ids, "pre", x, rng)

    def apply_post(self, params, x, rng=None):
        return self._apply_section(params, self.post_ids, "post", x, rng)

    def apply_block(self, block_params, x, rng=None):
        """Apply ONE body block given its (unstacked) params."""
        return self._layers[self.body_ids[0]].apply(block_params, x, rng=rng)

    def apply_body(self, params, x, rng=None, remat: bool = False):
        """All body blocks sequentially via scan over the stacked leaves,
        with remat at ``activation_checkpoint_interval`` granularity."""
        if not self.body_ids:
            return x
        interval = self.activation_checkpoint_interval
        if remat and interval <= 0:
            interval = 1
        if interval > 0:
            from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
                checkpoint_sequential,
            )

            return checkpoint_sequential(self.apply_block, params["blocks"], x, rng=rng, every=interval)

        def body(carry, bp):
            h, r = carry
            r2 = None if r is None else jax.random.fold_in(r, 1)
            h = self.apply_block(bp, h, r)
            return (h, r2), None

        (x, _), _ = jax.lax.scan(body, (x, rng), params["blocks"])
        return x

    def sequential(self, params, x, rng=None, remat: bool = False):
        """Full forward without pipelining (pipe axis = 1, eval, tests)."""
        x = self.apply_pre(params, x, rng)
        x = self.apply_body(params, x, rng, remat=remat)
        return self.apply_post(params, x, rng)

    # ------------------------------------------------------------------
    # partitioning bookkeeping (advisory on TPU; reference :355-410)
    # ------------------------------------------------------------------
    def configure_stages(self, num_stages: int) -> None:
        self.num_stages = num_stages
        if num_stages > 1:
            if not self.body_ids:
                raise ValueError(
                    "pipe parallelism needs a homogeneous run of layers to pipeline; "
                    "none found in this layer list"
                )
            if self.body_len % num_stages != 0:
                raise ValueError(
                    f"pipeline body of {self.body_len} layers does not divide "
                    f"evenly over {num_stages} stages"
                )
        self.parts = self._partition_layers(num_stages)
        for s in range(num_stages):
            logger.info(f"pipe stage {s}: layers [{self.parts[s]}, {self.parts[s + 1]})")

    def _partition_layers(self, num_stages: int) -> List[int]:
        method = (self.partition_method or "uniform").lower()
        n = len(self._layers)
        if method == "uniform":
            return partition_uniform(n, num_stages)
        if method == "parameters":
            weights = [self._layer_param_count(i) for i in range(n)]
            return partition_balanced(weights, num_stages)
        if method.startswith("type:"):
            pat = method.split(":", 1)[1]
            weights = [1 if re.search(pat, self._layers[i].name, re.IGNORECASE) else 0 for i in range(n)]
            return partition_balanced(weights, num_stages)
        raise NotImplementedError(f"partition_method '{method}'")

    def _layer_param_count(self, i: int) -> int:
        layer = self._layers[i]
        if not layer.has_params:
            return 0
        shapes = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
        import numpy as np

        return int(sum(np.prod(l.shape) for l in jax.tree.leaves(shapes)))

    def stage_of_layer(self, layer_idx: int) -> int:
        assert self.parts is not None, "configure_stages() first"
        for s in range(len(self.parts) - 1):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise ValueError(layer_idx)

    @property
    def num_layers(self) -> int:
        return len(self._layers)

    def topology(self):
        return self._topology

    def ckpt_layer_path(self, ckpt_dir: str, local_layer_idx: int) -> str:
        import os

        return os.path.join(ckpt_dir, f"layer_{local_layer_idx:02d}-model_states.msgpack")
