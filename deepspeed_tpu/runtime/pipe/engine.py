"""Pipeline-parallel engine.

Behavioral analog of the reference's ``runtime/pipe/engine.py``
(``PipelineEngine`` :46, ``train_batch`` :250, instruction executors
:540-1005, schedule interpreter ``_exec_schedule`` :1209) — redesigned
for XLA:

The reference interprets a 1F1B instruction stream per rank, moving
activations with broadcast-based p2p (pipe/p2p.py:31) and a dynamic
shape handshake (:718).  Here the **whole train batch is one compiled
program**: the homogeneous transformer body is stacked ``[L, ...]`` and
sharded ``P('pipe')``; a ``shard_map`` over the ``pipe`` axis runs
``M + S - 1`` ticks of a ``lax.scan``, each tick computing one stage
forward and rotating activations to the next stage with
``lax.ppermute`` (= XLA ``collective_permute`` riding ICI).  Reverse
pipelining falls out of autodiff: the transpose of the tick scan is the
reversed scan with reversed ppermutes, so backward runs pipelined too.
Shape handshakes disappear (static shapes), and XLA overlaps the
permute transfers with stage compute — the role of the reference's
even/odd send/recv interleave (schedule.py:249).

Two schedules (``pipeline.schedule`` config key):

* ``"1f1b"`` (default) — true one-forward-one-backward: a single scan
  of ``M + 2(S-1)`` ticks where each tick runs one forward slot and one
  backward slot per stage, with explicit per-micro-batch ``jax.vjp``
  recompute in the backward slot.  Slots execute unconditionally with
  MASKED data (``lax.cond`` would let GSPMD place auto-axis resharding
  collectives inside stage-divergent branches and deadlock); backward
  masking is exact because VJPs are linear in the cotangent.  Saved
  stage inputs live in a ring buffer of ``2S-1`` slots, so activation
  memory is **O(S), independent of M** — the property the reference's
  ``TrainSchedule`` (schedule.py:182, engine.py:540-1005) exists to
  provide.  The loss head runs inside the last stage's tick so backward
  of micro-batch m starts as soon as its forward completes.
* ``"gpipe"`` — all-forward-then-all-backward via autodiff of the tick
  scan: lower bubble in this compiled formulation (the transposed scan
  reuses the forward's tick count) but activation live-set grows with M.

Like the reference (pipe/engine.py:56), ZeRO stages >= 2 are rejected;
stage 0/1 compose (optimizer state sharded over ``fsdp``).

Tied layers (embedding ⇄ head) live outside the pipelined body and are
replicated over ``pipe``, so the reference's tied-grad all-reduce
(``_exec_reduce_tied_grads`` :215) is unnecessary: XLA's partitioner
emits the psum for the shared (auto-sharded) parameter automatically.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.analysis.shard import hooks as shard_hooks
from deepspeed_tpu.comm import collectives
from deepspeed_tpu.config.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.sharding.layout import DEFAULT_LAYOUT, batch_pspec
from deepspeed_tpu.sharding.rules import PartitionRules
from deepspeed_tpu.utils.logging import log_dist

# stacked-body leaf spec: [L, ...] over the pipe axis (sharding/layout.py)
_PIPE_STACKED = DEFAULT_LAYOUT.stacked(None)


class PipelineEngine(DeepSpeedEngine):
    """Training engine for :class:`PipelineModule` models."""

    def __init__(
        self,
        module: PipelineModule,
        config: DeepSpeedConfig,
        mesh=None,
        params: Any = None,
        tp_spec_fn=None,
        partition_rules=None,
        **kw,
    ):
        from deepspeed_tpu.comm.mesh import make_mesh

        if config.zero_config.stage > 1:
            # reference pipe/engine.py:56 — same constraint, same reason:
            # grad/param partitioning across DP conflicts with PP grad
            # accumulation semantics.
            raise AssertionError("ZeRO stages > 1 are incompatible with pipeline parallelism")

        mesh = mesh if mesh is not None else make_mesh(config.mesh)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.num_stages = sizes.get("pipe", 1)
        self.pipe_module = module
        module.configure_stages(self.num_stages)

        if params is None:
            params = module.build_params(jax.random.PRNGKey(config.seed))
        self._micro_batches = config.gradient_accumulation_steps
        # grads go straight into _apply_update; no accumulator buffer
        # (saves a full fp32 params-sized tree vs the base engine)
        self._use_grad_acc = False

        # partition-rule engine: the client's table (PartitionRules,
        # family name, rule table, or legacy tp_spec_fn) gains the
        # stacked-body view — leaves under ``blocks`` get the pipe axis
        # on their leading stacked dim, per-block specs shift right
        base_rules = PartitionRules.coerce(partition_rules, tp_spec_fn)

        super().__init__(
            model=self._pipelined_loss,
            params=params,
            config=config,
            mesh=mesh,
            partition_rules=base_rules.stacked(prefix="blocks"),
            **kw,
        )

        self._schedule = config.pipeline.schedule
        M, S = self._micro_batches, self.num_stages
        # compiled-formulation bubble: GPipe pays (S-1) idle ticks each
        # way but its transpose reuses the forward tick count; the
        # masked 1F1B loop runs M+2(S-1) uniform ticks for M of work
        bubble = (2 * (S - 1) / (M + 2 * (S - 1))) if self._schedule == "1f1b" else (
            (S - 1) / (M + S - 1)
        )
        log_dist(
            f"pipeline engine: stages={S} micro_batches={M} "
            f"body_layers={module.body_len} schedule={self._schedule} "
            f"bubble={bubble:.1%}"
        )

    # ------------------------------------------------------------------
    # the compiled pipeline (body leaves are sharded _PIPE_STACKED on
    # their stacked dim by the partition-rule engine's stacked() view)
    # ------------------------------------------------------------------
    def _split_batch(self, batch: Any) -> Tuple[Any, Any]:
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            return batch[0], batch[1]
        if isinstance(batch, dict):
            labels = batch.get("labels", batch.get("label"))
            if labels is None:
                raise TypeError("pipeline batch dict must contain a 'labels' entry")
            inputs = {k: v for k, v in batch.items() if k not in ("labels", "label")}
            if len(inputs) == 1:
                inputs = next(iter(inputs.values()))
            return inputs, labels
        raise TypeError("pipeline batch must be (inputs, labels) or a dict with 'labels'")

    def _pipelined_loss(self, params: Dict[str, Any], batch: Any, rng) -> jnp.ndarray:
        """Full-batch loss: pre (replicated) → pipelined body → post."""
        module = self.pipe_module
        inputs, labels = self._split_batch(batch)
        x = module.apply_pre(params, inputs, rng)

        if self.num_stages > 1 and module.body_ids:
            M = self._micro_batches
            B = x.shape[0]
            assert B % M == 0, f"batch {B} not divisible by {M} micro-batches"
            mb = B // M
            x_mb = x.reshape((M, mb) + x.shape[1:])
            x_mb = jax.lax.with_sharding_constraint(
                x_mb, self._sh(DEFAULT_LAYOUT.micro_batch_stack(x_mb.ndim))
            )
            y_mb = self._pipeline_body(params["blocks"], x_mb, rng)
            x = y_mb.reshape((B,) + y_mb.shape[2:])
        else:
            x = module.apply_body(params, x, rng, remat=True)

        out = module.apply_post(params, x, rng)
        loss = module.loss_fn(out, labels) if module.loss_fn is not None else out
        loss = jnp.asarray(loss)
        return jnp.mean(loss) if loss.ndim else loss

    def _stage_pass_fn(self) -> Callable:
        """One stage's forward over its local K stacked blocks — shared
        by the GPipe body and the 1F1B slots (the per-layer rng fold and
        remat wrapping must stay identical between the two schedules)."""
        module = self.pipe_module
        apply_blk = module.apply_block
        if module.activation_checkpoint_interval > 0:
            # per-microbatch-per-stage remat: the GPipe memory recipe
            # (reference keeps only boundary activations, engine.py:605)
            apply_blk = jax.checkpoint(apply_blk)

        def stage_pass(bp_local, h, r, layer0):
            # rng per (global layer, micro-batch): r is already folded
            # with the micro-batch id; fold the global layer index here
            def body(carry, p):
                hh, k = carry
                rk = None if r is None else jax.random.fold_in(r, k)
                return (apply_blk(p, hh, rng=rk), k + 1), None

            (h, _), _ = jax.lax.scan(body, (h, layer0), bp_local)
            return h

        return stage_pass

    def _pipeline_body(self, block_params: Any, x_mb: jnp.ndarray, rng) -> jnp.ndarray:
        """GPipe over the stacked body under shard_map('pipe').

        ``block_params`` leaves: [L, ...] sharded P('pipe') → local [K, ...].
        ``x_mb``: [M, mb, ...] replicated over pipe (sharded over data on
        the mb dim by the automatic axes).
        """
        module = self.pipe_module
        S = self.num_stages
        M = self._micro_batches
        stage_pass = self._stage_pass_fn()

        def pipelined(bp_local, x_local, r):
            stage = jax.lax.axis_index("pipe")
            K = module.body_len // S
            T = M + S - 1
            recv0 = jnp.zeros_like(x_local[0])
            out0 = jnp.zeros_like(x_local)

            def tick(carry, t):
                recv, out = carry
                # stage 0 consumes fresh micro-batches; others consume
                # what the previous stage permuted over last tick
                x_t = jax.lax.dynamic_index_in_dim(x_local, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                h_in = jnp.where(stage == 0, x_t, recv)
                mb_id = jnp.clip(t - stage, 0, M - 1)
                r_t = None if r is None else jax.random.fold_in(r, mb_id)
                y = stage_pass(bp_local, h_in, r_t, stage * K)
                # last stage completes micro-batch t-(S-1)
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                cur = jax.lax.dynamic_index_in_dim(out, out_idx, 0, keepdims=False)
                is_done = jnp.logical_and(stage == S - 1, t >= S - 1)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(is_done, y, cur), out_idx, 0
                )
                recv = collectives.p2p_shift(y, "pipe", S, 1)
                return (recv, out), None

            (recv, out), _ = jax.lax.scan(tick, (recv0, out0), jnp.arange(T))
            # only the last stage holds real outputs; all_reduce = broadcast
            out = collectives.all_reduce(
                jnp.where(stage == S - 1, out, jnp.zeros_like(out)), "pipe"
            )
            return out

        in_specs = (
            jax.tree.map(lambda _: _PIPE_STACKED, block_params),
            P(),
            P() if rng is not None else None,
        )
        if rng is None:
            fn = lambda bp, x: pipelined(bp, x, None)
            return collectives.shard_map_manual(
                fn, self.mesh, in_specs[:2], P(), manual_axes=("pipe",)
            )(block_params, x_mb)
        return collectives.shard_map_manual(
            lambda bp, x, r: pipelined(bp, x, r),
            self.mesh, in_specs, P(), manual_axes=("pipe",),
        )(block_params, x_mb, rng)

    # ------------------------------------------------------------------
    # 1F1B: manual forward/backward interleave (reference TrainSchedule
    # semantics, schedule.py:182 + engine.py:540-1005)
    # ------------------------------------------------------------------
    def _1f1b_loss_and_grads(self, params: Any, batch: Any, rng, ls_state):
        """Returns ``(mean_loss, grads)`` with grads already loss-scaled
        (what ``value_and_grad`` of the scaled loss would produce), via an
        explicit 1F1B tick loop: live saved activations are bounded by the
        ring buffer (2S-1 micro-batch inputs per stage) instead of
        growing with the micro-batch count."""
        module = self.pipe_module
        M = self._micro_batches
        S = self.num_stages
        K = module.body_len // S
        inputs, labels = self._split_batch(batch)

        cparams = jax.tree.map(lambda p: p.astype(self.compute_dtype), params)
        pre_sub = {"pre": cparams.get("pre", {}), "tied": cparams.get("tied", {})}
        post_sub = {"post": cparams.get("post", {}), "tied": cparams.get("tied", {})}
        bp = cparams["blocks"]

        def stack_micro(tree):
            def one(x):
                B = x.shape[0]
                assert B % M == 0, f"batch {B} not divisible by {M} micro-batches"
                x = x.reshape((M, B // M) + x.shape[1:])
                return jax.lax.with_sharding_constraint(
                    x, self._sh(DEFAULT_LAYOUT.micro_batch_stack(x.ndim))
                )

            return jax.tree.map(one, tree)

        inp_mb = stack_micro(inputs)
        lab_mb = stack_micro(labels)
        # cotangent seeded per micro-batch: d(scale·mean_m loss_m)/d loss_m
        cot = (self.loss_scaler.scale_loss(jnp.float32(1.0), ls_state) / M).astype(jnp.float32)

        def dyn(tree, i):
            return jax.tree.map(lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree)

        def pre_apply(ps, inp, r):
            full = {"pre": ps["pre"], "tied": ps["tied"]}
            return module.apply_pre(full, inp, r)

        def post_loss(ps, y, lab, r):
            full = {"post": ps["post"], "tied": ps["tied"]}
            out = module.apply_post(full, y, r)
            loss = module.loss_fn(out, lab) if module.loss_fn is not None else out
            loss = jnp.asarray(loss)
            return (jnp.mean(loss) if loss.ndim else loss).astype(jnp.float32)

        stage_pass = self._stage_pass_fn()
        zeros32 = lambda tree: jax.tree.map(lambda x: jnp.zeros(np.shape(x), jnp.float32), tree)

        # local-activation template (shapes as seen inside the shard_map:
        # global along auto axes, so eval_shape outside matches)
        h_abs = jax.eval_shape(lambda ps, im: pre_apply(ps, im, None), pre_sub, dyn(inp_mb, 0))

        def pipelined(bp_all, inp_mb, lab_mb, pre_sub, post_sub, r):
            stage = jax.lax.axis_index("pipe")
            layer0 = stage * K
            T = M + 2 * (S - 1)
            R = 2 * S - 1
            hz = jnp.zeros(h_abs.shape, h_abs.dtype)

            def tick(carry, t):
                # Every slot computes every tick with MASKED data — no
                # lax.cond: divergent branches would let GSPMD place
                # auto-axis resharding collectives inside stage-dependent
                # control flow (= deadlock).  Backward masking is free:
                # VJPs are linear in the cotangent, so zeroing the seed
                # zeroes every grad contribution exactly.
                ring, recv_f, recv_b, dblocks, dpre, dpost, loss_sum = carry

                # ---- forward slot: micro t - stage -------------------
                mf_raw = t - stage
                active_f = jnp.logical_and(mf_raw >= 0, mf_raw < M)
                mf = jnp.clip(mf_raw, 0, M - 1)
                r_f = None if r is None else jax.random.fold_in(r, mf)

                x_pre = pre_apply(pre_sub, dyn(inp_mb, mf), r_f).astype(hz.dtype)
                h_in = jnp.where(stage == 0, x_pre, recv_f)
                y = stage_pass(bp_all, h_in, r_f, layer0)
                slot = jax.lax.rem(mf, R)
                cur = jax.lax.dynamic_index_in_dim(ring, slot, 0, keepdims=False)
                ring = jax.lax.dynamic_update_index_in_dim(
                    ring, jnp.where(active_f, h_in, cur), slot, 0
                )

                # ---- loss head: last stage, same tick ----------------
                head_mask = jnp.logical_and(active_f, stage == S - 1)
                lab_m = dyn(lab_mb, mf)

                def pf(ps, yy):
                    return post_loss(ps, yy, lab_m, r_f)

                l_m, head_vjp = jax.vjp(pf, post_sub, y)
                dpost_d, dy_self = head_vjp(jnp.where(head_mask, cot, 0.0))
                loss_sum = loss_sum + jnp.where(head_mask, l_m, 0.0)
                dpost = jax.tree.map(lambda a, d: a + d.astype(jnp.float32), dpost, dpost_d)

                # ---- backward slot: micro t - 2(S-1) + stage ---------
                mb_raw = t - 2 * (S - 1) + stage
                active_b = jnp.logical_and(mb_raw >= 0, mb_raw < M)
                mb_i = jnp.clip(mb_raw, 0, M - 1)
                r_b = None if r is None else jax.random.fold_in(r, mb_i)
                dy_in = jnp.where(
                    active_b, jnp.where(stage == S - 1, dy_self, recv_b), jnp.zeros_like(hz)
                )
                x_saved = jax.lax.dynamic_index_in_dim(ring, jax.lax.rem(mb_i, R), 0, keepdims=False)

                def f_blk(bpp, xx):
                    return stage_pass(bpp, xx, r_b, layer0)

                _, blk_vjp = jax.vjp(f_blk, bp_all, x_saved)
                dbp_d, dx = blk_vjp(dy_in)
                dblocks = jax.tree.map(lambda a, d: a + d.astype(jnp.float32), dblocks, dbp_d)

                def f_pre(ps):
                    return pre_apply(ps, dyn(inp_mb, mb_i), r_b).astype(hz.dtype)

                _, pre_vjp = jax.vjp(f_pre, pre_sub)
                (dpre_d,) = pre_vjp(jnp.where(stage == 0, dx, jnp.zeros_like(dx)))
                dpre = jax.tree.map(lambda a, d: a + d.astype(jnp.float32), dpre, dpre_d)

                # ---- rotate --------------------------------------------
                recv_f = collectives.p2p_shift(y, "pipe", S, 1)
                recv_b = collectives.p2p_shift(dx, "pipe", S, -1)
                return (ring, recv_f, recv_b, dblocks, dpre, dpost, loss_sum), None

            carry0 = (
                jnp.zeros((R,) + h_abs.shape, h_abs.dtype),
                hz,
                hz,
                zeros32(bp_all),
                zeros32(pre_sub),
                zeros32(post_sub),
                jnp.float32(0.0),
            )
            (ring, _, _, dblocks, dpre, dpost, loss_sum), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T)
            )
            # only one stage contributed to each of these: psum = select+broadcast
            loss_sum = collectives.all_reduce(loss_sum, "pipe")
            dpre = collectives.all_reduce(dpre, "pipe")
            dpost = collectives.all_reduce(dpost, "pipe")
            return loss_sum / M, dblocks, dpre, dpost

        in_specs = [
            jax.tree.map(lambda _: _PIPE_STACKED, bp),
            jax.tree.map(lambda _: P(), inp_mb),
            jax.tree.map(lambda _: P(), lab_mb),
            jax.tree.map(lambda _: P(), pre_sub),
            jax.tree.map(lambda _: P(), post_sub),
        ]
        out_specs = (
            P(),
            jax.tree.map(lambda _: _PIPE_STACKED, bp),
            jax.tree.map(lambda _: P(), pre_sub),
            jax.tree.map(lambda _: P(), post_sub),
        )
        args = [bp, inp_mb, lab_mb, pre_sub, post_sub]
        if rng is not None:
            in_specs.append(P())
            args.append(rng)
            fn = pipelined
        else:
            fn = lambda b_, i_, l_, pr_, po_: pipelined(b_, i_, l_, pr_, po_, None)
        loss, dblocks, dpre, dpost = collectives.shard_map_manual(
            fn, self.mesh, tuple(in_specs), out_specs, manual_axes=("pipe",)
        )(*args)

        grads = {
            "pre": dpre["pre"],
            "blocks": dblocks,
            "post": dpost["post"],
            "tied": jax.tree.map(jnp.add, dpre["tied"], dpost["tied"]),
        }
        # match the params tree exactly (build_params always has all keys)
        grads = {k: grads[k] if k in grads else zeros32(v) for k, v in params.items()}
        return loss, grads

    # ------------------------------------------------------------------
    # public API (reference train_batch, pipe/engine.py:250)
    # ------------------------------------------------------------------
    def _full_batch_from(self, data_iter_or_batch: Any) -> Any:
        if hasattr(data_iter_or_batch, "__next__"):
            micro = [next(data_iter_or_batch) for _ in range(self._micro_batches)]
            return jax.tree.map(lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *micro)
        return data_iter_or_batch

    def train_batch(self, data_iter: Any = None, batch: Any = None) -> jnp.ndarray:
        """One global batch: all micro-batches pipelined + optimizer step,
        one compiled program.  Accepts a data iterator (reference
        signature) or a full batch (leaves shaped [gas*micro_bs, ...])."""
        self.tput_timer.start()
        full = self._full_batch_from(data_iter if data_iter is not None else batch)
        full = jax.tree.map(
            lambda x: jax.device_put(
                np.asarray(x) if not isinstance(x, jax.Array) else x,
                self._sh(batch_pspec(1)),
            ),
            full,
        )

        if "pipe_train" not in self._compiled:
            use_1f1b = (
                self._schedule == "1f1b" and self.num_stages > 1 and bool(self.pipe_module.body_ids)
            )

            def full_step(state, b):
                rng = jax.random.fold_in(state["rng"], state["global_step"])
                if use_1f1b:
                    loss, grads = self._1f1b_loss_and_grads(
                        state["params"], b, rng, state["loss_scale"]
                    )
                else:
                    (scaled_loss, loss), grads = jax.value_and_grad(
                        lambda p: self._compute_loss(p, b, rng, state["loss_scale"]), has_aux=True
                    )(state["params"])
                grads = self.comm.constrain_grads(
                    grads, jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda s: isinstance(s, P))
                )
                grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
                state = dict(state)
                state["micro_step"] = state["micro_step"] + self._micro_batches
                state["global_samples"] = (
                    state["global_samples"]
                    + self.train_micro_batch_size_per_gpu * self._micro_batches * self.mesh_info.dp_world_size
                )
                state, info = self._apply_update(state, grads)
                return state, loss, info

            self._compiled["pipe_train"] = jax.jit(self._scoped(full_step), donate_argnums=(0,))
            # ds_shard Pass 1/2 feed (no-op unless the audit armed it)
            if shard_hooks.armed():
                budget, decisions = shard_hooks.train_budget(self)
                shard_hooks.note_jit(
                    self, "pipe.train_batch", self._compiled["pipe_train"],
                    (self.state, full),
                    leaves=shard_hooks.live_param_leaves(self.state["params"]),
                    budget=budget, decisions=decisions,
                )

        self.state, loss, info = self._compiled["pipe_train"](self.state, full)
        if self.loss_scaler.dynamic:
            if bool(info["overflow"]):
                self.skipped_steps += 1
                log_dist(f"step skipped on overflow; loss scale -> {self.loss_scale}")
            else:
                self._host_global_step += 1
        else:
            self._host_global_step += 1
        self._host_micro_step += self._micro_batches
        self.tput_timer.stop(sync_token=loss)
        self._maybe_report_progress()
        return loss

    def eval_batch(self, data_iter: Any = None, batch: Any = None) -> jnp.ndarray:
        full = self._full_batch_from(data_iter if data_iter is not None else batch)
        full = jax.tree.map(
            lambda x: jax.device_put(
                np.asarray(x) if not isinstance(x, jax.Array) else x,
                self._sh(batch_pspec(1)),
            ),
            full,
        )
        if "pipe_eval" not in self._compiled:

            def eval_fn(state, b):
                _, loss = self._compute_loss(state["params"], b, None, state["loss_scale"])
                return loss

            self._compiled["pipe_eval"] = jax.jit(self._scoped(eval_fn))
        return self._compiled["pipe_eval"](self.state, full)

    # The reference disables the unfused API on pipeline engines
    # (pipe/engine.py:1100-1130): same here.
    def forward(self, *a, **kw):
        raise RuntimeError("PipelineEngine only supports train_batch() / eval_batch()")

    __call__ = forward

    def backward(self, *a, **kw):
        raise RuntimeError("PipelineEngine only supports train_batch() / eval_batch()")

    def step(self, *a, **kw):
        raise RuntimeError("PipelineEngine only supports train_batch() / eval_batch()")
