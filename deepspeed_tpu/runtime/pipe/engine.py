"""Pipeline-parallel engine.

Behavioral analog of the reference's ``runtime/pipe/engine.py``
(``PipelineEngine`` :46, ``train_batch`` :250, instruction executors
:540-1005, schedule interpreter ``_exec_schedule`` :1209) — redesigned
for XLA:

The reference interprets a 1F1B instruction stream per rank, moving
activations with broadcast-based p2p (pipe/p2p.py:31) and a dynamic
shape handshake (:718).  Here the **whole train batch is one compiled
program**: the homogeneous transformer body is stacked ``[L, ...]`` and
sharded ``P('pipe')``; a ``shard_map`` over the ``pipe`` axis runs
``M + S - 1`` ticks of a ``lax.scan``, each tick computing one stage
forward and rotating activations to the next stage with
``lax.ppermute`` (= XLA ``collective_permute`` riding ICI).  Reverse
pipelining falls out of autodiff: the transpose of the tick scan is the
reversed scan with reversed ppermutes, so backward runs pipelined too.
Shape handshakes disappear (static shapes), and XLA overlaps the
permute transfers with stage compute — the role of the reference's
even/odd send/recv interleave (schedule.py:249).

Scheduling semantics match ``GPipe`` (all-forward then all-backward per
batch with per-microbatch remat); the 1F1B instruction stream in
``schedule.py`` remains the documented per-rank equivalent and is used
for buffer/bubble accounting.  Like the reference (pipe/engine.py:56),
ZeRO stages >= 2 are rejected; stage 0/1 compose (optimizer state
sharded over ``fsdp``).

Tied layers (embedding ⇄ head) live outside the pipelined body and are
replicated over ``pipe``, so the reference's tied-grad all-reduce
(``_exec_reduce_tied_grads`` :215) is unnecessary: XLA's partitioner
emits the psum for the shared (auto-sharded) parameter automatically.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.config.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.runtime.pipe.module import PipelineModule
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):
    """Training engine for :class:`PipelineModule` models."""

    def __init__(
        self,
        module: PipelineModule,
        config: DeepSpeedConfig,
        mesh=None,
        params: Any = None,
        tp_spec_fn=None,
        **kw,
    ):
        from deepspeed_tpu.comm.mesh import make_mesh

        if config.zero_config.stage > 1:
            # reference pipe/engine.py:56 — same constraint, same reason:
            # grad/param partitioning across DP conflicts with PP grad
            # accumulation semantics.
            raise AssertionError("ZeRO stages > 1 are incompatible with pipeline parallelism")

        mesh = mesh if mesh is not None else make_mesh(config.mesh)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.num_stages = sizes.get("pipe", 1)
        self.pipe_module = module
        module.configure_stages(self.num_stages)

        if params is None:
            params = module.build_params(jax.random.PRNGKey(config.seed))
        self._micro_batches = config.gradient_accumulation_steps
        self._client_tp_spec_fn = tp_spec_fn
        # grads go straight into _apply_update; no accumulator buffer
        # (saves a full fp32 params-sized tree vs the base engine)
        self._use_grad_acc = False

        super().__init__(
            model=self._pipelined_loss,
            params=params,
            config=config,
            mesh=mesh,
            tp_spec_fn=self._pipe_tp_spec,
            **kw,
        )

        sched = TrainSchedule(self._micro_batches, self.num_stages, 0)
        log_dist(
            f"pipeline engine: stages={self.num_stages} micro_batches={self._micro_batches} "
            f"body_layers={module.body_len} bubble={sched.bubble_fraction():.1%}"
        )

    # ------------------------------------------------------------------
    # sharding: body leaves get P('pipe') on the stacked dim
    # ------------------------------------------------------------------
    def _pipe_tp_spec(self, path: str, shape) -> Optional[P]:
        if path.startswith("blocks/") or path == "blocks":
            # a client tp_spec_fn sees the per-block path and shape (the
            # stacked dim is prepended here)
            if self._client_tp_spec_fn is not None:
                base = self._client_tp_spec_fn(path, shape[1:])
                if base is not None:
                    return P("pipe", *tuple(base))
            return P("pipe")
        if self._client_tp_spec_fn is not None:
            return self._client_tp_spec_fn(path, shape)
        return None

    # ------------------------------------------------------------------
    # the compiled pipeline
    # ------------------------------------------------------------------
    def _split_batch(self, batch: Any) -> Tuple[Any, Any]:
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            return batch[0], batch[1]
        if isinstance(batch, dict):
            labels = batch.get("labels", batch.get("label"))
            if labels is None:
                raise TypeError("pipeline batch dict must contain a 'labels' entry")
            inputs = {k: v for k, v in batch.items() if k not in ("labels", "label")}
            if len(inputs) == 1:
                inputs = next(iter(inputs.values()))
            return inputs, labels
        raise TypeError("pipeline batch must be (inputs, labels) or a dict with 'labels'")

    def _pipelined_loss(self, params: Dict[str, Any], batch: Any, rng) -> jnp.ndarray:
        """Full-batch loss: pre (replicated) → pipelined body → post."""
        module = self.pipe_module
        inputs, labels = self._split_batch(batch)
        x = module.apply_pre(params, inputs, rng)

        if self.num_stages > 1 and module.body_ids:
            M = self._micro_batches
            B = x.shape[0]
            assert B % M == 0, f"batch {B} not divisible by {M} micro-batches"
            mb = B // M
            x_mb = x.reshape((M, mb) + x.shape[1:])
            x_mb = jax.lax.with_sharding_constraint(
                x_mb, self._sh(P(None, ("data", "fsdp")))
            )
            y_mb = self._pipeline_body(params["blocks"], x_mb, rng)
            x = y_mb.reshape((B,) + y_mb.shape[2:])
        else:
            x = module.apply_body(params, x, rng, remat=True)

        out = module.apply_post(params, x, rng)
        loss = module.loss_fn(out, labels) if module.loss_fn is not None else out
        loss = jnp.asarray(loss)
        return jnp.mean(loss) if loss.ndim else loss

    def _pipeline_body(self, block_params: Any, x_mb: jnp.ndarray, rng) -> jnp.ndarray:
        """GPipe over the stacked body under shard_map('pipe').

        ``block_params`` leaves: [L, ...] sharded P('pipe') → local [K, ...].
        ``x_mb``: [M, mb, ...] replicated over pipe (sharded over data on
        the mb dim by the automatic axes).
        """
        module = self.pipe_module
        S = self.num_stages
        M = self._micro_batches
        apply_blk = module.apply_block
        if module.activation_checkpoint_interval > 0:
            # per-microbatch-per-stage remat: the GPipe memory recipe
            # (reference keeps only boundary activations, engine.py:605)
            apply_blk = jax.checkpoint(apply_blk)

        def stage_pass(bp_local, h, r, layer0):
            # rng per (global layer, micro-batch): r is already folded
            # with the micro-batch id; fold the global layer index here
            def body(carry, p):
                hh, k = carry
                rk = None if r is None else jax.random.fold_in(r, k)
                return (apply_blk(p, hh, rng=rk), k + 1), None

            (h, _), _ = jax.lax.scan(body, (h, layer0), bp_local)
            return h

        def pipelined(bp_local, x_local, r):
            stage = jax.lax.axis_index("pipe")
            K = module.body_len // S
            T = M + S - 1
            recv0 = jnp.zeros_like(x_local[0])
            out0 = jnp.zeros_like(x_local)

            def tick(carry, t):
                recv, out = carry
                # stage 0 consumes fresh micro-batches; others consume
                # what the previous stage permuted over last tick
                x_t = jax.lax.dynamic_index_in_dim(x_local, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                h_in = jnp.where(stage == 0, x_t, recv)
                mb_id = jnp.clip(t - stage, 0, M - 1)
                r_t = None if r is None else jax.random.fold_in(r, mb_id)
                y = stage_pass(bp_local, h_in, r_t, stage * K)
                # last stage completes micro-batch t-(S-1)
                out_idx = jnp.clip(t - (S - 1), 0, M - 1)
                cur = jax.lax.dynamic_index_in_dim(out, out_idx, 0, keepdims=False)
                is_done = jnp.logical_and(stage == S - 1, t >= S - 1)
                out = jax.lax.dynamic_update_index_in_dim(
                    out, jnp.where(is_done, y, cur), out_idx, 0
                )
                recv = jax.lax.ppermute(y, "pipe", [(i, (i + 1) % S) for i in range(S)])
                return (recv, out), None

            (recv, out), _ = jax.lax.scan(tick, (recv0, out0), jnp.arange(T))
            # only the last stage holds real outputs; psum = broadcast
            out = jax.lax.psum(jnp.where(stage == S - 1, out, jnp.zeros_like(out)), "pipe")
            return out

        in_specs = (
            jax.tree.map(lambda _: P("pipe"), block_params),
            P(),
            P() if rng is not None else None,
        )
        if rng is None:
            fn = lambda bp, x: pipelined(bp, x, None)
            return jax.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs[:2], out_specs=P(),
                axis_names={"pipe"}, check_vma=False,
            )(block_params, x_mb)
        return jax.shard_map(
            lambda bp, x, r: pipelined(bp, x, r),
            mesh=self.mesh, in_specs=in_specs, out_specs=P(),
            axis_names={"pipe"}, check_vma=False,
        )(block_params, x_mb, rng)

    # ------------------------------------------------------------------
    # public API (reference train_batch, pipe/engine.py:250)
    # ------------------------------------------------------------------
    def _full_batch_from(self, data_iter_or_batch: Any) -> Any:
        if hasattr(data_iter_or_batch, "__next__"):
            micro = [next(data_iter_or_batch) for _ in range(self._micro_batches)]
            return jax.tree.map(lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *micro)
        return data_iter_or_batch

    def train_batch(self, data_iter: Any = None, batch: Any = None) -> jnp.ndarray:
        """One global batch: all micro-batches pipelined + optimizer step,
        one compiled program.  Accepts a data iterator (reference
        signature) or a full batch (leaves shaped [gas*micro_bs, ...])."""
        self.tput_timer.start()
        full = self._full_batch_from(data_iter if data_iter is not None else batch)
        full = jax.tree.map(
            lambda x: jax.device_put(
                np.asarray(x) if not isinstance(x, jax.Array) else x,
                self._sh(P(("data", "fsdp"))),
            ),
            full,
        )

        if "pipe_train" not in self._compiled:

            def full_step(state, b):
                rng = jax.random.fold_in(state["rng"], state["global_step"])
                (scaled_loss, loss), grads = jax.value_and_grad(
                    lambda p: self._compute_loss(p, b, rng, state["loss_scale"]), has_aux=True
                )(state["params"])
                grads = jax.lax.with_sharding_constraint(
                    grads, jax.tree.map(self._sh, self._grad_specs, is_leaf=lambda s: isinstance(s, P))
                )
                grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
                state = dict(state)
                state["micro_step"] = state["micro_step"] + self._micro_batches
                state["global_samples"] = (
                    state["global_samples"]
                    + self.train_micro_batch_size_per_gpu * self._micro_batches * self.mesh_info.dp_world_size
                )
                state, info = self._apply_update(state, grads)
                return state, loss, info

            self._compiled["pipe_train"] = jax.jit(full_step, donate_argnums=(0,))

        self.state, loss, info = self._compiled["pipe_train"](self.state, full)
        if self.loss_scaler.dynamic:
            if bool(info["overflow"]):
                self.skipped_steps += 1
                log_dist(f"step skipped on overflow; loss scale -> {self.loss_scale}")
            else:
                self._host_global_step += 1
        else:
            self._host_global_step += 1
        self._host_micro_step += self._micro_batches
        self.tput_timer.stop(sync_token=loss)
        self._maybe_report_progress()
        return loss

    def eval_batch(self, data_iter: Any = None, batch: Any = None) -> jnp.ndarray:
        full = self._full_batch_from(data_iter if data_iter is not None else batch)
        full = jax.tree.map(
            lambda x: jax.device_put(
                np.asarray(x) if not isinstance(x, jax.Array) else x,
                self._sh(P(("data", "fsdp"))),
            ),
            full,
        )
        if "pipe_eval" not in self._compiled:

            def eval_fn(state, b):
                _, loss = self._compute_loss(state["params"], b, None, state["loss_scale"])
                return loss

            self._compiled["pipe_eval"] = jax.jit(eval_fn)
        return self._compiled["pipe_eval"](self.state, full)

    # The reference disables the unfused API on pipeline engines
    # (pipe/engine.py:1100-1130): same here.
    def forward(self, *a, **kw):
        raise RuntimeError("PipelineEngine only supports train_batch() / eval_batch()")

    __call__ = forward

    def backward(self, *a, **kw):
        raise RuntimeError("PipelineEngine only supports train_batch() / eval_batch()")

    def step(self, *a, **kw):
        raise RuntimeError("PipelineEngine only supports train_batch() / eval_batch()")
