"""Checkpoint save/load.

Replaces the reference's per-rank checkpoint file zoo
(``mp_rank_XX_model_states.pt`` + ``*_zero_pp_rank_N_..._optim_states.pt``,
engine.py:1854-2106 and SURVEY.md §5.4) with **one sharded checkpoint per
tag** written through orbax/tensorstore: every rank writes its shards of
the same logical arrays, and on load orbax reshards to whatever mesh the
restoring job uses — which subsumes the reference's elastic-DP checkpoint
machinery (stage2.py:1828-2004) and ``MegatronSDLoader`` MP resize
(state_dict_factory.py:199) in one mechanism.

Kept semantics: ``latest`` tag file, client_state round-trip, tag
validation mode.  The ``zero_to_fp32`` analog (full fp32 state_dict from a
sharded checkpoint) is ``consolidate_fp32_state_dict`` below.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"


def _ckpt_path(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(
    engine,
    save_dir: str,
    tag: Optional[str] = None,
    client_state: Optional[dict] = None,
    save_latest: bool = True,
) -> str:
    if tag is None:
        tag = f"global_step{int(engine.state['global_step'])}"
    path = _ckpt_path(save_dir, tag)
    os.makedirs(os.path.dirname(path), exist_ok=True)

    ckptr = _checkpointer()
    # flat-padded ZeRO leaves are stored in their natural shapes so the
    # checkpoint is independent of this job's fsdp degree
    ckptr.save(os.path.join(path, "state"), engine._to_portable_state(engine.state), force=True)
    ckptr.wait_until_finished()

    # ZeRO-Offload/Infinity: fp32 masters + moments live on host, outside
    # engine.state — persist them beside the sharded state (reference
    # writes *_optim_states.pt per rank; host state is process-local here)
    save_host = getattr(engine, "_save_host_optimizer", None)
    if save_host is not None:
        save_host(path)

    meta = {
        "tag": str(tag),
        "global_step": int(engine.state["global_step"]),
        "micro_step": int(engine.state["micro_step"]),
        "global_samples": int(engine.state["global_samples"]),
        "skipped_steps": int(engine.skipped_steps),
        "world_size": engine.mesh_info.world_size,
        "dp_world_size": engine.mesh_info.dp_world_size,
        "mp_world_size": engine.mesh_info.model_parallel_world_size,
        "zero_stage": engine.zero_stage,
        # whether the tag contains an allocated grad accumulator (gas==1
        # engines skip the persistent buffer; a restoring job with a
        # different gas must know to partial-restore)
        "has_grad_acc": bool(engine.state.get("grad_acc")),
        "client_state": client_state or {},
        "ds_tpu_version": _version(),
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
        if save_latest:
            with open(os.path.join(os.path.abspath(save_dir), LATEST_FILE), "w") as f:
                f.write(str(tag))
    log_dist(f"saved checkpoint {path}")
    return path


def load_checkpoint(
    engine,
    load_dir: str,
    tag: Optional[str] = None,
    load_optimizer_states: bool = True,
    load_lr_scheduler_states: bool = True,
    load_module_only: bool = False,
):
    """Returns (path, client_state) like the reference (engine.py:1654),
    or (None, {}) if nothing to load."""
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no '{LATEST_FILE}' file at {load_dir}; nothing loaded")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _ckpt_path(load_dir, tag)
    if not os.path.isdir(path):
        logger.warning(f"checkpoint {path} not found")
        return None, {}

    # phase-dependent state layouts (1-bit Adam's compressed phase) must
    # be aligned with the tag's step count BEFORE the restore target is
    # built, or the on-disk tree won't match
    meta_path = os.path.join(path, "meta.json")
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        sync_phase = getattr(engine, "_sync_onebit_phase", None)
        if sync_phase is not None:
            sync_phase(int(meta.get("global_step", 0)))

    ckptr = _checkpointer()
    # Abstract target: checkpoint-layout shapes + *current* shardings —
    # orbax reshards on read, giving elastic DP/MP resize on load.
    # (Flat-padded ZeRO leaves are stored in natural shapes; the engine
    # re-pads them for its own mesh below.)
    target = engine._portable_target()

    def _partial_restore(skip_keys):
        import orbax.checkpoint as ocp

        partial_target = {k: v for k, v in target.items() if k not in skip_keys}
        out = dict(
            ocp.PyTreeCheckpointer().restore(
                os.path.join(path, "state"),
                args=ocp.args.PyTreeRestore(
                    item=jax.tree.map(lambda a: np.zeros(a.shape, a.dtype), partial_target),
                    partial_restore=True,
                ),
            )
        )
        for k in skip_keys:
            out[k] = {}
        return out

    # grad_acc layout mismatch across gas settings (a gas==1 engine never
    # allocates the persistent accumulator): skip it in the restore and
    # keep this engine's own — at any saved step boundary it is zeros, so
    # no information is lost.  Tags from before the meta key existed were
    # written by engines that always allocated the accumulator, so a
    # missing key means "the tag has one".
    disk_has_acc = meta.get("has_grad_acc", True)
    skip = set()
    if disk_has_acc != bool(target.get("grad_acc")) and getattr(engine, "_use_grad_acc", True):
        skip.add("grad_acc")

    from_partial = False
    try:
        if skip:
            restored = _partial_restore(skip)
            from_partial = True
        else:
            restored = ckptr.restore(os.path.join(path, "state"), target)
    except (ValueError, TypeError):
        if getattr(engine, "_host_opt", None) is None:
            raise
        # offload engine restoring a non-offload checkpoint: the saved
        # tree has real opt_state arrays while our target has {} — restore
        # everything except opt_state and keep the host masters path below
        restored = _partial_restore(skip | {"opt_state"})
        from_partial = True

    # checkpoint layout -> this engine's state layout (re-pad flat
    # leaves for the current mesh), then pin the state shardings
    restored = engine._from_portable_state(restored)
    if "grad_acc" in skip:
        # keep this engine's accumulator SHAPE but force it to zeros —
        # a restore mid-accumulation must not mix pending grads from the
        # pre-restore params into the restored run
        restored["grad_acc"] = (
            jax.jit(
                lambda t: jax.tree.map(jnp.zeros_like, t),
                out_shardings=engine._state_shardings["grad_acc"],
            )(engine.state["grad_acc"])
            if engine.state["grad_acc"]
            else {}
        )
    if engine._flat_plan:
        restored = jax.device_put(restored, engine._state_shardings)
    elif from_partial:
        restored["params"] = jax.device_put(restored["params"], engine._state_shardings["params"])

    if load_module_only or not load_optimizer_states:
        engine.state["params"] = restored["params"]
        if not load_module_only:
            for key in ("micro_step", "global_step", "global_samples", "loss_scale", "rng"):
                engine.state[key] = restored[key]
    else:
        engine.state = restored

    if getattr(engine, "_host_opt", None) is not None:
        # restores per-shard npz when allowed and present; otherwise
        # rebuilds fp32 masters from the restored (compute-dtype) params
        engine._load_host_optimizer(
            path, restored["params"], use_files=load_optimizer_states and not load_module_only
        )

    client_state: Dict[str, Any] = {}
    if meta:
        client_state = meta.get("client_state", {})
        engine.skipped_steps = meta.get("skipped_steps", 0)
        if load_lr_scheduler_states and engine.client_lr_scheduler is not None and hasattr(engine.client_lr_scheduler, "load_state_dict"):
            sd = client_state.get("__lr_scheduler__")
            if sd:
                engine.client_lr_scheduler.load_state_dict(sd)
    # reconcile the engine's host-side step mirrors with the restored state
    engine._host_global_step = int(engine.state["global_step"])
    engine._host_micro_step = int(engine.state["micro_step"])
    log_dist(f"loaded checkpoint {path} (global_step={engine._host_global_step})")
    return path, client_state


def consolidate_fp32_state_dict(engine) -> Dict[str, np.ndarray]:
    """Gather full (unsharded) fp32 params on host — the
    ``zero_to_fp32.py`` / ``_zero3_consolidated_fp16_state_dict``
    (engine.py:2039) analog.  Works for any ZeRO stage because params are
    logical arrays; this is just a device->host gather."""
    flat = {}

    def visit(path, leaf):
        arr = np.asarray(jax.device_get(leaf)).astype(np.float32)
        from deepspeed_tpu.runtime.zero.stages import _path_str

        flat[_path_str(path)] = arr

    jax.tree_util.tree_map_with_path(visit, engine._unflatten_state_leaves(engine.state["params"]))
    return flat


def _version() -> str:
    from deepspeed_tpu.version import __version__

    return __version__
