"""Checkpoint save/load.

Replaces the reference's per-rank checkpoint file zoo
(``mp_rank_XX_model_states.pt`` + ``*_zero_pp_rank_N_..._optim_states.pt``,
engine.py:1854-2106 and SURVEY.md §5.4) with **one sharded checkpoint per
tag** written through orbax/tensorstore: every rank writes its shards of
the same logical arrays, and on load orbax reshards to whatever mesh the
restoring job uses — which subsumes the reference's elastic-DP checkpoint
machinery (stage2.py:1828-2004) and ``MegatronSDLoader`` MP resize
(state_dict_factory.py:199) in one mechanism.

Durability (deepspeed_tpu.resilience, docs/resilience.md): a tag is
written into ``<tag>.tmp``, a size+checksum ``manifest.json`` goes in
last, and a single rename publishes it — a kill at any point leaves the
previous tree intact.  On load the manifest is re-verified; a corrupt
tag is quarantined (``<tag>.corrupt``) and the load falls back to the
newest verified tag.  Checkpoint I/O runs under the configured retry
policy, and retention GC (``keep_last_n``/``keep_every``) runs after
each successful save.

Kept semantics: ``latest`` tag file (written atomically), client_state
round-trip, tag validation mode.  The ``zero_to_fp32`` analog (full fp32
state_dict from a sharded checkpoint) is ``consolidate_fp32_state_dict``
below.
"""
from __future__ import annotations

import json
import os
import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.resilience import CheckpointNotFoundError, atomic, faults, manager
from deepspeed_tpu.resilience.policy import retry_call
from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = manager.LATEST_FILE


def _ckpt_path(save_dir: str, tag: str) -> str:
    return os.path.join(os.path.abspath(save_dir), str(tag))


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _resilience_cfg(engine):
    cfg = getattr(getattr(engine, "config", None), "resilience", None)
    if cfg is None:
        from deepspeed_tpu.config.config import ResilienceConfig

        cfg = ResilienceConfig()
    return cfg


def _note_ckpt_dir(engine, directory: str) -> None:
    note = getattr(engine, "_note_checkpoint_dir", None)
    if note is not None:
        note(directory)


def _sanitizer(engine):
    return getattr(engine, "_sanitizer", None)


def _supervisor(engine):
    return getattr(engine, "_supervision", None)


def _loader_state(engine) -> Optional[dict]:
    """The registered dataloader's resume cursor, or None (loaders
    without the state protocol never break a save)."""
    loader = getattr(engine, "_train_loader", None)
    if loader is None or not hasattr(loader, "state_dict"):
        return None
    try:
        return loader.state_dict()
    except Exception as e:  # noqa: BLE001 — cursors are best-effort
        logger.warning(f"dataloader state_dict failed ({e!r}); checkpoint has no resume cursor")
        return None


def _merge_loader_state(engine, client_state: Optional[dict]) -> Optional[dict]:
    """Fold the registered loader's cursor into the client state (an
    explicit caller-provided '__dataloader__' wins)."""
    sd = _loader_state(engine)
    if sd is None:
        return client_state
    out = dict(client_state or {})
    out.setdefault("__dataloader__", sd)
    return out


def _restore_loader_state(engine, client_state: Dict[str, Any]) -> None:
    sd = client_state.get("__dataloader__")
    loader = getattr(engine, "_train_loader", None)
    if not sd or loader is None or not hasattr(loader, "load_state_dict"):
        return
    try:
        loader.load_state_dict(sd)
        log_dist(
            f"dataloader cursor restored (epoch {sd.get('epoch')}, batch {sd.get('cursor')})"
        )
    except Exception as e:  # noqa: BLE001
        logger.warning(f"dataloader cursor restore failed ({e!r}); loader starts fresh")


def _build_meta(engine, tag: str, client_state: Optional[dict]) -> Dict[str, Any]:
    return {
        "tag": tag,
        "global_step": int(jax.device_get(engine.state["global_step"])),
        "micro_step": int(jax.device_get(engine.state["micro_step"])),
        "global_samples": int(jax.device_get(engine.state["global_samples"])),
        "skipped_steps": int(engine.skipped_steps),
        "world_size": engine.mesh_info.world_size,
        "dp_world_size": engine.mesh_info.dp_world_size,
        "mp_world_size": engine.mesh_info.model_parallel_world_size,
        "zero_stage": engine.zero_stage,
        # whether the tag contains an allocated grad accumulator (gas==1
        # engines skip the persistent buffer; a restoring job with a
        # different gas must know to partial-restore)
        "has_grad_acc": bool(engine.state.get("grad_acc")),
        # comm-layer error-feedback residual rows (docs/comm.md): their
        # (n, Mp) shape keys on the dp grid, so a job restoring under a
        # different mesh/strategy must skip-and-reset them
        "comm_state": _comm_state_shape(engine.state.get("comm")),
        "client_state": client_state or {},
        "ds_tpu_version": _version(),
    }


def _comm_state_shape(comm) -> Optional[list]:
    """``[rows, padded_len]`` of the error-feedback residuals, or None
    when the engine runs a stateless comm strategy."""
    if not comm:
        return None
    we = comm.get("worker_error") if isinstance(comm, dict) else None
    return [int(we.shape[0]), int(we.shape[1])] if we is not None else None


def save_checkpoint(
    engine,
    save_dir: str,
    tag: Optional[str] = None,
    client_state: Optional[dict] = None,
    save_latest: bool = True,
    async_save: Optional[bool] = None,
) -> str:
    """Write one checkpoint tag.  ``async_save=None`` defers to the
    engine's ``overlap.async_checkpoint`` config: when an async writer is
    armed, the device state is snapshotted to host (the only stall) and
    the stage->manifest->rename commit runs on a background thread —
    training resumes immediately and the returned path is where the tag
    WILL be committed (``engine._async_writer.drain()`` to wait).  Any
    save request drains an in-flight async save first."""
    rcfg = _resilience_cfg(engine)
    ck = rcfg.checkpoint
    san = _sanitizer(engine)
    if san is not None:
        # a donated (deleted) leaf fed into the snapshot would otherwise
        # surface as a mid-save crash with no provenance
        san.donation.check_live(engine.state, "checkpoint.save")
    if tag is None:
        tag = f"global_step{int(jax.device_get(engine.state['global_step']))}"
    tag = str(tag)
    client_state = _merge_loader_state(engine, client_state)
    save_dir = os.path.abspath(save_dir)
    final_path = _ckpt_path(save_dir, tag)
    os.makedirs(save_dir, exist_ok=True)

    # the stall clock starts BEFORE the drain: waiting out the previous
    # in-flight commit is checkpoint-induced training stall and must
    # show up in the ckpt_stall phase, not hide in "other"
    timeline = getattr(engine, "timeline", None)
    t_stall = time.perf_counter()
    writer = getattr(engine, "_async_writer", None)
    if writer is not None:
        # sync saves drain too: they share the tree's staging/latest/GC
        # state with whatever commit is still in flight
        writer.drain()
    use_async = (writer is not None) if async_save is None else (bool(async_save) and writer is not None)
    if use_async:
        blockers = []
        if jax.process_count() > 1:
            blockers.append("multi-process saves are collective (staging barriers)")
        if getattr(engine, "_host_opt", None) is not None:
            blockers.append("host-offload optimizer state lives outside engine.state")
        if not ck.atomic:
            blockers.append("'resilience.checkpoint.atomic' is off")
        if blockers:
            logger.warning(
                f"async checkpoint save unavailable ({'; '.join(blockers)}); saving synchronously"
            )
            use_async = False

    if use_async:
        path = _submit_async_save(
            engine, writer, save_dir, tag, final_path, rcfg, client_state, save_latest
        )
        if timeline is not None:
            timeline.note("ckpt_stall", time.perf_counter() - t_stall)
        return path
    # checkpoint I/O is deliberate host traffic: relax any armed
    # sanitizer transfer guard for the duration of the sync write
    with san.transfer.io_region() if san is not None else nullcontext():
        path = _sync_save(engine, save_dir, tag, final_path, rcfg, client_state, save_latest)
    if timeline is not None:
        timeline.note("ckpt_stall", time.perf_counter() - t_stall)
    return path


def _sync_save(
    engine,
    save_dir: str,
    tag: str,
    final_path: str,
    rcfg,
    client_state: Optional[dict],
    save_latest: bool,
) -> str:
    ck = rcfg.checkpoint
    meta = _build_meta(engine, tag, client_state)

    def _barrier(name: str) -> None:
        if jax.process_count() > 1:
            # watchdog-armed: a peer dying mid-save must surface as a
            # supervised deadline/rescue, not an eternal barrier
            from deepspeed_tpu.resilience.supervision import supervised_sync

            supervised_sync(f"ckpt_{name}_{tag}", supervisor=_supervisor(engine))

    def _write_tag() -> None:
        faults.check("ckpt.save.state", path=final_path)
        if ck.atomic:
            # rank 0 owns the staging-dir lifecycle (clearing a leftover
            # from a crashed save must not race other ranks' writes);
            # everyone else waits, then writes into it
            if jax.process_index() == 0:
                target = manager.begin_stage(save_dir, tag)
            else:
                target = manager.stage_path(save_dir, tag)
            _barrier("stage")
        else:
            target = final_path
        os.makedirs(target, exist_ok=True)
        try:
            ckptr = _checkpointer()
            # flat-padded ZeRO leaves are stored in their natural shapes so
            # the checkpoint is independent of this job's fsdp degree
            ckptr.save(
                os.path.join(target, "state"), engine._to_portable_state(engine.state), force=True
            )
            ckptr.wait_until_finished()

            # ZeRO-Offload/Infinity: fp32 masters + moments live on host,
            # outside engine.state — persist them beside the sharded state
            # (reference writes *_optim_states.pt per rank; host state is
            # process-local here)
            save_host = getattr(engine, "_save_host_optimizer", None)
            if save_host is not None:
                save_host(target)
            # every rank's plain-file writes (host optimizer npz) must be
            # complete before rank 0 hashes the tree into the manifest
            _barrier("host_state")

            if jax.process_index() == 0:
                faults.check("ckpt.save.meta", path=target)
                atomic.atomic_write_text(
                    os.path.join(target, "meta.json"), json.dumps(meta, indent=2)
                )
                if ck.atomic:
                    # manifest last: its presence certifies completeness
                    atomic.write_manifest(target, algorithm=ck.checksum)
                    manager.commit_tag(save_dir, tag)
            # no rank reads `latest` / proceeds past the save until the
            # tag is committed everywhere
            _barrier("commit")
        except OSError:
            if ck.atomic and jax.process_index() == 0:
                manager.abort_stage(save_dir, tag)
            raise
        finally:
            if ck.atomic and jax.process_index() == 0:
                # after this frame unwinds no live save owns the staging
                # dir (a real crash clears the in-memory registry with
                # the process; a simulated kill must match)
                manager.release_stage(save_dir, tag)

    policy = rcfg.retry.policy()
    if jax.process_count() > 1:
        # _write_tag is a collective (staging/commit barriers): retrying
        # it on ONE rank would desync the barrier sequence and hang the
        # job — without cross-rank retry agreement, fail fast instead
        import dataclasses as _dc

        policy = _dc.replace(policy, max_attempts=1)
    retry_call(
        policy,
        _write_tag,
        on_retry=lambda attempt, e, pause: logger.warning(
            f"checkpoint save of '{tag}' failed (attempt {attempt}: {e}); retrying in {pause:.1f}s"
        ),
    )

    if jax.process_index() == 0:
        if save_latest:
            retry_call(rcfg.retry.policy(), manager.write_latest, save_dir, tag)
        deleted = manager.retention_gc(
            save_dir, keep_last_n=ck.keep_last_n, keep_every=ck.keep_every, protect=(tag,)
        )
        if deleted:
            log_dist(f"retention gc: deleted old tag(s) {deleted} (keep_last_n={ck.keep_last_n})")
    _note_ckpt_dir(engine, save_dir)
    log_dist(f"saved checkpoint {final_path}")
    return final_path


def _snapshot_state_to_host(engine) -> Any:
    """Portable-layout state with every leaf materialized on host.
    ``copy_to_host_async`` fans the D2H transfers out first so the
    blocking ``np.asarray`` walk overlaps them; after this returns,
    training may donate/overwrite the device buffers freely."""
    portable = engine._to_portable_state(engine.state)
    for leaf in jax.tree.leaves(portable):
        copy_async = getattr(leaf, "copy_to_host_async", None)
        if copy_async is not None:
            try:
                copy_async()
            except Exception:  # noqa: BLE001 — fall back to the sync pull
                pass
    return jax.tree.map(np.asarray, portable)


def _submit_async_save(
    engine,
    writer,
    save_dir: str,
    tag: str,
    final_path: str,
    rcfg,
    client_state: Optional[dict],
    save_latest: bool,
) -> str:
    """Snapshot now (the only training stall), commit in the background.

    The background job is the SAME single-process commit protocol as
    :func:`_sync_save` — stage into ``<tag>.tmp`` under the in-flight
    registry, meta, manifest last, one rename, latest pointer, retention
    GC — so every fault-injection durability property carries over: a
    kill at any background instruction leaves the previous tree (plus a
    ``.tmp`` leftover) and never a loadable-but-corrupt tag."""
    ck = rcfg.checkpoint
    meta = _build_meta(engine, tag, client_state)  # device->host scalar reads
    snapshot = _snapshot_state_to_host(engine)
    # built on the CALLER thread: the orbax import chain registers
    # threading/concurrent.futures atexit hooks, which raise if first
    # reached from the background thread during interpreter shutdown
    # (a script whose last act is this save)
    ckptr = _checkpointer()
    policy = rcfg.retry.policy()

    def commit() -> None:
        def _write() -> None:
            faults.check("ckpt.save.state", path=final_path)
            target = manager.begin_stage(save_dir, tag)
            try:
                ckptr.save(os.path.join(target, "state"), snapshot, force=True)
                ckptr.wait_until_finished()
                faults.check("ckpt.save.meta", path=target)
                atomic.atomic_write_text(
                    os.path.join(target, "meta.json"), json.dumps(meta, indent=2)
                )
                # manifest last: its presence certifies completeness
                atomic.write_manifest(target, algorithm=ck.checksum)
                manager.commit_tag(save_dir, tag)
            except OSError:
                manager.abort_stage(save_dir, tag)
                raise
            finally:
                manager.release_stage(save_dir, tag)

        retry_call(
            policy,
            _write,
            on_retry=lambda attempt, e, pause: logger.warning(
                f"async checkpoint save of '{tag}' failed (attempt {attempt}: {e}); "
                f"retrying in {pause:.1f}s"
            ),
        )
        if save_latest:
            retry_call(rcfg.retry.policy(), manager.write_latest, save_dir, tag)
        deleted = manager.retention_gc(
            save_dir, keep_last_n=ck.keep_last_n, keep_every=ck.keep_every, protect=(tag,)
        )
        if deleted:
            log_dist(f"retention gc: deleted old tag(s) {deleted} (keep_last_n={ck.keep_last_n})")
        log_dist(f"async checkpoint save of {final_path} committed")

    writer.submit(tag, final_path, commit)
    _note_ckpt_dir(engine, save_dir)
    log_dist(f"async checkpoint save of {final_path} submitted; training resumes")
    return final_path


def _broadcast_tag(tag: Optional[str], supervisor=None) -> Optional[str]:
    """Share rank 0's resolved tag with every process (no-op
    single-process).  Fixed-width uint8 buffer; empty means None."""
    if jax.process_count() <= 1:
        return tag
    from contextlib import nullcontext

    from jax.experimental import multihost_utils

    buf = np.zeros(256, np.uint8)
    if tag:
        raw = str(tag).encode()[:256]
        buf[: len(raw)] = np.frombuffer(raw, np.uint8)
    with supervisor.armed("ckpt.tag_broadcast") if supervisor is not None else nullcontext():
        out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    decoded = bytes(out[: int(np.max(np.nonzero(out)[0], initial=-1)) + 1]).decode(errors="ignore")
    return decoded or None


def _load_candidates(load_dir: str, requested: Optional[str], explicit: bool) -> List[str]:
    """Tags to try, in order: the requested one first, then (unless the
    tag was named explicitly by the caller) every other committed tag
    newest-first — the fallback scan for a stale/corrupt ``latest``."""
    candidates: List[str] = [requested] if requested else []
    if not explicit:
        for t in manager.newest_first(load_dir):
            if t not in candidates:
                candidates.append(t)
    return candidates


def load_checkpoint(
    engine,
    load_dir: str,
    tag: Optional[str] = None,
    load_optimizer_states: bool = True,
    load_lr_scheduler_states: bool = True,
    load_module_only: bool = False,
    strict: Optional[bool] = None,
):
    """Returns (path, client_state) like the reference (engine.py:1654),
    or (None, {}) if nothing loadable was found.

    ``strict=True`` (or config ``resilience.checkpoint.fail_on_missing``)
    raises :class:`CheckpointNotFoundError` instead of the silent
    ``(None, {})``.  With ``verify_on_load`` (default), every candidate
    tag's manifest is re-checked first; corrupt tags are quarantined to
    ``<tag>.corrupt`` and the newest verified tag wins.
    """
    rcfg = _resilience_cfg(engine)
    ck = rcfg.checkpoint
    if strict is None:
        strict = ck.fail_on_missing
    writer = getattr(engine, "_async_writer", None)
    if writer is not None:
        # restoring while a background commit mutates the tree (rename,
        # latest update, GC) would race the candidate scan
        writer.drain()
    load_dir = os.path.abspath(load_dir)
    explicit = tag is not None
    requested = str(tag) if explicit else manager.read_latest(load_dir)
    if requested is None and not explicit:
        logger.warning(f"no '{LATEST_FILE}' file at {load_dir}; scanning for committed tags")

    tried: List[str] = []
    chosen: Optional[str] = None
    if jax.process_index() == 0:
        # rank 0 alone resolves the candidate (verify + quarantine): a
        # per-rank decision could quarantine/restore DIFFERENT tags and
        # silently resume ranks at different steps
        for cand in _load_candidates(load_dir, requested, explicit):
            path = _ckpt_path(load_dir, cand)
            if not os.path.isdir(path):
                tried.append(f"'{cand}': missing")
                continue
            if ck.verify_on_load:
                ok, notes = manager.verify_tag(load_dir, cand)
                if not ok:
                    dest = manager.quarantine_tag(load_dir, cand)
                    logger.warning(
                        f"checkpoint tag '{cand}' failed verification ({'; '.join(notes)}); "
                        f"quarantined to {os.path.basename(dest)}"
                    )
                    tried.append(f"'{cand}': corrupt ({notes[0]})")
                    continue
                if notes:
                    logger.warning(f"checkpoint tag '{cand}': {'; '.join(notes)}")
            if cand != requested:
                logger.warning(
                    f"falling back to verified tag '{cand}' (requested "
                    f"{'nothing' if requested is None else repr(requested)})"
                )
            chosen = cand
            break
    chosen = _broadcast_tag(chosen, supervisor=_supervisor(engine))
    if chosen is not None:
        san = _sanitizer(engine)
        with san.transfer.io_region() if san is not None else nullcontext():
            return _restore_tag(
                engine,
                _ckpt_path(load_dir, chosen),
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states,
                load_module_only=load_module_only,
            )

    detail = f" (requested tag '{requested}')" if requested else ""
    attempts = f"; tried: {', '.join(tried)}" if tried else ""
    msg = f"no loadable checkpoint under {load_dir}{detail}{attempts}"
    if strict:
        raise CheckpointNotFoundError(
            msg + "; pass strict=False or set 'resilience.checkpoint.fail_on_missing' = false "
            "for the legacy (None, {}) return"
        )
    logger.warning(msg + "; nothing loaded")
    return None, {}


def _restore_tag(
    engine,
    path: str,
    load_optimizer_states: bool = True,
    load_lr_scheduler_states: bool = True,
    load_module_only: bool = False,
) -> Tuple[str, Dict[str, Any]]:
    # phase-dependent state layouts (1-bit Adam's compressed phase) must
    # be aligned with the tag's step count BEFORE the restore target is
    # built, or the on-disk tree won't match
    meta_path = os.path.join(path, "meta.json")
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        sync_phase = getattr(engine, "_sync_onebit_phase", None)
        if sync_phase is not None:
            sync_phase(int(meta.get("global_step", 0)))

    ckptr = _checkpointer()
    # Abstract target: checkpoint-layout shapes + *current* shardings —
    # orbax reshards on read, giving elastic DP/MP resize on load.
    # (Flat-padded ZeRO leaves are stored in natural shapes; the engine
    # re-pads them for its own mesh below.)
    target = engine._portable_target()

    if meta.get("format") == "local_npz":
        # supervision emergency tag (docs/resilience.md): a survivor's
        # rank-local host snapshot, committed with no collectives.  The
        # npz holds full logical arrays, so the device_put below
        # reshards for whatever mesh THIS job runs — the emergency
        # analog of orbax's elastic DP-resize restore.
        from deepspeed_tpu.resilience.supervision import load_local_state

        restored = load_local_state(path, target)
        return _finish_restore(
            engine, path, meta, restored, from_partial=True, skip=set(),
            load_optimizer_states=load_optimizer_states,
            load_lr_scheduler_states=load_lr_scheduler_states,
            load_module_only=load_module_only, full_put=True,
        )

    def _partial_restore(skip_keys):
        import orbax.checkpoint as ocp

        partial_target = {k: v for k, v in target.items() if k not in skip_keys}
        try:
            out = dict(
                ocp.PyTreeCheckpointer().restore(
                    os.path.join(path, "state"),
                    args=ocp.args.PyTreeRestore(
                        item=jax.tree.map(lambda a: np.zeros(a.shape, a.dtype), partial_target),
                        partial_restore=True,
                    ),
                )
            )
        except TypeError:
            # older orbax has no partial_restore kwarg: rebuild a
            # DISK-shaped target for the reconstructible skipped keys,
            # read everything, and discard the skipped values below
            from jax.sharding import NamedSharding, PartitionSpec as _P

            repl = NamedSharding(engine.mesh, _P())
            full_target = dict(partial_target)
            for k in skip_keys:
                if k == "grad_acc":
                    # the tag's accumulator is a params-shaped fp32 tree
                    # (or the empty node a gas==1/explicit-comm engine saved)
                    full_target[k] = (
                        jax.tree.map(
                            lambda a: jax.ShapeDtypeStruct(a.shape, np.float32, sharding=repl),
                            target["params"],
                        )
                        if meta.get("has_grad_acc", True)
                        else {}
                    )
                elif k == "comm" and "comm_state" not in meta:
                    pass  # pre-comm-layer tag: no subtree on disk
                elif k == "comm":
                    dc = meta.get("comm_state")
                    if dc:
                        n_, mp_ = int(dc[0]), int(dc[1])
                        full_target[k] = {
                            "worker_error": jax.ShapeDtypeStruct((n_, mp_), np.float32, sharding=repl),
                            "server_error": jax.ShapeDtypeStruct((n_, mp_ // n_), np.float32, sharding=repl),
                        }
                    else:
                        full_target[k] = {}
            # remaining skipped keys with no reconstructible schema
            # (e.g. an offload engine reading a non-offload tag's
            # opt_state): rebuild DISK-shaped targets from orbax
            # metadata — old orbax insists the restore target cover
            # every on-disk key; the values are discarded below
            try:
                disk_meta = ckptr.metadata(os.path.join(path, "state"))
            except Exception:  # noqa: BLE001 — metadata is best-effort help
                disk_meta = {}
            for k in skip_keys:
                if k in full_target or k not in disk_meta:
                    continue
                full_target[k] = jax.tree.map(
                    lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=repl),
                    disk_meta[k],
                    is_leaf=lambda m: hasattr(m, "shape") and hasattr(m, "dtype"),
                )
            out = dict(ckptr.restore(os.path.join(path, "state"), full_target))
        for k in skip_keys:
            out[k] = {}
        return out

    # grad_acc layout mismatch across gas settings (a gas==1 engine never
    # allocates the persistent accumulator): skip it in the restore and
    # keep this engine's own — at any saved step boundary it is zeros, so
    # no information is lost.  Tags from before the meta key existed were
    # written by engines that always allocated the accumulator, so a
    # missing key means "the tag has one".
    disk_has_acc = meta.get("has_grad_acc", True)
    skip = set()
    if disk_has_acc != bool(target.get("grad_acc")) and getattr(engine, "_use_grad_acc", True):
        skip.add("grad_acc")
    # comm EF residuals: restore only when the tag's rows layout matches
    # this engine's exactly (same dp grid, same strategy/EF setting) —
    # anything else skips the subtree through the partial-restore path
    # (modern orbax never reads the bytes; the old-orbax fallback inside
    # _partial_restore rebuilds the DISK layout from meta and discards)
    reset_comm = False
    if "comm" in target:
        eng_comm = _comm_state_shape(target.get("comm"))
        if "comm_state" not in meta or meta.get("comm_state") != eng_comm:
            skip.add("comm")
            reset_comm = True
        if reset_comm and eng_comm is not None:
            logger.warning(
                "comm: error-feedback residuals in the tag do not match this "
                f"engine's layout (tag {meta.get('comm_state', 'absent')}, engine "
                f"{eng_comm}); residuals RESET to zero — the error-feedback bias "
                "restarts from scratch (bounded; convergence unaffected)"
            )

    from_partial = False
    try:
        if skip:
            restored = _partial_restore(skip)
            from_partial = True
        else:
            restored = ckptr.restore(os.path.join(path, "state"), target)
    except (ValueError, TypeError):
        if getattr(engine, "_host_opt", None) is None:
            raise
        # offload engine restoring a non-offload checkpoint: the saved
        # tree has real opt_state arrays while our target has {} — restore
        # everything except opt_state and keep the host masters path below
        restored = _partial_restore(skip | {"opt_state"})
        from_partial = True

    return _finish_restore(
        engine, path, meta, restored, from_partial=from_partial, skip=skip,
        load_optimizer_states=load_optimizer_states,
        load_lr_scheduler_states=load_lr_scheduler_states,
        load_module_only=load_module_only,
    )


def _finish_restore(
    engine,
    path: str,
    meta: Dict[str, Any],
    restored: Dict[str, Any],
    from_partial: bool,
    skip: set,
    load_optimizer_states: bool,
    load_lr_scheduler_states: bool,
    load_module_only: bool,
    full_put: bool = False,
) -> Tuple[str, Dict[str, Any]]:
    # checkpoint layout -> this engine's state layout (re-pad flat
    # leaves for the current mesh), then pin the state shardings
    restored = engine._from_portable_state(restored)
    if "grad_acc" in skip:
        # keep this engine's accumulator SHAPE but force it to zeros —
        # a restore mid-accumulation must not mix pending grads from the
        # pre-restore params into the restored run
        restored["grad_acc"] = (
            jax.jit(
                lambda t: jax.tree.map(jnp.zeros_like, t),
                out_shardings=engine._state_shardings["grad_acc"],
            )(engine.state["grad_acc"])
            if engine.state["grad_acc"]
            else {}
        )
    if "comm" in skip:
        # keep this engine's EF-residual SHAPE but start from zero (the
        # residual is a bias corrector, not training state — resetting
        # it is always safe)
        restored["comm"] = (
            jax.jit(
                lambda t: jax.tree.map(jnp.zeros_like, t),
                out_shardings=engine._state_shardings["comm"],
            )(engine.state["comm"])
            if engine.state.get("comm")
            else {}
        )
    if engine._flat_plan or full_put:
        restored = jax.device_put(restored, engine._state_shardings)
    elif from_partial:
        restored["params"] = jax.device_put(restored["params"], engine._state_shardings["params"])

    if load_module_only or not load_optimizer_states:
        engine.state["params"] = restored["params"]
        if not load_module_only:
            for key in ("micro_step", "global_step", "global_samples", "loss_scale", "rng"):
                engine.state[key] = restored[key]
    else:
        engine.state = restored

    if getattr(engine, "_host_opt", None) is not None:
        # restores per-shard npz when allowed and present; otherwise
        # rebuilds fp32 masters from the restored (compute-dtype) params
        engine._load_host_optimizer(
            path, restored["params"], use_files=load_optimizer_states and not load_module_only
        )

    client_state: Dict[str, Any] = {}
    if meta:
        client_state = meta.get("client_state", {})
        engine.skipped_steps = meta.get("skipped_steps", 0)
        if load_lr_scheduler_states and engine.client_lr_scheduler is not None and hasattr(engine.client_lr_scheduler, "load_state_dict"):
            sd = client_state.get("__lr_scheduler__")
            if sd:
                engine.client_lr_scheduler.load_state_dict(sd)
    # resume-cursor: hand the loader its saved epoch/batch position so a
    # restarted job neither replays nor skips batches
    _restore_loader_state(engine, client_state)
    # reconcile the engine's host-side step mirrors with the restored state
    engine._host_global_step = int(jax.device_get(engine.state["global_step"]))
    engine._host_micro_step = int(jax.device_get(engine.state["micro_step"]))
    _note_ckpt_dir(engine, os.path.dirname(path))
    san = _sanitizer(engine)
    if san is not None:
        # a restore is the classic sharding-drift injection point: orbax
        # reshards to the abstract target, but any partial/fallback path
        # that leaves a leaf placed differently than declared is caught
        # here, not N steps later as a silent reshard collective
        san.drift.check_state(engine, label="checkpoint.load", step=engine._host_global_step)
    log_dist(f"loaded checkpoint {path} (global_step={engine._host_global_step})")
    return path, client_state


def consolidate_fp32_state_dict(engine) -> Dict[str, np.ndarray]:
    """Gather full (unsharded) fp32 params on host — the
    ``zero_to_fp32.py`` / ``_zero3_consolidated_fp16_state_dict``
    (engine.py:2039) analog.  Works for any ZeRO stage because params are
    logical arrays; this is just a device->host gather."""
    flat = {}

    def visit(path, leaf):
        arr = np.asarray(jax.device_get(leaf)).astype(np.float32)
        from deepspeed_tpu.runtime.zero.stages import _path_str

        flat[_path_str(path)] = arr

    jax.tree_util.tree_map_with_path(visit, engine._unflatten_state_leaves(engine.state["params"]))
    return flat


def _version() -> str:
    from deepspeed_tpu.version import __version__

    return __version__
