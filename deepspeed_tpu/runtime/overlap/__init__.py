"""Overlap subsystem: keep the accelerator busy while the host moves bytes.

The reference DeepSpeed hides host work behind device compute (pinned-
memory input pipelines, overlapped collectives, background NVMe swaps in
ZeRO-Infinity).  This package is the TPU-native expression of the same
principle, attacking the two biggest host-side stalls of a JAX training
loop plus the instrumentation to prove it:

* :mod:`~deepspeed_tpu.runtime.overlap.prefetch` —
  :class:`DevicePrefetcher`, a two-stage (load / sharded ``device_put``)
  pipelined input prefetcher (``engine.prefetch_loader`` routes here);
* :mod:`~deepspeed_tpu.runtime.overlap.async_writer` —
  :class:`AsyncCheckpointWriter`, background stage->manifest->rename
  checkpoint commits with drain semantics (``overlap.async_checkpoint``
  config block; durability contract unchanged from docs/resilience.md);
* :mod:`~deepspeed_tpu.runtime.overlap.timeline` —
  :class:`StepTimeline`, honest (fenced) per-step attribution of wall
  time to ``data_wait`` / ``compute`` / ``ckpt_stall`` / ``compile`` /
  ``other``, exported through ``bench.py`` and ``ds_report``;
* :mod:`~deepspeed_tpu.runtime.overlap.worker` —
  :class:`BoundedWorker`, the shared bounded-queue background thread
  (serving KV tier migration rides on it; see
  ``deepspeed_tpu/serving/kvcache/tiers.py``).

See ``docs/performance.md`` for the architecture and the config knobs.
"""
from deepspeed_tpu.runtime.overlap.async_writer import (  # noqa: F401
    AsyncCheckpointWriter,
    PendingSave,
)
from deepspeed_tpu.runtime.overlap.prefetch import (  # noqa: F401
    DevicePrefetcher,
    InlineLoader,
    inline_loader,
)
from deepspeed_tpu.runtime.overlap.timeline import PHASES, StepTimeline  # noqa: F401
from deepspeed_tpu.runtime.overlap.worker import BoundedWorker  # noqa: F401
