"""Background checkpoint commits: training resumes while bytes hit disk.

A synchronous atomic save (PR 2's stage -> manifest -> rename protocol)
stalls training for the full serialize+hash+fsync — seconds to minutes
at scale.  The async path splits the save at the only point that needs
the device state to hold still:

1. **snapshot** (caller thread, the only stall): device state is copied
   to host (``copy_to_host_async`` fan-out, then ``device_get``) at the
   step boundary — after this, training may donate/overwrite the device
   buffers freely;
2. **commit** (background thread): the snapshot runs the *unchanged*
   stage -> meta -> manifest -> rename protocol against the checkpoint
   tree, so every durability property proven by the PR 2 fault-injection
   harness holds for async saves too — a kill mid-commit leaves the
   previous tree plus a ``.tmp`` staging dir, never a loadable-but-
   corrupt tag.

One save is in flight at a time: a second save request **drains** the
in-flight one first (so tags commit in submission order and the staging
registry in ``resilience.manager`` never sees two owners of one dir).
The preemption watchdog drains synchronously before its emergency save,
keeping the exit-43 => committed-checkpoint contract intact.

A failed background commit is logged and surfaced on the next
:meth:`drain` (``PendingSave.error``); it never takes down the training
thread — the durability model is "the previous tag survives", same as a
crash at that instruction would have left.
"""
from __future__ import annotations

import atexit
import threading
import time
from typing import Callable, Optional

from deepspeed_tpu.utils.logging import logger


class PendingSave:
    """Handle for one in-flight (or finished) background save."""

    def __init__(self, tag: str, final_path: str):
        self.tag = tag
        self.final_path = final_path
        self.started_at = time.monotonic()
        self.error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def ok(self) -> bool:
        return self.done and self.error is None

    @property
    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join the background commit; True if it finished (ok or not)
        within ``timeout``."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()


class AsyncCheckpointWriter:
    """Serializes background saves: at most one in flight, drained in
    submission order."""

    def __init__(self, drain_timeout_seconds: float = 300.0):
        self.drain_timeout_seconds = float(drain_timeout_seconds)
        self._lock = threading.Lock()
        self._pending: Optional[PendingSave] = None
        self._atexit_registered = False
        self.last_error: Optional[BaseException] = None
        self.completed = 0
        self.failed = 0

    def _register_exit_drain(self) -> None:
        """A script whose last act is a save must not lose it.  The
        commit runs orbax, which schedules onto ThreadPoolExecutors —
        and ``concurrent.futures`` disables ALL executors from its own
        threading-atexit hook at the very start of interpreter shutdown.
        Threading-atexit callbacks run in reverse registration order, so
        registering the drain here (long after concurrent.futures
        imported) runs it BEFORE executors are disabled; plain
        ``atexit`` would be too late (observed:
        "cannot schedule new futures after interpreter shutdown")."""
        register = getattr(threading, "_register_atexit", None)
        if register is not None:
            register(self._exit_drain)
        else:  # pragma: no cover - future-python fallback, best effort
            atexit.register(self._exit_drain)

    def _exit_drain(self) -> None:
        try:
            if self.in_flight:
                logger.warning("draining in-flight async checkpoint at interpreter exit")
            self.drain()  # also surfaces a finished-but-failed commit
        except BaseException as e:  # noqa: BLE001 — exit path must not throw
            logger.error(f"async checkpoint drain at exit failed: {e!r}")

    @property
    def in_flight(self) -> bool:
        with self._lock:
            p = self._pending
        return p is not None and not p.done

    def _settle_locked(self, pending: "PendingSave") -> None:
        """Account one finished save.  Caller holds ``self._lock`` and
        owns the ``_pending -> None`` (or replace) transition, so each
        save hits completed/failed exactly once."""
        if pending.error is not None:
            self.failed += 1
            self.last_error = pending.error
        else:
            self.completed += 1

    def submit(self, tag: str, final_path: str, commit_fn: Callable[[], None]) -> PendingSave:
        """Start ``commit_fn`` on a background thread.  The caller must
        :meth:`drain` first — two concurrent saves would race the
        checkpoint tree's staging/latest/GC state."""
        settled: Optional[PendingSave] = None
        with self._lock:
            if self._pending is not None and not self._pending.done:
                raise RuntimeError(
                    f"async save of '{self._pending.tag}' still in flight; drain() first"
                )
            if self._pending is not None:
                # finished but nobody drained it (a concurrent drain read
                # the handle, then lost the transition to us) — settle it
                # here or the save is never counted
                settled = self._pending
                self._settle_locked(settled)
                self._pending = None
            pending = PendingSave(tag, final_path)

            def run():
                # the background commit is a first-class trace span
                # (docs/telemetry.md: checkpoint-writer track) and an
                # async-saves counter; no-ops when the plane is off
                from deepspeed_tpu.telemetry import PID_CHECKPOINT, get_registry, get_tracer

                tracer = get_tracer()
                t0 = tracer.now()
                try:
                    commit_fn()
                except BaseException as e:  # noqa: BLE001 — surfaced via drain()
                    pending.error = e
                finally:
                    tracer.add_span(
                        "ckpt_commit", "checkpoint", t0, tracer.now(),
                        pid=PID_CHECKPOINT,
                        args={"tag": tag, "ok": pending.error is None},
                    )
                    reg = get_registry()
                    if reg.enabled:
                        reg.counter(
                            "ckpt/async_saves",
                            outcome="ok" if pending.error is None else "failed",
                        ).inc()

            if not self._atexit_registered:
                self._register_exit_drain()
                self._atexit_registered = True
            t = threading.Thread(target=run, daemon=True, name=f"ds-async-ckpt-{tag}")
            pending._thread = t
            self._pending = pending
            t.start()
        if settled is not None and settled.error is not None:
            logger.error(
                f"async checkpoint save of '{settled.tag}' failed: {settled.error!r} "
                "(the previously committed tag is still the durable state)"
            )
        return pending

    def drain(self, timeout: Optional[float] = None) -> Optional[PendingSave]:  # ds-race: entry
        """Wait for the in-flight save (if any) to finish and return its
        handle.  Raises ``TimeoutError`` if it does not finish within
        ``timeout`` (default: ``drain_timeout_seconds``) — callers on an
        exit path treat that as "not saved".  A failed commit is logged
        and recorded (``last_error``) but NOT re-raised: the previous
        tag is still the durable state, and the caller's next save
        proceeds fresh."""
        with self._lock:
            pending = self._pending
        if pending is None:
            return None
        timeout = self.drain_timeout_seconds if timeout is None else float(timeout)
        if not pending.wait(timeout):
            raise TimeoutError(
                f"async save of '{pending.tag}' did not finish within {timeout:.0f}s"
            )
        # The trainer and the preemption watchdog can drain the same
        # handle concurrently; whichever thread wins the None-out
        # transition owns the accounting, so completed/failed count each
        # save exactly once.
        accounted = False
        with self._lock:
            if self._pending is pending:
                self._pending = None
                accounted = True
                self._settle_locked(pending)
        if accounted and pending.error is not None:
            logger.error(
                f"async checkpoint save of '{pending.tag}' failed: {pending.error!r} "
                "(the previously committed tag is still the durable state)"
            )
        return pending
