"""Step-phase timeline: honest per-step wall-time attribution.

Every training step's wall time is split into named phases:

* ``data_wait``  — host blocked waiting for an input batch (loader pull,
  stacking, ``device_put`` transfer, prefetch-queue wait);
* ``compute``    — dispatch of the compiled step until its outputs are
  ready, recorded ONLY when the engine fences it with
  ``jax.block_until_ready`` (``overlap.timeline.fence``, defaulting to
  the ``wall_clock_breakdown`` opt-in) — XLA dispatch is asynchronous,
  so an unfenced delta only measures Python overhead (the ds_lint
  ``unfenced-timing`` rule) and a per-step fence costs the round trip
  ThroughputTimer deliberately avoids off report steps;
* ``ckpt_stall`` — time training was stalled on checkpoint I/O (the
  full save for synchronous saves; snapshot+submit for async saves);
* ``compile``    — building a new executable (trace+lower+compile);
* ``other``      — whatever remains of the step wall (host bookkeeping,
  logging, monitor flushes).

Notes accumulate into a *pending* record; :meth:`end_step` closes it
against the wall clock since the previous step boundary, so host work
that happens between steps (e.g. a checkpoint save between two
``train_batch`` calls) is attributed to the step that paid for it.

The timeline itself is pure host bookkeeping (two ``perf_counter``
reads and a dict update per note): enabled without the fence it does
not change the hot path and still attributes every host-measurable
phase; the per-step device fence is the engine's (opt-in) choice.
"""
from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, List, Optional

PHASES = ("data_wait", "compute", "ckpt_stall", "compile", "other")


class StepTimeline:
    """Rolling per-step phase attribution over the last ``window`` steps.

    ``phases`` customizes the attributed phase names (the serving engine
    uses ``prefill/decode/sched``); ``other`` is always present as the
    unattributed remainder.  :meth:`set_gauge` records per-step levels
    (e.g. queue depth) that are averaged — not ms-scaled — in
    :meth:`summary`."""

    def __init__(self, enabled: bool = True, window: int = 512, phases=None):
        self.enabled = bool(enabled)
        self.window = max(1, int(window))
        self.phases = tuple(phases) if phases is not None else PHASES
        if "other" not in self.phases:
            self.phases = self.phases + ("other",)
        self.records: Deque[Dict[str, float]] = deque(maxlen=self.window)
        self.total_steps = 0
        self._pending: Dict[str, float] = {}
        self._pending_gauges: Dict[str, float] = {}
        self._gauge_names: set = set()
        self._last_boundary: Optional[float] = None
        # comm metadata (docs/comm.md): the active gradient-exchange
        # strategy and its modeled bytes/step — static per engine, set
        # once by the comm layer, carried into every summary/record
        self.comm_strategy: Optional[str] = None
        self.comm_bytes: Optional[int] = None
        # telemetry plane attachment (docs/telemetry.md): None-checked
        # on the hot path; when attached, phases become Chrome-trace
        # spans and closed step records publish into the registry
        self._telemetry = None
        self._t_prefix = "train"
        self._trace_pid = 0

    def attach_telemetry(self, manager, prefix: str = "train", trace_pid: int = 0) -> None:
        """Route this timeline into a
        :class:`~deepspeed_tpu.telemetry.TelemetryManager`: every
        ``phase()`` block also lands as a span (when tracing is armed)
        and every ``end_step`` publishes the closed record as
        histograms/gauges.  Detach with ``manager=None``."""
        self._telemetry = manager
        self._t_prefix = prefix
        self._trace_pid = int(trace_pid)

    def set_comm(self, strategy: str, bytes_per_step: int) -> None:
        """Record the engine's active comm strategy + per-step
        grad-exchange bytes model (not gated on ``enabled`` — metadata,
        not a timed phase)."""
        self.comm_strategy = str(strategy)
        self.comm_bytes = int(bytes_per_step)

    # -- recording --------------------------------------------------------
    def note(self, phase: str, seconds: float) -> None:
        """Accumulate ``seconds`` of ``phase`` into the pending step."""
        if not self.enabled:
            return
        self._pending[phase] = self._pending.get(phase, 0.0) + float(seconds)

    @contextmanager
    def phase(self, name: str):
        """Time a host block and note it under ``name`` (and as a trace
        span when the attached telemetry plane has tracing armed)."""
        if not self.enabled:
            yield
            return
        tm = self._telemetry
        tracer = tm.tracer if tm is not None and tm.tracer.enabled else None
        t0m = tracer.now() if tracer is not None else 0.0
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.note(name, dt)
            if tracer is not None:
                tracer.add_span(
                    f"{self._t_prefix}/{name}", self._t_prefix, t0m, t0m + dt,
                    pid=self._trace_pid,
                )

    def set_gauge(self, name: str, value: float) -> None:
        """Record a per-step level (queue depth, live slots, ...): kept
        as-is in the step record and reported as a window mean, not a
        millisecond phase."""
        if not self.enabled:
            return
        self._pending_gauges[name] = float(value)
        self._gauge_names.add(name)

    def end_step(self, count: int = 1) -> None:
        """Close the pending record against the wall clock.  ``count > 1``
        spreads the window evenly over ``count`` steps (one compiled
        multi-step run, e.g. ``train_batches``)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        if self._last_boundary is None:
            # first boundary: no previous anchor, the wall is whatever
            # was explicitly noted (avoids charging engine build time
            # to step 1's "other")
            wall = sum(self._pending.values())
        else:
            wall = now - self._last_boundary
        self._last_boundary = now
        noted = sum(self._pending.values())
        other = max(0.0, wall - noted)
        count = max(1, int(count))
        rec = {p: self._pending.get(p, 0.0) / count for p in self.phases if p != "other"}
        rec["other"] = (self._pending.get("other", 0.0) + other) / count
        rec["wall"] = max(wall, noted) / count
        rec.update(self._pending_gauges)
        for _ in range(count):
            self.records.append(dict(rec))
        self.total_steps += count
        if self._telemetry is not None:
            # registry publish of the closed record (host dict ops; the
            # manager also derives the live MFU gauge from the wall)
            self._telemetry.publish_step(
                self._t_prefix, rec, count=count, gauge_names=self._gauge_names
            )
        self._pending = {}
        self._pending_gauges = {}

    def reset_window(self) -> None:
        """Drop recorded steps (keep the wall anchor); the next
        ``summary()`` covers only steps recorded after this call."""
        self.records.clear()

    # -- reporting --------------------------------------------------------
    def summary(self, last_n: Optional[int] = None) -> Dict[str, float]:
        """Mean per-step milliseconds per phase over the last ``last_n``
        recorded steps (default: the whole window), plus ``steps_per_s``
        derived from the mean step wall."""
        recs: List[Dict[str, float]] = list(self.records)
        if last_n is not None:
            recs = recs[-int(last_n):]
        out = {f"{p}_ms": 0.0 for p in self.phases}
        out["wall_ms"] = 0.0
        out["steps"] = len(recs)
        out["steps_per_s"] = 0.0
        for g in sorted(self._gauge_names):
            out[g] = 0.0
        if self.comm_strategy is not None:
            out["comm_strategy"] = self.comm_strategy
            out["comm_bytes_per_step"] = self.comm_bytes
        if not recs:
            return out
        n = len(recs)
        for p in self.phases:
            out[f"{p}_ms"] = round(sum(r.get(p, 0.0) for r in recs) * 1000.0 / n, 3)
        for g in sorted(self._gauge_names):
            out[g] = round(sum(r.get(g, 0.0) for r in recs) / n, 3)
        wall = sum(r.get("wall", 0.0) for r in recs) / n
        out["wall_ms"] = round(wall * 1000.0, 3)
        out["steps_per_s"] = round(1.0 / wall, 3) if wall > 0 else 0.0
        return out

    def format_summary(self, last_n: Optional[int] = None) -> str:
        """One log line: phase means and their share of the step wall."""
        s = self.summary(last_n)
        if not s["steps"]:
            return "step timeline: no steps recorded"
        wall = max(s["wall_ms"], 1e-9)
        parts = [
            f"{p}: {s[f'{p}_ms']:.1f}ms ({100.0 * s[f'{p}_ms'] / wall:.0f}%)"
            for p in self.phases
            if s[f"{p}_ms"] > 0.0 or p in ("data_wait", "compute")
        ]
        parts += [f"{g}: {s[g]:.1f}" for g in sorted(self._gauge_names)]
        comm = ""
        if s.get("comm_strategy"):
            comm = (
                f" | comm: {s['comm_strategy']}"
                f" ({s.get('comm_bytes_per_step', 0) / 1e6:.1f} MB/step grad exchange)"
            )
        return (
            f"step timeline over {s['steps']} step(s): wall {s['wall_ms']:.1f}ms "
            f"({s['steps_per_s']:.2f} steps/s) | " + " | ".join(parts) + comm
        )
