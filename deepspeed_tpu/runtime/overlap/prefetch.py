"""Sharding-aware double-buffered input prefetch.

The engine's compiled step dispatches asynchronously; what serializes a
training loop is the host work per batch — pulling the next batch out of
the loader (tokenization, disk reads) and ``jax.device_put`` of it with
the engine's batch sharding (a synchronous host RPC on remote/tunneled
TPU backends).  :class:`DevicePrefetcher` runs both ahead of the
consumer as a two-stage pipeline:

    loader thread:  ``next(loader)``      -> bounded queue (depth N)
    place  thread:  ``place_fn(batch)``   -> bounded queue (depth N)
    consumer:       pops device-resident batches; the jitted step never
                    waits on host transfer while the pipeline keeps up

Each stage is backpressured by its queue (``depth`` batches in flight
per stage), so host memory is bounded at ``~2*depth`` batches.  The
consumer-side queue wait — the time the accelerator would have idled on
input — is reported to the engine's ``StepTimeline`` as ``data_wait``.

Exceptions raised by the loader or the placement function are re-raised
in the consumer at the position they occurred; iteration order is
preserved exactly.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional

from deepspeed_tpu.runtime.dataloader import ResumableWrapperMixin


class _End:
    """Sentinel: the upstream stage is exhausted."""


class _Raised:
    """Sentinel wrapper: the upstream stage raised."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


_ABORT = object()  # returned by _get when the pipeline is being closed


def _put(q: "queue.Queue", item: Any, stop: threading.Event) -> bool:
    """Blocking put that aborts when ``stop`` is set, so a worker blocked
    on a full queue can never outlive :meth:`DevicePrefetcher.close`."""
    while True:
        try:
            q.put(item, timeout=0.05)
            return True
        except queue.Full:
            if stop.is_set():
                return False


def _get(q: "queue.Queue", stop: threading.Event) -> Any:
    """Blocking get with the same abort contract as :func:`_put`."""
    while True:
        try:
            return q.get(timeout=0.05)
        except queue.Empty:
            if stop.is_set():
                return _ABORT


def _load_worker(it, out_q: "queue.Queue", stop: threading.Event) -> None:
    while not stop.is_set():
        try:
            item = next(it)
        except StopIteration:
            item = _End()
        except BaseException as e:  # noqa: BLE001 — re-raised in the consumer
            item = _Raised(e)
        if not _put(out_q, item, stop):
            return
        if isinstance(item, (_End, _Raised)):
            return


def _place_worker(place: Callable[[Any], Any], in_q: "queue.Queue", out_q: "queue.Queue", stop: threading.Event) -> None:
    while not stop.is_set():
        item = _get(in_q, stop)
        if item is _ABORT:
            return
        if not isinstance(item, (_End, _Raised)):
            try:
                item = place(item)
            except BaseException as e:  # noqa: BLE001 — re-raised in the consumer
                item = _Raised(e)
        if not _put(out_q, item, stop):
            return
        if isinstance(item, (_End, _Raised)):
            return


class DevicePrefetcher(ResumableWrapperMixin):
    """Wraps a host batch iterator with pipelined load + device placement.

    ``place_fn``: host batch -> device-resident batch (the engine passes
    its stack-micro-batches + sharded ``device_put``); when omitted,
    ``sharding`` (a pytree of shardings, or None for default placement)
    drives a plain ``jax.device_put``.

    ``depth``: batches in flight per stage (2 = double buffering).

    ``timeline``: optional ``StepTimeline``; consumer-side queue waits
    are noted as ``data_wait``.

    ``sanitizer``: optional ds_san :class:`Sanitizer`; the place stage
    then runs under its transfer guard (region ``prefetch.place``), so a
    loader that smuggles implicit host↔device transfers into the
    pipeline is attributed instead of silently re-staging every batch.
    Violations re-raise in the consumer like any other place error.
    """

    def __init__(
        self,
        loader: Iterable,
        depth: int = 2,
        place_fn: Optional[Callable[[Any], Any]] = None,
        sharding: Any = None,
        timeline: Any = None,
        sanitizer: Any = None,
    ):
        self.loader = loader
        self.depth = max(1, int(depth))
        self.sharding = sharding
        self.place_fn = place_fn
        self.timeline = timeline
        self.sanitizer = sanitizer
        self._stop: Optional[threading.Event] = None
        self._threads: List[threading.Thread] = []

    def _place_inner(self, batch: Any) -> Any:
        if self.place_fn is not None:
            return self.place_fn(batch)
        import jax

        if self.sharding is not None:
            return jax.device_put(batch, self.sharding)
        return jax.device_put(batch)

    def _place(self, batch: Any) -> Any:
        if self.sanitizer is None:
            return self._place_inner(batch)
        # jax's transfer-guard context is thread-local, so arming it on
        # the place worker cannot leak into the consumer's own guards
        with self.sanitizer.transfer.guard("prefetch.place"):
            return self._place_inner(batch)

    def __iter__(self):
        self.close()  # a fresh iteration owns fresh threads/queues
        it = iter(self.loader)
        self._capture_base()
        stop = threading.Event()
        self._stop = stop
        loaded: "queue.Queue" = queue.Queue(maxsize=self.depth)
        placed: "queue.Queue" = queue.Queue(maxsize=self.depth)
        threads = [
            threading.Thread(
                target=_load_worker, args=(it, loaded, stop),
                daemon=True, name="ds-prefetch-load",
            ),
            threading.Thread(
                target=_place_worker, args=(self._place, loaded, placed, stop),
                daemon=True, name="ds-prefetch-place",
            ),
        ]
        self._threads = threads
        for t in threads:
            t.start()
        return self._consume(placed)

    def _consume(self, placed: "queue.Queue"):
        try:
            while True:
                t0 = time.perf_counter()
                item = placed.get()
                if self.timeline is not None:
                    self.timeline.note("data_wait", time.perf_counter() - t0)
                if isinstance(item, _End):
                    return
                if isinstance(item, _Raised):
                    raise item.exc
                self._served += 1
                yield item
        finally:
            self.close()

    def close(self) -> None:
        """Stop the pipeline threads (idempotent; runs automatically when
        iteration ends or the consumer breaks out)."""
        if self._stop is not None:
            self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
        self._stop = None

    def __len__(self):
        try:
            return len(self.loader)
        except TypeError:
            raise TypeError("wrapped loader is a generator with no len()") from None


class InlineLoader:
    """The unoverlapped fallback (``overlap.prefetch.enabled = false``):
    same interface as :class:`DevicePrefetcher` — re-iterable, with
    ``__len__`` — but synchronous load + place on the consumer thread,
    so swapping the knob never changes iteration semantics."""

    def __init__(
        self,
        loader: Iterable,
        place_fn: Callable[[Any], Any],
        timeline: Any = None,
        sanitizer: Any = None,
    ):
        self.loader = loader
        self.place_fn = place_fn
        self.timeline = timeline
        if sanitizer is not None:
            self.place_fn = sanitizer.transfer.wrap_callable(place_fn, "prefetch.place")

    def state_dict(self) -> Optional[dict]:
        # synchronous wrap: the inner cursor tracks consumption exactly
        fn = getattr(self.loader, "state_dict", None)
        return dict(fn()) if fn is not None else None

    def load_state_dict(self, sd: dict) -> None:
        fn = getattr(self.loader, "load_state_dict", None)
        if fn is not None:
            fn(sd)

    def __iter__(self):
        it = iter(self.loader)
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            out = self.place_fn(batch)
            if self.timeline is not None:
                self.timeline.note("data_wait", time.perf_counter() - t0)
            yield out

    def __len__(self):
        try:
            return len(self.loader)
        except TypeError:
            raise TypeError("wrapped loader is a generator with no len()") from None


def inline_loader(loader: Iterable, place_fn: Callable[[Any], Any], timeline: Any = None):
    """Back-compat alias for :class:`InlineLoader`."""
    return InlineLoader(loader, place_fn, timeline=timeline)
