"""Shared bounded-queue background worker.

A single daemon thread draining a bounded FIFO of host-side jobs.  The
checkpoint writer and device prefetcher each grew their own ad-hoc
thread + queue; the KV tier manager needs the same shape (slow disk IO
hidden under engine compute), so the pattern lives here once.

Contract:

- ``submit`` enqueues a callable; it never blocks the caller beyond the
  bounded-queue backpressure (``block=False`` returns ``False`` when the
  queue is full so callers can retry on their next tick).
- Jobs run strictly in submission order on one thread — callers rely on
  this for write-after-write ordering onto disk.
- Job exceptions never kill the thread: they are counted, remembered
  (``last_error``) and re-surfaced to the owner via ``errors()`` which
  drains the pending-error list.  A job raising is an abnormal event for
  tier migration (the entry simply stays in its current tier), not a
  crash.
- ``drain`` blocks until every job submitted so far has finished — used
  by tests and by engine shutdown to make background state durable.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["BoundedWorker"]

_POLL_S = 0.05


class BoundedWorker:
    """One daemon thread executing submitted thunks in FIFO order."""

    def __init__(self, name: str = "ds-worker", depth: int = 32) -> None:
        if depth < 1:
            raise ValueError(f"worker depth must be >= 1, got {depth}")
        self.name = name
        self._q: "queue.Queue[Optional[Tuple[str, Callable[[], Any]]]]" = (
            queue.Queue(maxsize=depth))
        self._stop = threading.Event()
        self._busy = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._errors: List[Tuple[str, BaseException]] = []
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.last_error: Optional[BaseException] = None

    # -- lifecycle ---------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name=self.name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            if item is None:
                self._q.task_done()
                break
            label, fn = item
            self._busy.set()
            try:
                fn()
                with self._lock:
                    self.completed += 1
            except BaseException as exc:  # noqa: BLE001 - surfaced to owner
                with self._lock:
                    self.failed += 1
                    self.last_error = exc
                    self._errors.append((label, exc))
            finally:
                self._busy.clear()
                self._q.task_done()

    # -- API ---------------------------------------------------------

    def submit(self, fn: Callable[[], Any], label: str = "",
               block: bool = False) -> bool:
        """Enqueue ``fn``; returns False when full (``block=False``) or
        after ``close``."""
        if self._stop.is_set():
            return False
        self._ensure_thread()
        try:
            if block:
                while not self._stop.is_set():
                    try:
                        self._q.put((label, fn), timeout=_POLL_S)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return False
            else:
                self._q.put_nowait((label, fn))
        except queue.Full:
            return False
        with self._lock:
            self.submitted += 1
        return True

    def pending(self) -> int:
        """Queued-but-unstarted jobs plus the in-flight one (if any)."""
        return self._q.qsize() + (1 if self._busy.is_set() else 0)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until everything submitted so far has run.

        Returns False on timeout (work may still be in flight)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self.pending() > 0:
            if self._thread is None or not self._thread.is_alive():
                return self.pending() == 0
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.001)
        return True

    def errors(self) -> List[Tuple[str, BaseException]]:
        """Drain and return (label, exception) pairs from failed jobs."""
        with self._lock:
            out, self._errors = self._errors, []
        return out

    def close(self, timeout: float = 2.0) -> None:
        """Stop accepting work and join the thread (idempotent)."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout)

    def stats(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "failed": self.failed,
                "pending": self.pending(),
            }
