"""CSR sparse tensor (sparse embedding gradients).

Reference: ``runtime/csr_tensor.py`` (``CSRTensor`` :11) + the engine's
sparse-gradient path (``engine.py:199-205``, ``csr_allreduce`` :1559):
``nn.Embedding`` gradients are converted to CSR before the DP allreduce
so only touched rows move over the wire.

TPU note: inside the compiled step, embedding grads are produced by XLA
scatter ops and reduced with ``psum`` — XLA already exploits the
scatter structure, and dynamic-nnz tensors can't live under jit (static
shapes).  This class therefore serves the *host-side* uses: compressed
checkpoint/state shipping and host-side gradient exchange for the
offload path, matching the reference's API shape (``sparse_size``,
``to_dense``, add/scale ops).
"""
from __future__ import annotations

from typing import Tuple

import numpy as np


class CSRTensor:
    def __init__(self, values: np.ndarray, indices: np.ndarray, dense_shape: Tuple[int, int]):
        """``values``: (nnz_rows, ncols) — row-sparse layout (embedding
        grads are row-sparse); ``indices``: (nnz_rows,) row ids."""
        self.values = np.asarray(values)
        self.indices = np.asarray(indices, np.int64)
        self.dense_shape = tuple(dense_shape)

    @classmethod
    def from_dense(cls, dense: np.ndarray, tol: float = 0.0) -> "CSRTensor":
        dense = np.asarray(dense)
        assert dense.ndim == 2, "CSRTensor is row-sparse over 2-D tensors"
        nonzero = np.where(np.abs(dense).max(axis=1) > tol)[0]
        return cls(dense[nonzero], nonzero, dense.shape)

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_shape, self.values.dtype)
        out[self.indices] = self.values
        return out

    def sparse_size(self) -> int:
        """Elements actually stored (reference ``sparse_size``)."""
        return int(self.values.size + self.indices.size)

    @property
    def density(self) -> float:
        return self.values.shape[0] / max(1, self.dense_shape[0])

    def scale(self, factor: float) -> "CSRTensor":
        return CSRTensor(self.values * factor, self.indices, self.dense_shape)

    def add(self, other: "CSRTensor") -> "CSRTensor":
        assert self.dense_shape == other.dense_shape
        rows = np.union1d(self.indices, other.indices)
        vals = np.zeros((len(rows), self.dense_shape[1]), np.result_type(self.values, other.values))
        # vectorized scatter-add per operand (rows is sorted by union1d)
        np.add.at(vals, np.searchsorted(rows, self.indices), self.values)
        np.add.at(vals, np.searchsorted(rows, other.indices), other.values)
        return CSRTensor(vals, rows, self.dense_shape)


def csr_allreduce_host(csr: CSRTensor, all_csrs) -> CSRTensor:
    """Host-side allreduce of row-sparse grads (reference
    ``csr_allreduce``): union of rows, summed values."""
    out = csr
    for other in all_csrs:
        if other is not csr:
            out = out.add(other)
    return out
