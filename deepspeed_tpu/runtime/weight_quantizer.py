"""Post-training weight quantization for inference (MoQ serving path).

Reference: ``runtime/weight_quantizer.py`` (``WeightQuantization`` :5) and
``module_inject/module_quantize.py`` (``quantize_transformer_layer``) —
grouped symmetric int8 quantization of transformer weights applied while
building the inference engine.

TPU-native form: quantize-dequantize is a jittable elementwise transform;
serving true-int8 matmuls is a Pallas-kernel optimization on top of the
same grouped scales (``ops/quantizer`` holds the kernels).  Here we store
either (a) dequantized bf16 weights (simulated quantization — numerics
identical to the reference's dequantized path) or (b) the packed
int8+scales pair for kernels that consume them.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class WeightQuantization:
    def __init__(self, bits: int = 8, groups: int = 1, mlp_extra_grouping: bool = False):
        if bits not in (4, 8):
            raise ValueError(f"bits must be 4 or 8, got {bits}")
        self.bits = bits
        self.groups = max(1, int(groups))
        self.mlp_extra_grouping = mlp_extra_grouping

    # -- core grouped symmetric quantizer ---------------------------------
    def quantize(self, w: np.ndarray, groups: int = None) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (q int8, scales fp32).  Granularity: each *row* of the
        matrix (all leading dims flattened — so a stacked (L, in, out)
        weight quantizes per (layer, input-row), never across layers)
        split into ``groups`` column groups when divisible, else one scale
        per row.  Mirrors the reference's grouped sym path
        (``csrc/quantization/quantizer.cu``)."""
        groups = groups or self.groups
        w = np.asarray(w, np.float32)
        C = w.shape[-1]
        if C % groups != 0:
            groups = 1
        flat = w.reshape(-1, groups, C // groups)
        qmax = (1 << (self.bits - 1)) - 1
        scale = np.abs(flat).max(axis=2, keepdims=True) / qmax
        scale = np.where(scale == 0.0, 1.0, scale)
        q = np.clip(np.round(flat / scale), -qmax - 1, qmax).astype(np.int8)
        return q.reshape(w.shape), scale.astype(np.float32)

    def dequantize(self, q: np.ndarray, scale: np.ndarray) -> np.ndarray:
        rows, groups = scale.shape[0], scale.shape[1]
        return (q.astype(np.float32).reshape(rows, groups, -1) * scale).reshape(q.shape)

    def quantize_dequantize(self, w) -> np.ndarray:
        q, s = self.quantize(np.asarray(w))
        return self.dequantize(q, s)

    # -- tree-level application -------------------------------------------
    def _is_matmul_weight(self, name: str, shape) -> bool:
        return len(shape) >= 2 and name.endswith("_w")

    def quantize_dequantize_tree(self, params: Any) -> Any:
        """Simulated quantization over a parameter pytree: quantize every
        matmul weight, leave norms/biases/embedding tables' small tensors
        alone (reference quantizes qkvw/dense/mlp weights,
        ``module_quantize.py``)."""

        def visit(path, leaf):
            name = str(getattr(path[-1], "key", path[-1])) if path else ""
            arr = np.asarray(leaf)
            if self._is_matmul_weight(name, arr.shape) and "emb" not in name and name != "wte":
                groups = self.groups * (2 if self.mlp_extra_grouping and "fc" in name else 1)
                q, s = self.quantize(arr, groups=groups)
                return self.dequantize(q, s).astype(arr.dtype)
            return leaf

        return jax.tree_util.tree_map_with_path(visit, params)

    def quantize_tree_packed(self, params: Any) -> Dict[str, Any]:
        """True-int8 representation: {name: (q, scales)} for matmul
        weights (consumed by quantized-matmul kernels)."""
        packed = {}

        def visit(path, leaf):
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            arr = np.asarray(leaf)
            short = name.split("/")[-1]
            if self._is_matmul_weight(short, arr.shape) and "emb" not in short and short != "wte":
                packed[name] = self.quantize(arr)
            return leaf

        jax.tree_util.tree_map_with_path(visit, params)
        return packed


def quantize_transformer_layer(params: Any, bits: int = 8, groups: int = 1) -> Any:
    """Name-compat shim for ``module_inject/module_quantize.py``."""
    return WeightQuantization(bits=bits, groups=groups).quantize_dequantize_tree(params)


def pack_int8_tree(params: Any, donate: bool = False, mesh: Any = None) -> Any:
    """True-int8 packing for the serving path: every matmul weight
    (``*_w``, ndim>=2, non-embedding) becomes ``{"q": int8, "s": f32}``
    with per-output-channel scales (``ops/quantizer.quantize_per_channel``);
    the inference block computes ``(x @ q) * s`` so weights stay int8 in
    HBM — halving decode weight bandwidth vs bf16.  ``mesh`` scopes the
    pack trace (falls back to the mesh the params are already placed
    on, so GSPMD keeps their layout instead of guessing)."""
    from deepspeed_tpu.ops.quantizer.quantizer import quantize_per_channel
    from deepspeed_tpu.parallel.sequence import scoped_to

    def visit(path, leaf):
        name = str(getattr(path[-1], "key", path[-1])) if path else ""
        if np.ndim(leaf) >= 2 and name.endswith("_w") and "emb" not in name:
            q, s = quantize_per_channel(leaf)
            return {"q": q, "s": s}
        return leaf

    def pack(tree):
        return jax.tree_util.tree_map_with_path(
            visit, tree, is_leaf=lambda x: not isinstance(x, dict)
        )

    if any(isinstance(l, jax.Array) for l in jax.tree.leaves(params)):
        # device-resident params: one jitted pack over the whole tree
        # (per-leaf eager ops would pay a dispatch round trip each);
        # donate=True frees the full-precision originals as it goes —
        # only safe when the caller owns the tree (engine-created init)
        if mesh is None:
            for leaf in jax.tree.leaves(params):
                mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
                if mesh is not None:
                    break
        return jax.jit(scoped_to(mesh, pack), donate_argnums=0 if donate else ())(params)
    return jax.tree.map(np.asarray, pack(params))
