"""Async tensor swapping (ZeRO-Infinity building block).

Reference: ``runtime/swap_tensor/async_swapper.py`` (``AsyncTensorSwapper``
:16) — move tensors between accelerator/host memory and NVMe files using
the aio engine, overlapping I/O with compute.

Here tensors are host numpy arrays (the engine's host-offload path owns
device<->host movement); each logical tensor maps to one file in the
swap folder and swaps ride the native aio handle.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.ops.aio.aio import AioHandle
from deepspeed_tpu.utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, aio_handle: Optional[AioHandle] = None, aio_config=None):
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        if aio_handle is None:
            kw = {}
            if aio_config is not None:
                kw = dict(
                    block_size=aio_config.block_size,
                    queue_depth=aio_config.queue_depth,
                    single_submit=aio_config.single_submit,
                    overlap_events=aio_config.overlap_events,
                    thread_count=max(1, aio_config.thread_count),
                )
            aio_handle = AioHandle(**kw)
        self.aio = aio_handle
        # key -> (path, shape, dtype) for swapped-out tensors
        self._index: Dict[str, tuple] = {}
        self._pending = 0
        # buffers owned by in-flight async writes — the native engine
        # reads them from worker threads, so they must stay alive until
        # the next synchronize() (dropping the ref frees the memory mid-
        # write and corrupts the file)
        self._inflight_bufs: list = []

    def _path(self, key: str) -> str:
        safe = key.replace("/", "__")
        return os.path.join(self.swap_dir, f"{safe}.swp")

    def swap_out(self, key: str, array: np.ndarray, async_op: bool = True) -> None:
        """Write ``array`` to the swap file for ``key``.  With
        ``async_op`` the caller must not mutate ``array`` until
        ``synchronize()`` (aio reads the buffer in worker threads)."""
        arr = np.ascontiguousarray(array)
        path = self._path(key)
        self._index[key] = (path, arr.shape, arr.dtype)
        self._inflight_bufs.append(arr)
        self.aio.async_pwrite(arr, path)
        self._pending += 1
        if not async_op:
            self.synchronize()

    def swap_in(self, key: str, out: Optional[np.ndarray] = None, async_op: bool = True) -> np.ndarray:
        """Read ``key`` into ``out`` (allocated if None).  With
        ``async_op`` the data is valid only after ``synchronize()``."""
        if key not in self._index:
            raise KeyError(f"tensor '{key}' was never swapped out")
        path, shape, dtype = self._index[key]
        if out is None:
            out = np.empty(shape, dtype)
        assert out.nbytes == int(np.prod(shape)) * np.dtype(dtype).itemsize
        self.aio.async_pread(out, path)
        self._pending += 1
        if not async_op:
            self.synchronize()
        return out

    def synchronize(self) -> int:
        n = self.aio.wait()
        self._pending = 0
        self._inflight_bufs.clear()
        return n

    def release(self, key: str) -> None:
        info = self._index.pop(key, None)
        if info and os.path.exists(info[0]):
            os.unlink(info[0])

    @property
    def swapped_keys(self):
        return list(self._index)
