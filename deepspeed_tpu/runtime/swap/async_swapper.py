"""Async tensor swapping (ZeRO-Infinity building block).

Reference: ``runtime/swap_tensor/async_swapper.py`` (``AsyncTensorSwapper``
:16) — move tensors between accelerator/host memory and NVMe files using
the aio engine, overlapping I/O with compute.

Here tensors are host numpy arrays (the engine's host-offload path owns
device<->host movement); each logical tensor maps to one file in the
swap folder.  Reads ride one aio handle; writes ride a PER-KEY handle,
so a ``swap_in`` of a key whose write is still in flight waits for that
key's write ONLY — other keys' writes keep overlapping the caller's
compute (the reference's double-buffered pattern,
``pipelined_optimizer_swapper.py:60``).  An injected ``aio_handle``
serves every op (the injection contract: tuned settings / test fakes
observe all I/O) at the cost of bulk-granularity waits.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.ops.aio.aio import AioHandle
from deepspeed_tpu.utils.logging import logger


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, aio_handle: Optional[AioHandle] = None, aio_config=None):
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        kw = {}
        if aio_config is not None:
            kw = dict(
                block_size=aio_config.block_size,
                queue_depth=aio_config.queue_depth,
                single_submit=aio_config.single_submit,
                overlap_events=aio_config.overlap_events,
                thread_count=max(1, aio_config.thread_count),
            )
        self._handle_kw = kw
        self._injected = aio_handle is not None
        self.aio = aio_handle if aio_handle is not None else AioHandle(**kw)
        # writes ride a small FIXED pool of handles (keys hash to slots):
        # per-slot wait granularity keeps unrelated writes airborne while
        # bounding native aio contexts/threads regardless of key count.
        # NOTE the granularity is per-SLOT, not per-key: with more than
        # _WRITE_POOL concurrent writers a swap_in can wait on an
        # unrelated key's in-flight write that hashed to the same slot —
        # correctness is unaffected, overlap just degrades for
        # n_groups > _WRITE_POOL
        self._write_handles: Dict[int, AioHandle] = {}
        # key -> (path, shape, dtype) for swapped-out tensors
        self._index: Dict[str, tuple] = {}
        self._pending_reads = 0
        # key -> buffer owned by that key's in-flight async write — the
        # native engine reads it from worker threads, so it must stay
        # alive until the write completes (dropping the ref frees the
        # memory mid-write and corrupts the file)
        self._inflight_writes: Dict[str, np.ndarray] = {}

    def _path(self, key: str) -> str:
        safe = key.replace("/", "__")
        return os.path.join(self.swap_dir, f"{safe}.swp")

    _WRITE_POOL = 4

    def _slot(self, key: str) -> int:
        import zlib

        return zlib.crc32(key.encode()) % self._WRITE_POOL

    def _write_handle(self, key: str) -> AioHandle:
        if self._injected:
            return self.aio
        s = self._slot(key)
        h = self._write_handles.get(s)
        if h is None:
            h = self._write_handles[s] = AioHandle(**self._handle_kw)
        return h

    def swap_out(self, key: str, array: np.ndarray, async_op: bool = True) -> None:
        """Write ``array`` to the swap file for ``key``.  With
        ``async_op`` the swapper owns ``array`` until the write lands."""
        if key in self._inflight_writes:
            # never two in-flight writes against one file
            self.synchronize_writes(key)
        arr = np.ascontiguousarray(array)
        path = self._path(key)
        self._index[key] = (path, arr.shape, arr.dtype)
        self._inflight_writes[key] = arr
        self._write_handle(key).async_pwrite(arr, path)
        if not async_op:
            self.synchronize_writes(key)

    def swap_in(self, key: str, out: Optional[np.ndarray] = None, async_op: bool = True) -> np.ndarray:
        """Read ``key`` into ``out`` (allocated if None).  With
        ``async_op`` the data is valid only after ``synchronize()``."""
        if key not in self._index:
            raise KeyError(f"tensor '{key}' was never swapped out")
        if key in self._inflight_writes:
            # read-after-write: THIS key's bytes are still in flight;
            # other keys' writes stay airborne
            self.synchronize_writes(key)
        path, shape, dtype = self._index[key]
        if out is None:
            out = np.empty(shape, dtype)
        assert out.nbytes == int(np.prod(shape)) * np.dtype(dtype).itemsize
        self.aio.async_pread(out, path)
        self._pending_reads += 1
        if not async_op:
            self.synchronize()
        return out

    def synchronize_writes(self, key: Optional[str] = None) -> int:
        """Complete the in-flight write for ``key`` (all writes when
        None).  Waiting a key's pool slot completes every write on that
        slot — all such keys are cleared together."""
        if key is None:
            n = 0
            for k in list(self._inflight_writes):
                n += self.synchronize_writes(k)
            return n
        if key not in self._inflight_writes:
            return 0
        n = self._write_handle(key).wait()
        if self._injected:
            # a shared handle completes every op it carries
            self._inflight_writes.clear()
            self._pending_reads = 0
        else:
            s = self._slot(key)
            for k in [k for k in self._inflight_writes if self._slot(k) == s]:
                self._inflight_writes.pop(k, None)
        return n

    def synchronize_reads(self) -> int:
        """Complete all in-flight reads (writes stay airborne — an
        injected shared handle completes its writes too, tracked)."""
        if not self._pending_reads:
            return 0
        n = self.aio.wait()
        self._pending_reads = 0
        if self._injected:
            self._inflight_writes.clear()
        return n

    def synchronize(self) -> int:
        """Complete all in-flight reads and writes."""
        n = self.synchronize_reads()
        n += self.synchronize_writes()
        return n

    def release(self, key: str) -> None:
        if key in self._inflight_writes:
            self.synchronize_writes(key)
        info = self._index.pop(key, None)
        if info and os.path.exists(info[0]):
            os.unlink(info[0])

    @property
    def swapped_keys(self):
        return list(self._index)
