"""NVMe optimizer-state swapping (ZeRO-Infinity).

Reference: ``runtime/swap_tensor/partitioned_optimizer_swapper.py`` (:27)
and the double-buffered ``pipelined_optimizer_swapper.py``
(``PipelinedOptimizerSwapper`` :60): optimizer moments live on NVMe and
are streamed in/out around each parameter group's update so host RAM
holds only a small working set.

Host-offload here steps one *parameter group* at a time
(runtime/zero/offload.py), so the swapper pipelines at group
granularity: while group ``i`` is being updated, group ``i+1``'s moments
are already being prefetched and group ``i-1``'s written back — the
reference's OVERLAP_SWAP_TENSOR pattern with the aio thread pool
providing the async engine.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.runtime.swap.async_swapper import AsyncTensorSwapper


class PipelinedOptimizerSwapper:
    """Manages the moment buffers (m, v) of N parameter groups on disk.

    ``get(i)`` returns host arrays for group i (prefetched if the
    pipeline was primed), ``put(i)`` schedules write-back, ``prefetch(i)``
    starts an async read.  ``flush()`` barriers all I/O.
    """

    def __init__(self, swap_dir: str, shapes: List[tuple], aio_config=None, pipeline: bool = True):
        self.swapper = AsyncTensorSwapper(os.path.join(swap_dir, "optimizer"), aio_config=aio_config)
        self.shapes = shapes
        self.pipeline = pipeline
        self._resident: Dict[int, Dict[str, np.ndarray]] = {}
        self._prefetching: Dict[int, Dict[str, np.ndarray]] = {}
        self._initialized = set()

    def _keys(self, i: int):
        return (f"group{i}_m", f"group{i}_v")

    def initialize_group(self, i: int) -> None:
        """First touch: moments start as zeros (written lazily on first
        put)."""
        km, kv = self._keys(i)
        self._resident[i] = {
            "m": np.zeros(self.shapes[i], np.float32),
            "v": np.zeros(self.shapes[i], np.float32),
        }
        self._initialized.add(i)

    def prefetch(self, i: int) -> None:
        if i in self._resident or i in self._prefetching:
            return
        if i not in self._initialized:
            self.initialize_group(i)
            return
        km, kv = self._keys(i)
        bufs = {
            "m": self.swapper.swap_in(km, async_op=True),
            "v": self.swapper.swap_in(kv, async_op=True),
        }
        self._prefetching[i] = bufs

    def get(self, i: int) -> Dict[str, np.ndarray]:
        if i in self._resident:
            return self._resident[i]
        if i in self._prefetching:
            self.swapper.synchronize()  # barrier: prefetch + pending writebacks
            self._resident[i] = self._prefetching.pop(i)
            return self._resident[i]
        if i not in self._initialized:
            self.initialize_group(i)
            return self._resident[i]
        self.swapper.synchronize()
        km, kv = self._keys(i)
        bufs = {"m": self.swapper.swap_in(km, async_op=True), "v": self.swapper.swap_in(kv, async_op=True)}
        self.swapper.synchronize()
        self._resident[i] = bufs
        return bufs

    def put(self, i: int) -> None:
        """Schedule write-back of group i's moments and drop them from the
        working set once the write completes (on the next barrier)."""
        bufs = self._resident.pop(i, None)
        if bufs is None:
            return
        km, kv = self._keys(i)
        self.swapper.swap_out(km, bufs["m"], async_op=self.pipeline)
        self.swapper.swap_out(kv, bufs["v"], async_op=self.pipeline)

    def flush(self) -> None:
        self.swapper.synchronize()

    # checkpoint support ---------------------------------------------------
    def state_arrays(self, i: int) -> Dict[str, np.ndarray]:
        return self.get(i)

    def load_group(self, i: int, m: np.ndarray, v: np.ndarray) -> None:
        self._resident[i] = {"m": np.ascontiguousarray(m, np.float32), "v": np.ascontiguousarray(v, np.float32)}
        self._initialized.add(i)
