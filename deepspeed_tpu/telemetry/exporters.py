"""Pluggable metric exporters + the off-hot-path export loop.

Exporters consume :meth:`MetricsRegistry.snapshot` dicts; none of them
ever runs on the training/serving thread — the :class:`ExportLoop`
background thread flushes on the configured cadence
(``telemetry.export_interval_seconds``) and once more at interpreter
exit, so the hot path's only telemetry cost is the registry's host dict
updates.

* :class:`JsonlExporter` — one JSON line per export: the full typed
  snapshot (ts, rank, step, every metric).  The historical stream; a
  notebook replays a run from it.
* :class:`PrometheusTextfileExporter` — the node-exporter textfile-
  collector contract: the CURRENT value set in Prometheus exposition
  format, rewritten atomically (tmp + rename) each export so a scraper
  never reads a torn file.
* :class:`TensorBoardSink` — the PR-existing
  :class:`~deepspeed_tpu.utils.monitor.TensorBoardMonitor` rewired as a
  registry sink: counters/gauges land as scalars tagged
  ``Telemetry/<name>`` at the registry's current step.  (The engine's
  reference ``Train/Samples/*`` events keep their exact tags via the
  manager's direct forward — this sink is the everything-else stream.)
"""
from __future__ import annotations

import atexit
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")
_PROM_KINDS = {"counter": "counter", "gauge": "gauge", "histogram": "summary"}


def _prom_name(name: str) -> str:
    return "ds_" + _PROM_BAD.sub("_", name).strip("_")


def _prom_labels(labels: Dict[str, Any], rank: int) -> str:
    # a metric-level "rank" label wins over the snapshot's — duplicate
    # label names are invalid exposition format and would make the
    # collector reject the whole file
    items = sorted((str(k), str(v)) for k, v in labels.items())
    if not any(k == "rank" for k, _ in items):
        items.insert(0, ("rank", str(rank)))
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + inner + "}"


class JsonlExporter:
    name = "jsonl"

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(self.path, "a")

    def export(self, snapshot: Dict[str, Any]) -> None:
        self._fh.write(json.dumps(snapshot) + "\n")
        self._fh.flush()

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:  # pragma: no cover - double close on teardown
            pass


class PrometheusTextfileExporter:
    name = "prometheus"

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)

    def export(self, snapshot: Dict[str, Any]) -> None:
        rank = int(snapshot.get("rank", 0))
        lines: List[str] = [
            f"# deepspeed_tpu telemetry, ts={snapshot.get('ts', 0):.3f} "
            f"step={snapshot.get('step', 0)}"
        ]
        typed: set = set()
        for m in snapshot.get("metrics", []):
            pname = _prom_name(m["name"])
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} {_PROM_KINDS.get(m['kind'], 'untyped')}")
            labels = _prom_labels(m.get("labels", {}), rank)
            if m["kind"] == "histogram":
                base = pname
                lines.append(f"{base}_count{labels} {m.get('count', 0)}")
                lines.append(f"{base}_sum{labels} {m.get('sum', 0.0)}")
                for q, key in ((0.5, "p50"), (0.99, "p99")):
                    v = m.get(key)
                    if v is not None:
                        qlabels = labels[:-1] + f',quantile="{q}"' + "}"
                        lines.append(f"{base}{qlabels} {v}")
            else:
                v = m.get("value")
                if v is None:
                    continue
                lines.append(f"{pname}{labels} {v}")
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
        os.replace(tmp, self.path)

    def close(self) -> None:
        pass


class TensorBoardSink:
    name = "tensorboard"

    def __init__(self, monitor):
        self.monitor = monitor

    def export(self, snapshot: Dict[str, Any]) -> None:
        mon = self.monitor
        if mon is None or not getattr(mon, "enabled", False):
            return
        step = int(snapshot.get("step", 0))
        for m in snapshot.get("metrics", []):
            if m["kind"] == "histogram":
                value = m.get("mean")
            else:
                value = m.get("value")
            if value is None:
                continue
            suffix = "".join(
                f"/{k}.{v}" for k, v in sorted(m.get("labels", {}).items())
            )
            mon.add_scalar(f"Telemetry/{m['name']}{suffix}", float(value), step)
        mon.flush()

    def close(self) -> None:
        pass


class ExportLoop:
    """One daemon thread flushing the registry to every exporter on a
    cadence; ``flush()`` forces an immediate export (bench records, the
    atexit hook).  Exporter failures are logged, never raised — losing a
    scrape must not take down the run."""

    def __init__(self, registry, exporters, interval_seconds: float = 10.0):
        self.registry = registry
        self.exporters = list(exporters)
        self.interval = max(0.05, float(interval_seconds))
        self.last_export_at: Optional[float] = None
        self.exports = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._flush_lock = threading.Lock()

    def start(self) -> "ExportLoop":
        if self._thread is None and self.exporters:
            t = threading.Thread(target=self._loop, name="ds-telemetry-export", daemon=True)
            t.start()
            self._thread = t
            atexit.register(self.stop)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.flush()

    def flush(self) -> None:
        if not self.exporters:
            return
        with self._flush_lock:
            try:
                snapshot = self.registry.snapshot()
            except Exception as e:  # noqa: BLE001 — one bad scrape must not kill the loop
                logger.warning(f"telemetry: registry snapshot failed: {e!r}")
                return
            for ex in self.exporters:
                try:
                    ex.export(snapshot)
                except Exception as e:  # noqa: BLE001 — an exporter must not kill the run
                    logger.warning(f"telemetry: {getattr(ex, 'name', ex)} export failed: {e!r}")
            self.last_export_at = time.monotonic()
            self.exports += 1

    def last_export_age(self) -> Optional[float]:
        # falsy (None OR a zero/unset stamp) means "never exported" —
        # returning a monotonic-epoch delta here is how ds_report once
        # printed a billions-of-seconds "age" for a loop that had not
        # flushed yet
        if not self.last_export_at:
            return None
        return time.monotonic() - self.last_export_at

    def stop(self) -> None:
        """Final flush + close (idempotent; registered atexit)."""
        if self._stop.is_set():
            return
        self._stop.set()
        try:
            self.flush()
        finally:
            for ex in self.exporters:
                try:
                    ex.close()
                except Exception:  # noqa: BLE001 — teardown must not raise
                    pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)
