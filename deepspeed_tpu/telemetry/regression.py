"""Perf-regression plane: persistent bench history, noise-aware diffs,
and the runtime anomaly watch.

Three pieces (ISSUE 11 tentpole b/c):

* **bench history** — every ``bench.py`` / ``tools/bench_*.py`` record
  appends one schema'd line to ``bench_history.jsonl`` keyed by
  ``(rung, metric, config fingerprint, git sha, backend)``.  The
  trajectory was previously only recoverable by parsing log tails of
  five ``BENCH_r*.json`` snapshots; now it is a durable, append-only
  stream any tool can diff.
* **bench diff** — :func:`bench_diff` computes noise-aware deltas:
  the newest run's value vs the **median of the prior window** per key,
  with per-metric thresholds widened by the history's own dispersion
  (MAD), and returns ``regress`` / ``improve`` / ``noise`` /
  ``no-baseline`` verdicts.  ``tools/bench_diff.py --gate`` turns a
  ``regress`` verdict into a red CI (the ``perf-sentinel`` job);
  ``--bless`` records an intentional change so the baseline window
  restarts after it.
* **runtime anomaly watch** — step-wall spikes (window-relative, via
  the gauge ring :meth:`~.registry.Gauge.window_mean`) and cross-rank
  stragglers (rank step wall vs the cluster median, computed on the
  PR 9 heartbeat aggregation) surface as structured telemetry events
  the moment they happen, not at the next bench run.
"""
from __future__ import annotations

import hashlib
import json
import os
import statistics
import subprocess
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

HISTORY_SCHEMA = 1
HISTORY_FILENAME = "bench_history.jsonl"

# record fields that identify a *configuration* (not an outcome): two
# runs with equal fingerprints are comparable apples-to-apples
_FINGERPRINT_KEYS = (
    "unit", "micro_bs", "gas", "seq", "batch", "prompt_len", "kv",
    "offered_load", "zero_stage", "strategy", "mode",
)

# metrics where LOWER is better (everything else: higher is better)
_LOWER_IS_BETTER_TOKENS = ("_ms", "latency", "ttft", "tpot", "step_ms",
                           "wall", "stall", "p99", "p50")


def default_history_path(base_dir: Optional[str] = None) -> str:
    env = os.environ.get("DS_BENCH_HISTORY_PATH")
    if env:
        return env
    return os.path.join(base_dir or os.getcwd(), HISTORY_FILENAME)


def git_sha(repo_dir: Optional[str] = None) -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo_dir or os.getcwd(), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, timeout=5,
        )
        sha = out.stdout.decode().strip()
        return sha if out.returncode == 0 and sha else "unknown"
    except Exception:  # noqa: BLE001 — history must work outside a checkout
        return "unknown"


def config_fingerprint(record: Dict[str, Any]) -> str:
    """Short digest of the record's configuration keys — the
    apples-to-apples comparability key."""
    payload = {k: record[k] for k in _FINGERPRINT_KEYS if k in record}
    blob = json.dumps(payload, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:10]


def new_run_id() -> str:
    return uuid.uuid4().hex[:12]


def history_append(
    records: Iterable[Dict[str, Any]],
    rung: Optional[str] = None,
    path: Optional[str] = None,
    run_id: Optional[str] = None,
    sha: Optional[str] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> int:
    """Append one history line per measured record (skips records with
    no numeric ``value`` and skip markers).  Returns lines written.

    Child bench processes driven by a parent that appends for them set
    ``DS_BENCH_CHILD=1`` — the helper then refuses to double-write."""
    if os.environ.get("DS_BENCH_CHILD") == "1":
        return 0
    path = path or default_history_path()
    run_id = run_id or new_run_id()
    sha = sha or git_sha(os.path.dirname(os.path.abspath(path)) or None)
    lines = []
    for rec in records:
        if rec.get("skipped") or not isinstance(rec.get("value"), (int, float)):
            continue
        lines.append({
            "schema": HISTORY_SCHEMA,
            "kind": "bench",
            "ts": time.time(),
            "run_id": run_id,
            "git_sha": sha,
            "rung": rung or rec.get("rung") or "",
            "metric": rec.get("metric", "?"),
            "value": float(rec["value"]),
            "unit": rec.get("unit", ""),
            "backend": rec.get("backend", ""),
            "fingerprint": config_fingerprint(rec),
            # a DS_BENCH_INJECT-doctored value must stay marked in the
            # durable stream too — bench_diff never baselines on it
            **({"injected": rec["injected"]} if rec.get("injected") else {}),
            **(extra or {}),
        })
    if not lines:
        return 0
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        for line in lines:
            f.write(json.dumps(line, sort_keys=True) + "\n")
    return len(lines)


def history_bless(metric: str = "*", note: str = "", path: Optional[str] = None,
                  sha: Optional[str] = None) -> Dict[str, Any]:
    """Record an INTENTIONAL perf change: diffs for ``metric`` (``*`` =
    every metric) ignore runs before this marker, so the next gate
    compares against the new normal instead of flagging it forever."""
    path = path or default_history_path()
    marker = {
        "schema": HISTORY_SCHEMA, "kind": "bless", "ts": time.time(),
        "git_sha": sha or git_sha(), "metric": metric, "note": note,
    }
    with open(path, "a") as f:
        f.write(json.dumps(marker, sort_keys=True) + "\n")
    return marker


def history_load(path: Optional[str] = None) -> List[Dict[str, Any]]:
    path = path or default_history_path()
    out: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # a torn tail line must not kill the diff
            if isinstance(rec, dict):
                out.append(rec)
    return out


_TOOL_RUN_ID: Optional[str] = None


def tool_history_emit(rec: Dict[str, Any], rung: str,
                      base_dir: Optional[str] = None) -> int:
    """Standalone ``tools/bench_*.py`` hook: append one record to the
    repo's history stream.  No-op under a driver run (the bench.py
    parent sets ``DS_BENCH_CHILD=1`` and appends for everyone), shares
    one run_id per tool process, stamps the backend, never raises."""
    global _TOOL_RUN_ID
    try:
        if os.environ.get("DS_BENCH_CHILD") == "1":
            return 0
        if _TOOL_RUN_ID is None:
            _TOOL_RUN_ID = new_run_id()
        if "backend" not in rec:
            import jax  # tools always have jax up by emit time

            rec = dict(rec, backend=jax.default_backend())
        return history_append(
            [rec], rung=rung, path=default_history_path(base_dir),
            run_id=_TOOL_RUN_ID,
        )
    except Exception:  # noqa: BLE001 — history must never kill a bench
        return 0


# ---------------------------------------------------------------------------
# noise-aware diff
# ---------------------------------------------------------------------------

def lower_is_better(metric: str, unit: str = "") -> bool:
    m = (metric or "").lower()
    u = (unit or "").lower()
    return any(t in m for t in _LOWER_IS_BETTER_TOKENS) or u.endswith("ms") or u == "s"


def _noise_band(values: List[float], threshold: float,
                band_cap: Optional[float] = None) -> float:
    """Relative tolerance: the configured threshold widened by the
    baseline window's own dispersion (3·MAD/median) — a metric that
    historically wobbles ±8% must not gate at 5%.  ``band_cap`` bounds
    the widening (the CI sentinel's red check pins it so a few noisy
    seed runs cannot inflate the band past the injected regression)."""
    med = statistics.median(values)
    if med == 0 or len(values) < 3:
        return threshold
    mad = statistics.median(abs(v - med) for v in values)
    band = max(threshold, 3.0 * 1.4826 * mad / abs(med))
    return min(band, band_cap) if band_cap else band


def bench_diff(
    history: List[Dict[str, Any]],
    window: int = 8,
    default_threshold: float = 0.05,
    thresholds: Optional[Dict[str, float]] = None,
    metrics: Optional[Iterable[str]] = None,
    band_cap: Optional[float] = None,
) -> List[Dict[str, Any]]:
    """Verdict per (metric, backend, fingerprint) key: the NEWEST run's
    value against the median of up to ``window`` prior runs (after the
    last applicable bless marker).  ``thresholds`` maps metric-name
    substrings to relative thresholds (first match wins)."""
    thresholds = thresholds or {}
    bless_ts: Dict[str, float] = {}
    for rec in history:
        if rec.get("kind") == "bless":
            bless_ts[rec.get("metric", "*")] = max(
                bless_ts.get(rec.get("metric", "*"), 0.0), float(rec.get("ts", 0.0))
            )

    groups: Dict[Tuple[str, str, str], List[Dict[str, Any]]] = {}
    for rec in history:
        if rec.get("kind") != "bench":
            continue
        metric = rec.get("metric", "?")
        if metrics is not None and metric not in metrics:
            continue
        key = (metric, rec.get("backend", ""), rec.get("fingerprint", ""))
        groups.setdefault(key, []).append(rec)

    out: List[Dict[str, Any]] = []
    for (metric, backend, fp), recs in sorted(groups.items()):
        recs.sort(key=lambda r: float(r.get("ts", 0.0)))
        new = recs[-1]
        # bless semantics: the newest run at bless time becomes the new
        # baseline ANCHOR (you bless after seeing the red gate, so the
        # run that embodies the intentional change must seed the new
        # normal); everything older is out of the comparison
        cut = max(bless_ts.get("*", 0.0), bless_ts.get(metric, 0.0))
        pre_cut = [r for r in recs if float(r.get("ts", 0.0)) < cut]
        anchor = pre_cut[-1].get("run_id") if pre_cut else None
        recs = [
            r for r in recs
            if float(r.get("ts", 0.0)) >= cut or r.get("run_id") == anchor
        ]
        # baseline = prior RUNS (not prior lines): exclude every line of
        # the newest run_id so a multi-record rung can't self-baseline,
        # and never baseline on an injected (doctored) value — it exists
        # to be gated against, not to shift the normal
        prior = [
            r for r in recs
            if r.get("run_id") != new.get("run_id") and not r.get("injected")
        ]
        row = {
            "metric": metric, "backend": backend, "fingerprint": fp,
            "value": float(new["value"]), "unit": new.get("unit", ""),
            "run_id": new.get("run_id"), "git_sha": new.get("git_sha"),
            "n_baseline": len(prior),
        }
        if not prior:
            row.update(verdict="no-baseline", baseline=None, delta_pct=None,
                       band_pct=None)
            out.append(row)
            continue
        baseline_vals = [float(r["value"]) for r in prior[-window:]]
        baseline = statistics.median(baseline_vals)
        threshold = default_threshold
        for pat, th in thresholds.items():
            if pat in metric:
                threshold = float(th)
                break
        band = _noise_band(baseline_vals, threshold, band_cap=band_cap)
        delta = (row["value"] - baseline) / baseline if baseline else 0.0
        worse = -delta if not lower_is_better(metric, row["unit"]) else delta
        if worse > band:
            verdict = "regress"
        elif -worse > band:
            verdict = "improve"
        else:
            verdict = "noise"
        row.update(
            verdict=verdict, baseline=baseline,
            delta_pct=round(100.0 * delta, 2), band_pct=round(100.0 * band, 2),
        )
        out.append(row)
    return out


def gate(verdicts: List[Dict[str, Any]]) -> Tuple[bool, List[Dict[str, Any]]]:
    """(ok, regressions) — the perf-sentinel contract: ok is False iff
    any key carries a ``regress`` verdict."""
    bad = [v for v in verdicts if v["verdict"] == "regress"]
    return (not bad, bad)


def format_verdicts(verdicts: List[Dict[str, Any]]) -> str:
    lines = [
        f"{'verdict':12s} {'delta%':>8s} {'band%':>7s} {'baseline':>12s} "
        f"{'value':>12s}  metric [backend]"
    ]
    order = {"regress": 0, "improve": 1, "noise": 2, "no-baseline": 3}
    for v in sorted(verdicts, key=lambda v: (order.get(v["verdict"], 9), v["metric"])):
        d = "-" if v["delta_pct"] is None else f"{v['delta_pct']:+.1f}"
        b = "-" if v["band_pct"] is None else f"{v['band_pct']:.1f}"
        base = "-" if v["baseline"] is None else f"{v['baseline']:.1f}"
        lines.append(
            f"{v['verdict']:12s} {d:>8s} {b:>7s} {base:>12s} "
            f"{v['value']:12.1f}  {v['metric']} [{v['backend']}]"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# runtime anomaly watch
# ---------------------------------------------------------------------------

def check_step_spike(
    wall_ms: float,
    window_mean_ms: Optional[float],
    window_count: int,
    spike_factor: float = 2.5,
    min_window: int = 8,
) -> Optional[Dict[str, Any]]:
    """Window-relative step-wall spike test (pure; the manager feeds the
    gauge ring's mean from BEFORE the current sample so a spike can't
    mask itself).  Returns the structured event or None."""
    if window_mean_ms is None or window_count < min_window or window_mean_ms <= 0:
        return None
    if wall_ms <= spike_factor * window_mean_ms:
        return None
    return {
        "event": "step_wall_spike",
        "wall_ms": round(float(wall_ms), 3),
        "window_mean_ms": round(float(window_mean_ms), 3),
        "factor": round(float(wall_ms) / float(window_mean_ms), 2),
        "threshold_factor": spike_factor,
    }


def find_stragglers(
    latest: Dict[int, Dict[str, float]],
    alive: List[int],
    key_substr: str = "step_wall_ms",
    factor: float = 1.5,
) -> List[Dict[str, Any]]:
    """Cross-rank straggler test on the heartbeat-piggybacked snapshots:
    for every step-wall metric present on >= 2 live ranks, flag ranks
    whose wall exceeds ``factor`` x the cluster median."""
    by_metric: Dict[str, List[Tuple[int, float]]] = {}
    for r in alive:
        for name, v in (latest.get(r) or {}).items():
            if key_substr in name:
                by_metric.setdefault(name, []).append((r, float(v)))
    out: List[Dict[str, Any]] = []
    for name, pairs in sorted(by_metric.items()):
        if len(pairs) < 2:
            continue
        med = statistics.median(v for _, v in pairs)
        if med <= 0:
            continue
        for r, v in pairs:
            if v > factor * med:
                out.append({
                    "event": "straggler", "rank": r, "metric": name,
                    "value": round(v, 3), "cluster_median": round(med, 3),
                    "factor": round(v / med, 2), "threshold_factor": factor,
                })
    return out
