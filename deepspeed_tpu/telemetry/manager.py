"""TelemetryManager: the per-engine handle on the process-wide plane.

``deepspeed_tpu.telemetry.configure(cfg, ...)`` (called once by the
train engine, or explicitly by tools) arms the process singletons —
registry, trace buffer, export loop.  Each engine then owns one
:class:`TelemetryManager` labelled ``train`` / ``serving`` /
``inference``: it caches metric handles, publishes StepTimeline records
and engine progress events, carries the compiled step's cost analysis
(the MFU gauge's numerator), forwards the reference ``Train/Samples/*``
TensorBoard events, and triggers the on-demand / on-SLO-breach
``jax.profiler`` window capture.

Everything here is host bookkeeping; the manager is ``None``-checked at
every engine call site, so a disabled plane costs one pointer test.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger


class TelemetryManager:
    def __init__(self, label: str, registry, tracer, monitor=None, config=None):
        self.label = label
        self.registry = registry
        self.tracer = tracer
        self.monitor = monitor
        self.config = config
        self._cost: Dict[str, float] = {}
        self._attribution = None  # per-kernel cost table (attribution.py)
        self._spikes = 0
        self._jax_backend: Optional[str] = None
        self._profiler_fired = False
        self._lock = threading.Lock()
        # per-step publish runs on the hot path: memoize metric handles
        # by bare name so each publish is dict-hit + deque-append, not a
        # label-tuple rebuild through the registry lock path
        self._hists: Dict[str, Any] = {}
        self._gauges: Dict[str, Any] = {}
        self._counters: Dict[str, Any] = {}

    def _hist(self, name: str):
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = self.histogram(name)
        return h

    def _g(self, name: str):
        g = self._gauges.get(name)
        if g is None:
            g = self._gauges[name] = self.gauge(name)
        return g

    def _c(self, name: str):
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = self.counter(name)
        return c

    # -- wiring -------------------------------------------------------------
    @property
    def collect(self) -> bool:
        return self.registry.enabled

    @property
    def monitor_enabled(self) -> bool:
        return self.monitor is not None and getattr(self.monitor, "enabled", False)

    @property
    def exports_armed(self) -> bool:
        """Whether any sink is actually flowing — consumers who justify
        a deliberate report-cadence device sync (docs/telemetry.md).
        ``enabled: false`` wins over a listed exporter set: no loop was
        built, so no sync may be charged for it."""
        return bool(
            self.config is not None
            and getattr(self.config, "enabled", True)
            and getattr(self.config, "exporters", ())
        )

    def gauge(self, name: str, **labels):
        return self.registry.gauge(name, engine=self.label, **labels)

    def counter(self, name: str, **labels):
        return self.registry.counter(name, engine=self.label, **labels)

    def histogram(self, name: str, **labels):
        return self.registry.histogram(name, engine=self.label, **labels)

    # -- compiled-step cost (the MFU numerator) -----------------------------
    def set_step_cost(self, cost: Dict[str, float]) -> None:
        """The engine's AOT-compiled step cost analysis (flops, bytes
        accessed) — captured at compile time, free at publish time."""
        from deepspeed_tpu.profiling.flops_profiler import cost_bytes

        self._cost = dict(cost or {})
        if not self.registry.enabled:
            return  # cost kept for summary(); no handles when disabled
        flops = self._cost.get("flops", 0.0)
        if flops:
            self.gauge("flops_per_step").set(flops)
        hbm = cost_bytes(self._cost)
        if hbm:
            self.gauge("hbm_bytes_per_step").set(hbm)

    def step_cost(self) -> Dict[str, float]:
        return dict(self._cost)

    # -- per-kernel attribution (compile-time one-shot; attribution.py) ------
    def set_attribution(self, attribution) -> None:
        """Carry the compiled step's per-kernel cost table: registry
        gauges + Perfetto counter tracks now, ds_report/bench rows on
        demand.  Never raises — attribution is evidence, not control."""
        if attribution is None:
            return
        self._attribution = attribution
        try:
            attribution.publish(self)
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: attribution publish failed: {e!r}")

    def attribution(self):
        return self._attribution

    def attribute_compiled(self, compiled, label: str) -> None:
        """Walk one compiled executable into the bucket table (gated on
        ``telemetry.attribution``; skipped while the plane is disabled —
        the walk is one-shot at compile time but still not free)."""
        cfg = self.config
        if cfg is not None and not getattr(cfg, "attribution", True):
            return
        if not (self.registry.enabled or self.tracer.enabled):
            return
        from deepspeed_tpu.telemetry.attribution import attribute_executable

        try:
            attr = attribute_executable(
                compiled, label=label, backend=self._backend(),
                max_hlo_mb=float(getattr(cfg, "attribution_max_hlo_mb", 256.0) or 256.0),
            )
        except Exception as e:  # noqa: BLE001
            logger.warning(f"telemetry: attribution walk failed: {e!r}")
            return
        self.set_attribution(attr)

    def _backend(self) -> str:
        # memoized: jax.default_backend() is not free on a per-step path
        if self._jax_backend is None:
            import jax

            self._jax_backend = jax.default_backend()
        return self._jax_backend

    # -- per-step publish (StepTimeline hook) --------------------------------
    def publish_step(self, prefix: str, rec: Dict[str, float], count: int = 1,
                     gauge_names=()) -> None:
        """One closed StepTimeline record: phase histograms, wall/rate
        gauges, and the live MFU gauge (compiled-cost flops over the
        measured step wall).  Host dict ops only."""
        if not self.registry.enabled:
            return
        wall = rec.get("wall", 0.0)
        for phase, v in rec.items():
            if phase == "wall" or phase in gauge_names:
                continue
            # count-weighted: one multi-step window must weigh the same
            # as `count` per-step windows in exported counts/percentiles
            self._hist(f"{prefix}/{phase}_ms").observe(v * 1e3, n=count)
        for g in gauge_names:
            if g in rec:
                self._g(f"{prefix}/{g}").set(rec[g])
        if wall > 0:
            wall_ms = wall * 1e3
            g_wall = self._g(f"{prefix}/step_wall_ms")
            # spike test against the window BEFORE this sample joins it
            # (a spike must not mask itself), then publish
            prev_mean = g_wall.window_mean()
            prev_count = len(g_wall._ring)
            g_wall.set(wall_ms)
            self._g(f"{prefix}/steps_per_s").set(1.0 / wall)
            self._check_spike(prefix, wall_ms, prev_mean, prev_count)
            if self._cost:
                # the ONE shared MFU/HBM derivation (flops_profiler)
                from deepspeed_tpu.profiling.flops_profiler import derive_step_stats

                stats = derive_step_stats(self._cost, wall, backend=self._backend())
                if stats["flops_per_step"]:
                    self._g("mfu").set(stats["mfu"])
                if stats["hbm_bytes_per_step"]:
                    self._g("hbm_gbps").set(stats["hbm_gbps"])
        self._c(f"{prefix}/steps").inc(count)

    def _check_spike(self, prefix: str, wall_ms: float,
                     prev_mean: Optional[float], prev_count: int) -> None:
        """Runtime anomaly watch (regression.py): a step wall far above
        its own recent window becomes a structured event — counter,
        Perfetto instant, and a (rate-limited) log line."""
        from deepspeed_tpu.telemetry.regression import check_step_spike

        cfg = self.config
        event = check_step_spike(
            wall_ms, prev_mean, prev_count,
            spike_factor=float(getattr(cfg, "spike_factor", 2.5) or 2.5),
            min_window=int(getattr(cfg, "spike_min_window", 8) or 8),
        )
        if event is None:
            return
        self._spikes += 1
        self._c(f"{prefix}/anomaly/step_spikes").inc()
        if self.tracer.enabled:
            self.tracer.add_instant("step_wall_spike", "anomaly", args=event)
        if self._spikes <= 3 or self._spikes % 32 == 0:
            # a sustained slowdown flags every step until the window
            # catches up; don't let the log become the second anomaly
            logger.warning(
                f"telemetry[{self.label}]: step wall spike — "
                f"{event['wall_ms']:.1f}ms vs window mean "
                f"{event['window_mean_ms']:.1f}ms ({event['factor']}x)"
            )

    # -- engine progress events ---------------------------------------------
    def publish_train_progress(self, step: int, samples: int, loss: Optional[float],
                               lr: float, loss_scale: float) -> None:
        """The reference engine's loss/lr/loss-scale event set, routed
        through the registry; the exact ``Train/Samples/*`` tags are
        forwarded to the TensorBoard monitor unchanged (reference
        engine.py:1178-1188, :1356-1382).  ``loss`` is None on the
        sync-free default path (the engine only pays the d2h read when
        a monitor/sink consumer is armed)."""
        if self.registry.enabled:
            self.registry.set_step(step)
            self.gauge("train/lr").set(lr)
            self.gauge("train/loss_scale").set(loss_scale)
            self.gauge("train/samples").set(samples)
            if loss is not None:
                self.gauge("train/loss").set(loss)
        if self.monitor_enabled:
            events = [("Train/Samples/lr", lr), ("Train/Samples/loss_scale", loss_scale)]
            if loss is not None:
                events.append(("Train/Samples/train_loss", loss))
            self.monitor.write_events(events, samples)
            self.monitor.flush()

    def set_comm(self, summary: Dict[str, Any]) -> None:
        """The comm layer's resolved strategy + per-step byte model
        (static per engine; docs/comm.md)."""
        if not self.registry.enabled:
            return
        self.gauge("comm/bytes_per_step",
                   strategy=summary.get("strategy", "?")).set(
            summary.get("grad_exchange_bytes", 0)
        )

    # -- summaries for bench records / ds_report ------------------------------
    def summary(self) -> Dict[str, Any]:
        """Compact per-engine roll-up for bench records: the live MFU
        gauge, the compiled step's FLOPs/HBM bytes, and the snapshot
        digest."""
        from deepspeed_tpu.profiling.flops_profiler import cost_bytes

        mfu = self.registry.gauge("mfu", engine=self.label)
        out = {
            "mfu": None if mfu.value is None else round(mfu.value, 4),
            "flops_per_step": self._cost.get("flops"),
            "hbm_bytes_per_step": cost_bytes(self._cost) or None,
            "telemetry": self.digest(),
        }
        if self._attribution is not None:
            # top buckets by roofline time share — the bench record's
            # one-line answer to "which kernel family owns this step"
            out["attribution_top"] = [
                {"bucket": b, "time_share_pct": s}
                for b, s in self._attribution.top_buckets(3)
            ]
        return out

    def digest(self) -> Dict[str, Any]:
        """Content digest of the current compact snapshot — a bench
        record carries it so two runs' telemetry states are comparable
        at a glance without embedding the whole snapshot."""
        compact = self.registry.snapshot_compact()
        payload = json.dumps(compact, sort_keys=True).encode()
        return {
            "metrics": len(compact),
            "sha1": hashlib.sha1(payload).hexdigest()[:12],
        }

    # -- jax.profiler window capture -----------------------------------------
    def capture_profile(self, reason: str = "on-demand",
                        logdir: Optional[str] = None,
                        millis: Optional[int] = None) -> bool:
        """Programmatic ``jax.profiler`` window: start a trace now, stop
        it ``millis`` later from a timer thread (the caller's loop keeps
        running — the window captures real steps, not a stall).  One
        shot per process unless re-armed; returns whether a capture
        started."""
        cfg = self.config
        logdir = logdir or (getattr(cfg, "profiler_dir", "") or None)
        if logdir is None:
            return False
        with self._lock:
            if self._profiler_fired:
                return False
            self._profiler_fired = True
        millis = int(millis or getattr(cfg, "profiler_capture_ms", 2000))
        try:
            import jax

            jax.profiler.start_trace(logdir)
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            logger.warning(f"telemetry: jax.profiler capture failed to start: {e!r}")
            return False
        logger.warning(
            f"telemetry: jax.profiler window capture started ({reason}); "
            f"{millis}ms -> {logdir}"
        )
        if self.registry.enabled:
            self.counter("profiler_captures").inc()
        if self.tracer.enabled:
            self.tracer.add_instant("profiler_capture", "telemetry",
                                    args={"reason": reason, "millis": millis})

        def _stop():
            try:
                import jax

                jax.profiler.stop_trace()
                logger.warning(f"telemetry: jax.profiler window capture finished -> {logdir}")
            except Exception as e:  # noqa: BLE001
                logger.warning(f"telemetry: jax.profiler stop failed: {e!r}")

        t = threading.Timer(millis / 1e3, _stop)
        t.daemon = True
        t.start()
        return True

    def check_slo(self, ttft_ms: float) -> None:
        """Serving hook: one profiler window on the first TTFT SLO
        breach (``telemetry.slo_ttft_breach_ms``)."""
        threshold = float(getattr(self.config, "slo_ttft_breach_ms", 0.0) or 0.0)
        if threshold <= 0 or ttft_ms <= threshold:
            return
        if self.registry.enabled:
            self.counter("serving/slo_breaches").inc()
        if self.tracer.enabled:
            self.tracer.add_instant(
                "slo_breach", "serving",
                args={"ttft_ms": round(ttft_ms, 3), "threshold_ms": threshold},
            )
        self.capture_profile(reason=f"TTFT {ttft_ms:.0f}ms > SLO {threshold:.0f}ms")
