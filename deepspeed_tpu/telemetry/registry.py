"""Process-wide metrics registry: typed counters / gauges / histograms.

One :class:`MetricsRegistry` per process (module singleton in
``deepspeed_tpu.telemetry``); every subsystem publishes into it —
StepTimeline phases, comm-layer strategy decisions and step bytes,
serving scheduler/engine stats, resilience/supervision events, and the
flops profiler's MFU accounting (docs/telemetry.md has the catalog).

Design constraints (the hot path pays for every byte of this):

* **host-only**: a metric update is a couple of dict/deque operations —
  no jax, no device sync, nothing traced.  Values handed in must
  already be host scalars (the publishing site owns any ``device_get``
  and its cadence);
* **zero overhead when disabled**: every update starts with one
  ``enabled`` attribute check and returns.  Sources additionally gate
  their whole publish block on a local ``None`` check so a disabled
  plane costs one pointer comparison per step;
* **bounded**: histograms and the per-metric sample history live in
  ``deque(maxlen=ring)`` ring buffers — a week-long run holds the same
  memory as a minute-long one;
* **thread-safe**: the serving engine, the async checkpoint writer, and
  the supervision threads all publish concurrently.  Metric creation
  takes the registry lock; every update AND every read path (snapshot /
  compact / mean) takes the per-metric lock — a histogram's
  count/sum/min/max are one logical value, and the export thread must
  never observe a half-applied ``observe()`` (the torn-snapshot race
  ds_race flags as ``race-inconsistent-lockset``).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """Base: identity + the shared ``enabled`` gate (delegated to the
    owning registry so a late ``configure()`` flips every cached handle
    at once)."""

    kind = "metric"

    def __init__(self, registry: "MetricsRegistry", name: str, labels: Dict[str, Any]):
        self._registry = registry
        self.name = name
        self.labels = dict(labels)
        self.updated_at: float = 0.0

    @property
    def enabled(self) -> bool:
        return self._registry.enabled

    def qualified(self) -> str:
        if not self.labels:
            return self.name
        inner = ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))
        return f"{self.name}{{{inner}}}"

    def compact_value(self) -> float:
        raise NotImplementedError

    def snapshot(self) -> Dict[str, Any]:
        raise NotImplementedError


class Counter(Metric):
    """Monotonic event count (retries, finished requests, dead ranks)."""

    kind = COUNTER

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:  # ds-race: entry
        if not self._registry.enabled:
            return
        with self._lock:
            self.value += n
            self.updated_at = time.monotonic()

    def compact_value(self) -> float:  # ds-race: entry
        with self._lock:
            return self.value

    def snapshot(self) -> Dict[str, Any]:  # ds-race: entry
        with self._lock:
            value = self.value
        return {"name": self.name, "kind": self.kind, "labels": self.labels,
                "value": value}


class Gauge(Metric):
    """Last-written level (queue depth, MFU, loss, comm bytes/step) with
    a bounded ring of recent values for window means."""

    kind = GAUGE

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._lock = threading.Lock()
        self.value: Optional[float] = None
        self._ring: deque = deque(maxlen=registry.ring)

    def set(self, value: float) -> None:  # ds-race: entry
        if not self._registry.enabled:
            return
        v = float(value)
        with self._lock:
            self.value = v
            self._ring.append(v)
            self.updated_at = time.monotonic()

    def window_mean(self) -> Optional[float]:
        # copy under the writer's lock: iterating a deque while the hot
        # path appends raises RuntimeError in the export thread
        with self._lock:
            ring = list(self._ring)
        return sum(ring) / len(ring) if ring else None

    def compact_value(self) -> float:  # ds-race: entry
        with self._lock:
            return self.value if self.value is not None else 0.0

    def snapshot(self) -> Dict[str, Any]:  # ds-race: entry
        with self._lock:
            value = self.value
        return {"name": self.name, "kind": self.kind, "labels": self.labels,
                "value": value, "window_mean": self.window_mean()}


class Histogram(Metric):
    """Cumulative count/sum/min/max plus a bounded ring of recent
    samples; percentiles are computed over the RING (the recent window),
    which is what an SLO dashboard wants and what keeps memory bounded."""

    kind = HISTOGRAM

    def __init__(self, registry, name, labels):
        super().__init__(registry, name, labels)
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._ring: deque = deque(maxlen=registry.ring)

    def observe(self, value: float, n: int = 1) -> None:  # ds-race: entry
        """``n > 1`` records the value with multiplicity — a compiled
        multi-step run (``train_batches``) closes one window covering n
        identical per-step records, and the exported count/percentile
        weighting must match the per-step path's."""
        if not self._registry.enabled:
            return
        v = float(value)
        n = max(1, int(n))
        with self._lock:
            self.count += n
            self.sum += v * n
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._ring.extend([v] * min(n, self._ring.maxlen or n))
            self.updated_at = time.monotonic()

    def percentile(self, q: float) -> Optional[float]:
        # copy under the writer's lock (see Gauge.window_mean)
        with self._lock:
            ring = sorted(self._ring)
        if not ring:
            return None
        idx = min(len(ring) - 1, max(0, int(round((q / 100.0) * (len(ring) - 1)))))
        return ring[idx]

    def mean(self) -> Optional[float]:
        with self._lock:
            return self.sum / self.count if self.count else None

    def window_mean(self) -> Optional[float]:
        """Mean over the RING (recent window) — what a load-tracking
        consumer wants (the serving admission controller estimates TTFT
        from the *current* decode wall, not the lifetime mean, which a
        warmup compile would skew forever)."""
        with self._lock:
            ring = list(self._ring)
        return sum(ring) / len(ring) if ring else None

    def compact_value(self) -> float:  # ds-race: entry
        m = self.mean()
        return m if m is not None else 0.0

    def snapshot(self) -> Dict[str, Any]:  # ds-race: entry
        # count/sum/min/max are one logical value: copy them under the
        # writer's lock so a concurrent observe() can't tear the export
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
        return {
            "name": self.name, "kind": self.kind, "labels": self.labels,
            "count": count, "sum": total, "min": lo, "max": hi,
            "mean": (total / count if count else None),
            "p50": self.percentile(50), "p99": self.percentile(99),
        }


_KINDS = {COUNTER: Counter, GAUGE: Gauge, HISTOGRAM: Histogram}


class MetricsRegistry:
    """The process-wide metric table.  ``counter()``/``gauge()``/
    ``histogram()`` are get-or-create and return the SAME object for the
    same (name, labels) — callers may cache handles; a handle created
    while disabled becomes live when :meth:`configure` enables the
    registry (updates check the registry flag, not a frozen copy)."""

    def __init__(self, enabled: bool = False, ring: int = 1024, rank: int = 0):
        self.enabled = bool(enabled)
        self.ring = max(16, int(ring))
        self.rank = int(rank)
        self.step = 0  # engine-advanced; exporters stamp records with it
        self.created_at = time.monotonic()
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, str, tuple], Metric] = {}

    def configure(self, enabled: Optional[bool] = None, ring: Optional[int] = None,
                  rank: Optional[int] = None) -> "MetricsRegistry":
        """In-place reconfiguration of the process singleton (a second
        engine in the same process must not orphan cached handles).  A
        ring change resizes EXISTING metrics' windows too — the
        configured memory bound applies to the whole registry, not just
        metrics created afterwards."""
        if enabled is not None:
            self.enabled = bool(enabled)
        if ring is not None and max(16, int(ring)) != self.ring:
            self.ring = max(16, int(ring))
            for m in self.metrics():
                old = getattr(m, "_ring", None)
                if old is not None:
                    with m._lock:
                        m._ring = deque(old, maxlen=self.ring)
        if rank is not None:
            self.rank = int(rank)
        return self

    # -- get-or-create handles --------------------------------------------
    def _get(self, kind: str, name: str, labels: Dict[str, Any]) -> Metric:  # ds-race: entry
        # Fully locked (no double-checked fast path): two threads
        # creating the same key must agree on ONE Metric object, and a
        # concurrent reset()/snapshot() must never see the table
        # mid-insert.  Callers cache handles, so this is not hot.
        key = (kind, name, _label_key(labels))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = _KINDS[kind](self, name, labels)
                self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get(COUNTER, name, labels)  # type: ignore[return-value]

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get(GAUGE, name, labels)  # type: ignore[return-value]

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get(HISTOGRAM, name, labels)  # type: ignore[return-value]

    def set_step(self, step: int) -> None:
        self.step = int(step)

    # -- introspection / export -------------------------------------------
    def metrics(self) -> List[Metric]:  # ds-race: entry
        with self._lock:
            return list(self._metrics.values())

    def size(self) -> int:
        with self._lock:
            return len(self._metrics)

    def snapshot(self) -> Dict[str, Any]:  # ds-race: entry
        """Full typed snapshot for the exporters (JSONL / Prometheus /
        TensorBoard sink)."""
        return {
            "ts": time.time(),
            "rank": self.rank,
            "step": self.step,
            "metrics": [m.snapshot() for m in self.metrics()],
        }

    def snapshot_compact(self) -> Dict[str, float]:  # ds-race: entry
        """One float per metric, keyed by the qualified name — the shape
        that piggybacks on the supervision heartbeat (counters: total;
        gauges: last; histograms: mean).  Kept deliberately small: a
        beat line must stay a beat, not a bulk transfer."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            v = m.compact_value()
            if v is not None:
                out[m.qualified()] = round(float(v), 6)
        return out

    def reset(self) -> None:
        """Drop every metric (tests; a fresh engine in a long-lived
        process keeps the registry by default — labels disambiguate)."""
        with self._lock:
            self._metrics.clear()
