"""Unified telemetry plane (docs/telemetry.md).

One process-wide :class:`~.registry.MetricsRegistry` + one
:class:`~.spans.TraceBuffer`, armed by :func:`configure` (the train
engine calls it from the validated ``telemetry`` config block; tools
call it directly).  Sources publish through a per-engine
:class:`~.manager.TelemetryManager` or, for rare out-of-engine events
(retries, rescues, comm decisions), straight into :func:`get_registry`.

Exporters (JSONL / Prometheus textfile / TensorBoard sink) run on a
background cadence — never on the hot path; the Chrome-trace buffer
exports ``trace.json`` for Perfetto; cross-rank aggregation piggybacks
on the supervision heartbeat (:mod:`.aggregate`).
"""
from __future__ import annotations

import atexit
import os
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry.aggregate import (
    CrossRankAggregator,
    decode_metrics,
    encode_metrics,
)
from deepspeed_tpu.telemetry.exporters import (
    ExportLoop,
    JsonlExporter,
    PrometheusTextfileExporter,
    TensorBoardSink,
)
from deepspeed_tpu.telemetry.attribution import (
    BUCKETS,
    Attribution,
    attribute_executable,
    attribute_hlo_text,
    attribute_jit,
)
from deepspeed_tpu.telemetry.manager import TelemetryManager
from deepspeed_tpu.telemetry.regression import (
    bench_diff,
    check_step_spike,
    find_stragglers,
    history_append,
    history_bless,
    history_load,
)
from deepspeed_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deepspeed_tpu.telemetry.spans import (
    PID_CHECKPOINT,
    PID_ENGINE,
    PID_REQUESTS,
    TraceBuffer,
    validate_chrome_trace,
)

# process singletons: disabled at import; configure() arms them
_REGISTRY = MetricsRegistry(enabled=False)
_TRACER = TraceBuffer(enabled=False)
_EXPORT_LOOP: Optional[ExportLoop] = None
_CONFIG = None
_TRACE_PATH: Optional[str] = None
_ATEXIT_DONE = False


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def get_tracer() -> TraceBuffer:
    return _TRACER


def default_output_path(cfg=None) -> str:
    p = getattr(cfg or _CONFIG, "output_path", "") or ""
    return p or "telemetry"


def configure(config=None, rank: int = 0, label: str = "train",
              monitor=None) -> TelemetryManager:
    """Arm the process-wide plane from a validated
    :class:`~deepspeed_tpu.config.config.TelemetryConfig` (or None for
    defaults) and return the caller's :class:`TelemetryManager`.

    Idempotent-by-design for multi-engine processes: a second call
    reconfigures the shared registry/tracer in place (cached metric
    handles stay live) and replaces the export loop if the sink set
    changed."""
    global _EXPORT_LOOP, _CONFIG, _TRACE_PATH, _ATEXIT_DONE
    from deepspeed_tpu.config.config import TelemetryConfig

    if config is None:
        config = TelemetryConfig()
    elif isinstance(config, dict):
        config = TelemetryConfig.from_dict(config)
    _CONFIG = config

    _REGISTRY.configure(enabled=config.enabled, ring=config.ring, rank=rank)
    _TRACER.configure(
        enabled=config.enabled and config.trace,
        max_events=config.trace_buffer_events,
    )
    out_dir = default_output_path(config)
    _TRACE_PATH = config.trace_path or os.path.join(out_dir, "trace.json")

    # (re)build the export loop for the configured sink set
    if _EXPORT_LOOP is not None:
        _EXPORT_LOOP.stop()
        _EXPORT_LOOP = None
    if config.enabled and config.exporters:
        exporters = []
        for name in config.exporters:
            if name == "jsonl":
                exporters.append(
                    JsonlExporter(os.path.join(out_dir, f"metrics_rank{rank}.jsonl"))
                )
            elif name == "prometheus":
                exporters.append(
                    PrometheusTextfileExporter(
                        os.path.join(out_dir, f"metrics_rank{rank}.prom")
                    )
                )
            elif name == "tensorboard":
                exporters.append(TensorBoardSink(monitor))
        _EXPORT_LOOP = ExportLoop(
            _REGISTRY, exporters, interval_seconds=config.export_interval_seconds
        ).start()
    if not _ATEXIT_DONE:
        _ATEXIT_DONE = True
        atexit.register(shutdown)
    return TelemetryManager(label, _REGISTRY, _TRACER, monitor=monitor, config=config)


def manager_for(label: str, monitor=None) -> TelemetryManager:
    """A manager bound to the current process plane WITHOUT
    reconfiguring it (serving/inference engines attach to whatever the
    process armed; a no-config process gets no-op publishes)."""
    return TelemetryManager(label, _REGISTRY, _TRACER, monitor=monitor, config=_CONFIG)


def flush() -> None:
    """Force an immediate export (bench records read files right after)."""
    if _EXPORT_LOOP is not None:
        _EXPORT_LOOP.flush()


def export_trace(path: Optional[str] = None,
                 metadata: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Write the span buffer as Chrome-trace JSON; returns the path or
    None when tracing never armed."""
    if not _TRACER.enabled and not _TRACER.events():
        return None
    return _TRACER.export(path or _TRACE_PATH or "trace.json", metadata=metadata)


def shutdown() -> None:
    """Atexit: final metric export + trace flush (a crash-adjacent exit
    must not drop the evidence)."""
    global _EXPORT_LOOP
    if _EXPORT_LOOP is not None:
        _EXPORT_LOOP.stop()
        _EXPORT_LOOP = None
    if _TRACER.enabled and _TRACER.events():
        try:
            export_trace()
        except OSError:  # pragma: no cover - exit path best-effort
            pass


def status() -> Dict[str, Any]:
    """ds_report rows: enabled sinks, cadence, registry size, last
    export age, trace state."""
    loop = _EXPORT_LOOP
    return {
        "enabled": _REGISTRY.enabled,
        "rank": _REGISTRY.rank,
        "registry_size": _REGISTRY.size(),
        "ring": _REGISTRY.ring,
        "sinks": [getattr(e, "name", "?") for e in (loop.exporters if loop else [])],
        "export_interval_seconds": loop.interval if loop else None,
        "exports": loop.exports if loop else 0,
        "last_export_age_seconds": loop.last_export_age() if loop else None,
        "trace_enabled": _TRACER.enabled,
        "trace_events": len(_TRACER.events()),
        "trace_path": _TRACE_PATH,
    }


def reset_for_tests() -> None:
    """Tear the plane back to import state (tests only)."""
    global _EXPORT_LOOP, _CONFIG, _TRACE_PATH
    if _EXPORT_LOOP is not None:
        _EXPORT_LOOP.stop()
        _EXPORT_LOOP = None
    _REGISTRY.reset()
    _REGISTRY.configure(enabled=False)
    _TRACER.clear()
    _TRACER.enabled = False
    _CONFIG = None
    _TRACE_PATH = None


__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "TraceBuffer", "validate_chrome_trace",
    "PID_ENGINE", "PID_REQUESTS", "PID_CHECKPOINT",
    "JsonlExporter", "PrometheusTextfileExporter", "TensorBoardSink", "ExportLoop",
    "CrossRankAggregator", "encode_metrics", "decode_metrics",
    "TelemetryManager",
    "Attribution", "BUCKETS",
    "attribute_executable", "attribute_hlo_text", "attribute_jit",
    "bench_diff", "history_append", "history_bless", "history_load",
    "check_step_spike", "find_stragglers",
    "configure", "manager_for", "get_registry", "get_tracer",
    "flush", "export_trace", "shutdown", "status", "reset_for_tests",
]
