"""Per-op cost attribution: which kernel family owns the step's cost.

PR 9 ended at whole-step gauges (``mfu``, ``hbm_bytes_per_step``); the
kernel arc needs to know *which* fusion is the bottleneck before any
Pallas kernel lands.  This module walks a compiled executable's
optimized HLO (``executable.as_text()``) instruction by instruction,
computes per-instruction FLOPs/HBM bytes analytically, and buckets them
into named kernel families:

* ``attention``        — flash/sparse/ring attention math (``ops/attention``)
* ``matmul``           — parameter matmuls (qkv/proj/ffn/lm-head dots + grads)
* ``optimizer-update`` — Adam/LAMB master-weight update (``ops/adam|lamb``)
* ``comm-collective``  — all-reduce/-gather/reduce-scatter/… + comm-layer math
* ``kv-dequant``       — (de)quantization traffic (``ops/quantizer``, runtime
  quantize) — the int8-KV decode round-trip the roadmap targets
* ``layernorm/other``  — layernorm, loss/xent, dropout, and the residual

The bucket table is **calibrated against the module's own
``cost_analysis()``**: the analytically-unattributed remainder lands in
``layernorm/other`` (recorded as ``unattributed_*``), so the table's
totals always match XLA's whole-module numbers — tests pin the sum to
within 1% and the ``matmul`` bucket to the analytic ``6N`` count.

Per bucket the roofline view reports arithmetic intensity (FLOPs/byte),
a compute- vs memory-bound verdict against the platform's machine
balance, the roofline-implied minimum time share, and %-of-peak — the
evidence format EQuARX (arXiv:2506.17615) and cross-replica sharding
(arXiv:2004.13336) used to prove their wins.

Publishing surfaces: registry gauges (``attribution/<bucket>/*``),
Perfetto counter tracks, ``ds_report`` rows, bench records, and the
``perf-sentinel`` CI artifact (``python -m
deepspeed_tpu.telemetry.attribution``).

This file also owns the ONE ``jax.profiler`` trace cost-walk shared by
``tools/profile_train_step.py`` / ``profile_bert_step.py`` /
``profile_decode.py`` (previously three ad-hoc copies).
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

BUCKETS = (
    "attention",
    "matmul",
    "optimizer-update",
    "comm-collective",
    "kv-dequant",
    "layernorm/other",
)
OTHER = "layernorm/other"

# opcodes whose cost is ~one flop per output element (cheap transcendentals
# deliberately counted as 1 — the residual calibration absorbs the model error)
_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "negate", "maximum", "minimum",
    "abs", "exponential", "exponential-minus-one", "log", "log-plus-one",
    "sqrt", "rsqrt", "cbrt", "power", "tanh", "logistic", "sine", "cosine",
    "atan2", "remainder", "compare", "select", "clamp", "sign", "floor",
    "ceil", "round-nearest-afz", "round-nearest-even", "and", "or", "xor",
    "not", "shift-left", "shift-right-logical", "shift-right-arithmetic",
}
# every other opcode (broadcast/copy/transpose/slice/gather/...) is data
# movement: zero flops by fall-through, but its bytes are still counted
_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

_DTYPE_BYTES = {
    "f64": 8, "s64": 8, "u64": 8, "c64": 8, "c128": 16,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "s4": 1, "u4": 1,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"^\s+(?:ROOT\s+)?%(?P<name>[^\s=]+)\s+=\s+(?P<type>\([^)]*\)|\S+)\s+"
    r"(?P<opcode>[\w-]+)\((?P<rest>.*)$"
)
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?(?P<name>[\w.\-]+)\s+\([^)]*\)\s*->")
_META_RE = re.compile(
    r'metadata=\{[^}]*?op_name="(?P<op>[^"]*)"'
    r'(?:[^}]*?source_file="(?P<src>[^"]*)")?'
)
_CALLS_RE = re.compile(r"calls=%([\w.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")


def _shape_elems_bytes(type_str: str) -> Tuple[int, int]:
    """(total elements, total bytes) of an HLO type string; tuple types
    sum their members."""
    elems = 0
    nbytes = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        elems += n
        nbytes += n * _DTYPE_BYTES[dt]
    if elems == 0 and type_str.split("{")[0] in _DTYPE_BYTES:
        # scalar like "f32[]" is matched above; bare "f32" (rare) here
        elems, nbytes = 1, _DTYPE_BYTES[type_str.split("{")[0]]
    return elems, nbytes


def _dot_flops(out_type: str, rest: str) -> float:
    """2 · |out| · Π(contracted dims), from the dot's result type, its
    lhs operand shape and ``lhs_contracting_dims``."""
    out_elems, _ = _shape_elems_bytes(out_type)
    m = _CONTRACT_RE.search(rest)
    first_operand = _SHAPE_RE.search(rest)
    if m is None or first_operand is None:
        return 2.0 * out_elems  # degenerate; residual calibration absorbs it
    dims_txt = first_operand.group(2)
    lhs_dims = [int(d) for d in dims_txt.split(",")] if dims_txt else []
    contract = 1
    for idx in (int(i) for i in m.group(1).split(",") if i):
        if 0 <= idx < len(lhs_dims):
            contract *= lhs_dims[idx]
    return 2.0 * out_elems * contract


def classify(opcode: str, op_name: str, source_file: str) -> str:
    """Bucket one HLO instruction.  Priority: collective opcode > comm
    source > quantize source > attention > optimizer > matmul > other."""
    if opcode.startswith(_COLLECTIVES):
        return "comm-collective"
    src = source_file or ""
    op = op_name or ""
    if "/comm/" in src:
        return "comm-collective"
    # the Pallas kernel suite (ops/kernels, docs/kernels.md) wins over
    # the dequant match: with the fused flash-decode kernel armed, the
    # int8 scale math happens IN-KERNEL and is attention work — the
    # kv-dequant bucket exists to expose the un-fused round-trip
    if "ops/kernels/flash_decode" in src or "flash_decode" in op:
        return "attention"
    if "ops/kernels/fused_update" in src or "fused_update" in op:
        return "optimizer-update"
    if "quantiz" in src or "dequant" in op or "quantize" in op:
        return "kv-dequant"
    if "ops/attention" in src or "flash_attention" in op or "attention" in op:
        return "attention"
    if "ops/adam" in src or "ops/lamb" in src or "/optimizer" in src:
        return "optimizer-update"
    if opcode == "dot" or (
        opcode == "custom-call" and ("matmul" in op or "dot" in op)
    ):
        return "matmul"
    return OTHER


@dataclass
class BucketCost:
    flops: float = 0.0
    bytes: float = 0.0
    ops: int = 0


@dataclass
class Attribution:
    """Per-bucket cost table for ONE compiled executable, calibrated to
    its module-level ``cost_analysis()``."""

    label: str
    buckets: Dict[str, BucketCost]
    module_flops: float
    module_bytes: float
    unattributed_flops: float  # residual folded into layernorm/other
    unattributed_bytes: float
    backend: Optional[str] = None
    meta: Dict[str, Any] = field(default_factory=dict)

    # -- derived views ------------------------------------------------------
    def total_flops(self) -> float:
        return sum(b.flops for b in self.buckets.values())

    def total_bytes(self) -> float:
        return sum(b.bytes for b in self.buckets.values())

    def roofline(self, backend: Optional[str] = None,
                 wall_s: Optional[float] = None) -> List[Dict[str, Any]]:
        """Per-bucket roofline rows: arithmetic intensity, bound verdict
        vs the platform machine balance, the roofline-implied minimum
        time (the floor at peak hardware) and its share of the module
        floor.  With a measured ``wall_s``, each row also carries
        ``pct_peak`` — the bucket's binding-resource utilization under
        the time-share estimate ``t_bucket ≈ share × wall`` (an honest
        static estimate; the per-op *measured* %-of-peak comes from the
        jax.profiler trace walk on real hardware)."""
        from deepspeed_tpu.profiling.flops_profiler import (
            peak_flops,
            peak_hbm_bytes_per_s,
        )

        backend = backend or self.backend
        pk_f = peak_flops(backend)
        pk_b = peak_hbm_bytes_per_s(backend)
        balance = pk_f / pk_b  # flops/byte at the roofline ridge
        rows = []
        times = {
            name: max(b.flops / pk_f, b.bytes / pk_b)
            for name, b in self.buckets.items()
        }
        t_total = sum(times.values()) or 1.0
        for name in BUCKETS:
            b = self.buckets.get(name)
            if b is None or (b.flops == 0 and b.bytes == 0):
                continue
            ai = b.flops / b.bytes if b.bytes else float("inf")
            bound = "compute" if ai >= balance else "memory"
            t = times[name]
            row = {
                "bucket": name,
                "flops": b.flops,
                "bytes": b.bytes,
                "ops": b.ops,
                "ai": round(ai, 3),
                "bound": bound,
                "min_time_ms": round(t * 1e3, 6),
                "min_time_share_pct": round(100.0 * t / t_total, 2),
            }
            if wall_s and wall_s > 0:
                est_t = (t / t_total) * wall_s
                peak_rate = pk_f if bound == "compute" else pk_b
                used = b.flops if bound == "compute" else b.bytes
                row["pct_peak"] = round(100.0 * used / (est_t * peak_rate), 2)
            rows.append(row)
        rows.sort(key=lambda r: -r["min_time_share_pct"])
        return rows

    def verdict(self, bucket: str, backend: Optional[str] = None) -> Optional[str]:
        for row in self.roofline(backend):
            if row["bucket"] == bucket:
                return row["bound"]
        return None

    def top_buckets(self, n: int = 3, backend: Optional[str] = None) -> List[Tuple[str, float]]:
        return [(r["bucket"], r["min_time_share_pct"]) for r in self.roofline(backend)[:n]]

    # -- serialization ------------------------------------------------------
    def to_record(self, backend: Optional[str] = None) -> Dict[str, Any]:
        return {
            "label": self.label,
            "backend": backend or self.backend,
            "module_flops": self.module_flops,
            "module_bytes": self.module_bytes,
            "unattributed_flops": self.unattributed_flops,
            "unattributed_bytes": self.unattributed_bytes,
            "roofline": self.roofline(backend),
            **self.meta,
        }

    def format_table(self, backend: Optional[str] = None) -> str:
        lines = [
            f"attribution [{self.label}] module: "
            f"{self.module_flops / 1e9:.3f} GFLOPs, "
            f"{self.module_bytes / 1e6:.1f} MB accessed",
            f"{'bucket':18s} {'GFLOPs':>10s} {'MB':>9s} {'AI':>8s} "
            f"{'bound':>8s} {'floor-ms':>9s} {'t-share%':>8s}",
        ]
        for r in self.roofline(backend):
            lines.append(
                f"{r['bucket']:18s} {r['flops'] / 1e9:10.4f} {r['bytes'] / 1e6:9.2f} "
                f"{r['ai']:8.2f} {r['bound']:>8s} {r['min_time_ms']:9.4f} "
                f"{r['min_time_share_pct']:8.2f}"
            )
        return "\n".join(lines)

    # -- publishing ---------------------------------------------------------
    def publish(self, manager) -> None:
        """Registry gauges + Perfetto counter tracks through a
        :class:`~deepspeed_tpu.telemetry.TelemetryManager` (one-shot at
        compile time — nothing here runs on the hot path)."""
        rows = self.roofline()
        if manager.registry.enabled:
            present = set()
            for r in rows:
                present.add(r["bucket"])
                g = lambda name: manager.gauge(name, bucket=r["bucket"])  # noqa: E731
                g("attribution/flops").set(r["flops"])
                g("attribution/bytes").set(r["bytes"])
                g("attribution/time_share_pct").set(r["min_time_share_pct"])
            # a recompile that drops a bucket must not leave its old
            # gauges reporting forever (same rule as the straggler
            # gauges): zero EXISTING handles for buckets absent from the
            # new table (never create handles just to zero them)
            for m in manager.registry.metrics():
                if (
                    m.kind == "gauge"
                    and m.name.startswith("attribution/")
                    and m.labels.get("engine") == manager.label
                    and m.labels.get("bucket") not in present
                    and m.value
                ):
                    m.set(0.0)
        tracer = getattr(manager, "tracer", None)
        if tracer is not None and tracer.enabled and rows:
            # ONE "C" sample carrying the whole series — Perfetto stacks
            # the args keys into per-bucket tracks on one timestamp
            tracer.add_counter(
                f"attribution/{self.label}/time_share_pct",
                {r["bucket"]: r["min_time_share_pct"] for r in rows},
            )


# ---------------------------------------------------------------------------
# the HLO walk
# ---------------------------------------------------------------------------

def attribute_hlo_text(
    hlo_text: str,
    module_cost: Optional[Dict[str, float]] = None,
    label: str = "module",
    backend: Optional[str] = None,
) -> Attribution:
    """Walk optimized HLO text into a calibrated bucket table.

    FLOPs are computed analytically per instruction (dots:
    ``2·|out|·Πcontracted``; elementwise: one per output element; reduce:
    one per input element) and bytes per *top-level* instruction
    (operands + result — fusion bodies are internal traffic and free).
    The module-level ``cost_analysis()`` numbers are authoritative: the
    unattributed remainder is folded into ``layernorm/other`` so bucket
    totals sum to the module cost exactly; an analytic *over*-count is
    scaled back proportionally (both recorded)."""
    buckets: Dict[str, BucketCost] = {b: BucketCost() for b in BUCKETS}

    # pass 1: find fusion-body computations (their instructions carry
    # flops attribution but NOT byte traffic)
    fused = set(_CALLS_RE.findall(hlo_text))

    current: Optional[str] = None
    for line in hlo_text.splitlines():
        if not line:
            continue
        if not line.startswith(" "):
            m = _COMP_RE.match(line)
            if m is not None:
                current = m.group("name")
            continue
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        opcode = m.group("opcode")
        if opcode in ("parameter", "constant", "tuple", "get-tuple-element"):
            continue
        out_type = m.group("type")
        rest = m.group("rest")
        meta = _META_RE.search(rest)
        op_name = meta.group("op") if meta else ""
        source = (meta.group("src") or "") if meta else ""
        bucket = classify(opcode, op_name, source)
        bc = buckets[bucket]
        bc.ops += 1

        out_elems, out_bytes = _shape_elems_bytes(out_type)
        # flops — attributed wherever the instruction lives
        if opcode == "dot":
            bc.flops += _dot_flops(out_type, rest)
        elif opcode in _ELEMENTWISE:
            bc.flops += out_elems
        elif opcode in ("reduce", "reduce-window"):
            operand = _SHAPE_RE.search(rest)
            if operand is not None:
                n = 1
                for d in (operand.group(2).split(",") if operand.group(2) else []):
                    n *= int(d)
                bc.flops += n
        elif opcode == "convolution":
            bc.flops += 2.0 * out_elems  # lower bound; residual calibrates

        # bytes — only top-level (non-fusion-body) instructions touch
        # HBM; bitcasts are layout bookkeeping, not traffic
        if current in fused or opcode == "bitcast":
            continue
        operand_bytes = 0
        # strip trailing metadata/attrs before scanning operand types so
        # attribute payloads (e.g. replica_groups) don't count as shapes
        arg_section = rest.split("), ")[0] if "), " in rest else rest
        arg_section = arg_section.split(", metadata=")[0]
        for dt, dims in _SHAPE_RE.findall(arg_section):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in (dims.split(",") if dims else []):
                n *= int(d)
            operand_bytes += n * _DTYPE_BYTES[dt]
        bc.bytes += out_bytes + operand_bytes

    module_cost = module_cost or {}
    module_flops = float(module_cost.get("flops", 0.0) or 0.0)
    from deepspeed_tpu.profiling.flops_profiler import cost_bytes

    module_bytes = float(cost_bytes(module_cost))

    unattr_flops = _calibrate(buckets, "flops", module_flops)
    unattr_bytes = _calibrate(buckets, "bytes", module_bytes)
    return Attribution(
        label=label,
        buckets=buckets,
        module_flops=module_flops or sum(b.flops for b in buckets.values()),
        module_bytes=module_bytes or sum(b.bytes for b in buckets.values()),
        unattributed_flops=unattr_flops,
        unattributed_bytes=unattr_bytes,
        backend=backend,
    )


def _calibrate(buckets: Dict[str, BucketCost], attr: str, module_total: float) -> float:
    """Fold the unattributed remainder into ``layernorm/other`` (or
    scale an overcount back) so ``sum(buckets) == module_total``.
    Returns the signed residual that was applied."""
    if module_total <= 0:
        return 0.0
    attributed = sum(getattr(b, attr) for b in buckets.values())
    residual = module_total - attributed
    other = buckets[OTHER]
    if residual >= 0:
        setattr(other, attr, getattr(other, attr) + residual)
        return residual
    # overcount: shrink `other` first, then scale every bucket
    take = min(getattr(other, attr), -residual)
    setattr(other, attr, getattr(other, attr) - take)
    remaining = sum(getattr(b, attr) for b in buckets.values())
    if remaining > 0 and remaining > module_total:
        scale = module_total / remaining
        for b in buckets.values():
            setattr(b, attr, getattr(b, attr) * scale)
    return residual


def _module_cost(compiled) -> Dict[str, float]:
    try:
        cost = compiled.cost_analysis() or {}
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        import numpy as np

        return {k: float(v) for k, v in cost.items() if np.isscalar(v)}
    except Exception:  # noqa: BLE001 — attribution is best-effort evidence
        return {}


def attribute_executable(
    compiled,
    label: str = "module",
    backend: Optional[str] = None,
    module_cost: Optional[Dict[str, float]] = None,
    max_hlo_mb: float = 256.0,
) -> Optional[Attribution]:
    """Attribute one compiled executable (``jit(...).lower().compile()``
    result, or the engine's cached train-step executable).  Returns None
    when the HLO text is unavailable or over the size cap (a fully
    unrolled XL module can reach hundreds of MB of text; the cap keeps
    compile-time hooks bounded)."""
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — some backends ship no text
        return None
    if not text or len(text) > max_hlo_mb * 1e6:
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    return attribute_hlo_text(
        text, module_cost=module_cost or _module_cost(compiled),
        label=label, backend=backend,
    )


def attribute_jit(fn, *args, label: str = "fn", static_argnums=(),
                  backend: Optional[str] = None) -> Optional[Attribution]:
    """AOT lower+compile ``fn(*args)`` and attribute it (tools/tests;
    no execution happens)."""
    import jax

    # AOT analysis only (never executed): layout is irrelevant, the walk
    # reads whatever GSPMD produced
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(*args).compile()  # ds-lint: disable=bare-jit
    return attribute_executable(compiled, label=label, backend=backend)


# ---------------------------------------------------------------------------
# analytic pins (the 6N check bench.py and the tests share)
# ---------------------------------------------------------------------------

def analytic_matmul_flops(n_params: int, tokens: int, n_devices: int = 1) -> float:
    """The ``6N`` analytic training count for the parameter matmuls
    (fwd 2N + bwd 4N per token), per device — what the ``matmul`` bucket
    of a full train step should show (attention-score math lives in the
    ``attention`` bucket and is excluded here, unlike bench.py's
    whole-step ``6N + 12·L·D·s`` MFU count)."""
    return 6.0 * float(n_params) * float(tokens) / max(1, int(n_devices))


# ---------------------------------------------------------------------------
# the shared jax.profiler trace cost-walk (tools/profile_*.py)
# ---------------------------------------------------------------------------

_SKIP_CATEGORIES = ("while", "conditional", "call")


def load_profiler_trace(trace_dir: str) -> List[Dict[str, Any]]:
    """Newest ``*.trace.json.gz`` under a ``jax.profiler.trace`` output
    dir → the device-op events (complete spans with an
    ``hlo_category``), control-flow wrappers dropped."""
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz"))
    )
    if not paths:
        raise FileNotFoundError(f"no profiler trace under {trace_dir}")
    with gzip.open(paths[-1]) as fh:
        data = json.load(fh)
    out = []
    for e in data.get("traceEvents", ()):
        if e.get("ph") != "X" or not e.get("args"):
            continue
        cat = e["args"].get("hlo_category")
        if not cat or cat in _SKIP_CATEGORIES:
            continue
        out.append(e)
    return out


def trace_tables(events: Iterable[Dict[str, Any]], denom: float = 1.0) -> Dict[str, Any]:
    """The per-source / per-HLO-category / top-op device-time tables the
    three profile tools all print.  ``denom`` divides durations (steps
    for a train profile, tokens for decode); TFLOP/s uses the trace's
    own ``model_flops``."""
    src_t: collections.Counter = collections.Counter()
    src_f: collections.Counter = collections.Counter()
    cat_t: collections.Counter = collections.Counter()
    cat_f: collections.Counter = collections.Counter()
    op_t: collections.Counter = collections.Counter()
    total = 0.0
    for e in events:
        dur = e.get("dur", 0)
        flops = int(e["args"].get("model_flops", 0) or 0)
        src = e["args"].get("source", "?")
        cat = e["args"]["hlo_category"]
        src_t[src] += dur
        src_f[src] += flops
        cat_t[cat] += dur
        cat_f[cat] += flops
        op_t[e.get("name", "?")[:70]] += dur
        total += dur

    def rows(t: collections.Counter, f: Optional[collections.Counter], n: int):
        out = []
        for key, dur in t.most_common(n):
            row = {"name": key, "ms": dur / 1e3 / denom}
            if f is not None:
                row["tflops"] = f[key] / (dur * 1e-6) / 1e12 if dur else 0.0
            out.append(row)
        return out

    return {
        "total_ms": total / 1e3 / denom,
        "by_source": rows(src_t, src_f, 20),
        "by_category": rows(cat_t, cat_f, 12),
        "top_ops": rows(op_t, None, 15),
    }


def format_trace_tables(tables: Dict[str, Any], unit: str = "step") -> str:
    lines = [f"total device time: {tables['total_ms']:.2f} ms/{unit}"]
    lines.append(f"\n{'source':68s} {'ms/' + unit:>9s} {'TFLOP/s':>8s}")
    for r in tables["by_source"]:
        lines.append(f"{r['name'][-68:]:68s} {r['ms']:9.2f} {r['tflops']:8.1f}")
    lines.append(f"\n{'hlo category':30s} {'ms/' + unit:>9s} {'TFLOP/s':>8s}")
    for r in tables["by_category"]:
        lines.append(f"{r['name']:30s} {r['ms']:9.2f} {r['tflops']:8.1f}")
    lines.append(f"\n{'top ops':70s} {'ms/' + unit:>9s}")
    for r in tables["top_ops"]:
        lines.append(f"{r['name']:70s} {r['ms']:9.2f}")
    return "\n".join(lines)


def profile_and_report(engine_step, trace_dir: Optional[str] = None,
                       steps: int = 3, unit: str = "step",
                       denom: Optional[float] = None,
                       sync=None) -> Dict[str, Any]:
    """Run ``engine_step()`` ``steps`` times under ``jax.profiler.trace``
    and return the cost tables (the whole body the three profile tools
    used to duplicate).  ``sync`` (e.g. ``lambda: float(loss)``) runs
    once INSIDE the trace window so async dispatch is fully captured;
    ``denom`` overrides the per-unit divisor (tokens for decode)."""
    import tempfile

    import jax

    trace_dir = trace_dir or tempfile.mkdtemp(prefix="ds_attr_trace_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            engine_step()
        if sync is not None:
            sync()
    tables = trace_tables(load_profiler_trace(trace_dir),
                          denom=denom if denom is not None else steps)
    tables["trace_dir"] = trace_dir
    return tables


# ---------------------------------------------------------------------------
# CLI: the perf-sentinel roofline artifact (8-device dryrun)
# ---------------------------------------------------------------------------

def _dryrun_roofline(out_path: Optional[str]) -> int:
    """Build the dryrun tiny train engine + serving decode executable,
    attribute both, print the tables, and (optionally) write the JSON
    artifact CI uploads."""
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    cfg = dataclasses.replace(gpt2.GPT2_TINY, remat=False,
                              scan_unroll=gpt2.GPT2_TINY.n_layer)
    model_fn, init_fn, tp_fn = gpt2.make_model(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_fn, model_parameters=init_fn(),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 10_000,
        },
        tp_spec_fn=tp_fn,
    )
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, cfg.vocab_size, (16, 16), dtype=np.int32)}
    engine.train_batch(batch)
    records = []
    attr = engine.train_step_attribution()
    if attr is not None:
        print(attr.format_table())
        records.append(attr.to_record())

    # serving decode executable (plain jit → on-demand AOT attribution)
    import jax.numpy as jnp

    from deepspeed_tpu.serving import ServingEngine

    inf = deepspeed_tpu.init_inference(
        model_config=gpt2.GPT2_TINY, params=gpt2.init_params(gpt2.GPT2_TINY),
        dtype=jnp.float32, max_out_tokens=gpt2.GPT2_TINY.n_positions,
    )
    srv = ServingEngine(inf, num_slots=2, prefill_chunk=8, max_len=32)
    dattr = srv.attribute_decode()
    if dattr is not None:
        print()
        print(dattr.format_table())
        records.append(dattr.to_record())

    if out_path:
        with open(out_path, "w") as f:
            json.dump({"schema": 1, "backend": jax.default_backend(),
                       "tables": records}, f, indent=1)
        print(f"\nroofline artifact -> {out_path}")
    return 0 if records else 1


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Per-kernel cost attribution roofline (8-device dryrun)"
    )
    p.add_argument("--out", default="", help="write the roofline JSON artifact here")
    args = p.parse_args(argv)
    return _dryrun_roofline(args.out or None)


if __name__ == "__main__":
    raise SystemExit(main())
