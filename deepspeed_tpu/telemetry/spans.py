"""Chrome-trace/Perfetto span buffer + ``trace.json`` export.

Every host-observable activity becomes a proper span in one
process-wide :class:`TraceBuffer`: StepTimeline phases (train AND
serving), async-checkpoint writer commits, comm decision instants, and
the serving per-request lifecycle (queue → prefill chunks → decode →
retire).  :meth:`TraceBuffer.export` writes the JSON-object form of the
Chrome trace-event format — load it in ``ui.perfetto.dev`` or
``chrome://tracing`` (docs/telemetry.md has the how-to and the track
layout).

Event vocabulary (the subset of the trace-event spec we emit):

* ``"ph": "X"`` — complete span: ``ts``/``dur`` in **microseconds**
  against the buffer's monotonic epoch;
* ``"ph": "i"`` — instant (retire markers, comm decisions, SLO
  breaches), ``"s": "t"`` (thread scope);
* ``"ph": "M"`` — metadata (``process_name``/``thread_name`` rows so
  Perfetto labels the tracks).

Track layout: ``pid`` groups a subsystem (0 = engine step phases,
1 = serving requests, 2 = checkpoint writer); ``tid`` separates lanes
inside it (request id for serving, 0 otherwise).

The buffer is a bounded ring (``maxlen`` events, oldest dropped,
``dropped`` counted) and every ``add_*`` starts with one ``enabled``
check — tracing off costs a pointer test at the call site.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

# subsystem pid lanes (metadata names are registered on first use)
PID_ENGINE = 0
PID_REQUESTS = 1
PID_CHECKPOINT = 2

_PID_NAMES = {
    PID_ENGINE: "engine step phases",
    PID_REQUESTS: "serving requests",
    PID_CHECKPOINT: "checkpoint writer",
}

_VALID_PH = {"X", "i", "M", "C"}


class TraceBuffer:
    def __init__(self, enabled: bool = False, max_events: int = 100_000):
        self.enabled = bool(enabled)
        self.max_events = max(1000, int(max_events))
        self.epoch = time.monotonic()
        self.dropped = 0
        self._events: deque = deque(maxlen=self.max_events)
        # (pid, tid|None) -> track name; kept OUT of the ring so the
        # process/thread name rows survive ring eviction on long runs
        self._meta: Dict[tuple, str] = {}
        self._lock = threading.Lock()

    def configure(self, enabled: Optional[bool] = None,
                  max_events: Optional[int] = None) -> "TraceBuffer":
        if max_events is not None and int(max_events) != self.max_events:
            self.max_events = max(1000, int(max_events))
            with self._lock:
                self._events = deque(self._events, maxlen=self.max_events)
        if enabled is not None:
            self.enabled = bool(enabled)
        return self

    # -- clock -------------------------------------------------------------
    def now(self) -> float:
        """The buffer's clock (``time.monotonic``) — span start/end
        stamps MUST come from this clock family or ordering breaks."""
        return time.monotonic()

    def _us(self, t: float) -> float:
        return (t - self.epoch) * 1e6

    # -- recording ---------------------------------------------------------
    def _push(self, ev: Dict[str, Any]) -> None:
        # the lock serializes writers against events()/clear() readers:
        # iterating a deque mid-append raises RuntimeError, which would
        # drop the atexit trace export
        with self._lock:
            if len(self._events) == self.max_events:
                self.dropped += 1
            self._events.append(ev)

    def _ensure_meta(self, pid: int, tid: int, tid_name: Optional[str] = None) -> None:
        # same lock as events(): the name table must not change size
        # under a concurrent export's iteration
        with self._lock:
            if (pid, None) not in self._meta:
                self._meta[(pid, None)] = _PID_NAMES.get(pid, f"pid {pid}")
            if tid_name and (pid, tid) not in self._meta:
                self._meta[(pid, tid)] = tid_name

    def _meta_events(self) -> List[Dict[str, Any]]:
        out = []
        for (pid, tid), name in sorted(self._meta.items(),
                                       key=lambda kv: (kv[0][0], kv[0][1] or 0)):
            if tid is None:
                out.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                            "args": {"name": name}})
            else:
                out.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                            "args": {"name": name}})
        return out

    def add_span(self, name: str, cat: str, start: float, end: float,
                 pid: int = PID_ENGINE, tid: int = 0,
                 args: Optional[Dict[str, Any]] = None,
                 tid_name: Optional[str] = None) -> None:
        """One complete "X" span; ``start``/``end`` are ``now()`` stamps."""
        if not self.enabled:
            return
        self._ensure_meta(pid, tid, tid_name)
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(self._us(start), 3),
              "dur": round(max(0.0, end - start) * 1e6, 3),
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self._push(ev)

    def add_counter(self, name: str, series: Dict[str, float],
                    ts: Optional[float] = None, pid: int = PID_ENGINE,
                    tid: int = 0) -> None:
        """One "C" counter sample: Perfetto renders each ``series`` key
        as a stacked counter track under ``name`` (the attribution
        module emits per-bucket time-share tracks this way)."""
        if not self.enabled:
            return
        self._ensure_meta(pid, tid)
        self._push({
            "name": name, "ph": "C",
            "ts": round(self._us(self.now() if ts is None else ts), 3),
            "pid": pid, "tid": tid,
            "args": {str(k): float(v) for k, v in series.items()},
        })

    def add_instant(self, name: str, cat: str, ts: Optional[float] = None,
                    pid: int = PID_ENGINE, tid: int = 0,
                    args: Optional[Dict[str, Any]] = None) -> None:
        if not self.enabled:
            return
        self._ensure_meta(pid, tid)
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": round(self._us(self.now() if ts is None else ts), 3),
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        self._push(ev)

    @contextmanager
    def span(self, name: str, cat: str, pid: int = PID_ENGINE, tid: int = 0,
             args: Optional[Dict[str, Any]] = None):
        """Time a host block into one span (no-op when disabled)."""
        if not self.enabled:
            yield
            return
        t0 = self.now()
        try:
            yield
        finally:
            self.add_span(name, cat, t0, self.now(), pid=pid, tid=tid, args=args)

    # -- export ------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Metadata rows first (rebuilt from the name table, immune to
        ring eviction), then the recorded span ring."""
        with self._lock:
            return self._meta_events() + list(self._events)

    def export(self, path: str, metadata: Optional[Dict[str, Any]] = None) -> str:
        """Write the Chrome trace-event JSON object to ``path``
        (atomically: tmp + replace, so a reader never sees a torn
        trace).  Returns the path."""
        doc = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "deepspeed_tpu.telemetry",
                "epoch_monotonic": self.epoch,
                "dropped_events": self.dropped,
                **(metadata or {}),
            },
        }
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._meta.clear()
            self.dropped = 0


def validate_chrome_trace(doc: Any) -> List[str]:
    """Validate a loaded ``trace.json`` against the Chrome trace-event
    schema (JSON-object form).  Returns a list of problems — empty means
    schema-valid.  Shared by tests and the CI telemetry smoke."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"top level must be an object, got {type(doc).__name__}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["'traceEvents' must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            problems.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing/empty 'name'")
        for key in ("pid", "tid"):
            if not isinstance(ev.get(key), int):
                problems.append(f"{where}: '{key}' must be an int")
        if ph in ("X", "i"):
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: 'ts' must be a non-negative number")
            if not isinstance(ev.get("cat"), str):
                problems.append(f"{where}: spans need a 'cat' string")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: 'dur' must be a non-negative number")
        if ph == "i" and ev.get("s") not in (None, "t", "p", "g"):
            problems.append(f"{where}: instant scope 's' must be t/p/g")
        if ph == "C":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: 'ts' must be a non-negative number")
            if not isinstance(ev.get("args"), dict) or not ev["args"]:
                problems.append(f"{where}: counter events need a non-empty 'args' object")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: 'args' must be an object")
    return problems
