"""Cross-rank metric aggregation over the supervision heartbeat channel.

Per-rank metric snapshots piggyback on the liveness beats the
supervision plane already sends (docs/resilience.md): each beat carries
the rank's :meth:`MetricsRegistry.snapshot_compact` as one compact JSON
payload, so cross-rank observability costs zero extra connections,
zero collectives, and nothing on the hot path (the beat thread already
exists and already wakes on its interval).

Rank 0's supervisor feeds a :class:`CrossRankAggregator`: per metric it
exports min/mean/max/n across the ranks it has heard from, and —
because the channel is the same one that detects death — a dead rank is
flagged **in the same stream** (``dead_ranks``), with its last-seen
snapshot retained so the post-mortem shows where it stopped.

The exported aggregate stream is JSONL (``aggregate_rank0.jsonl`` under
the telemetry output dir): one line per export with ``alive``/``dead``
rank lists and the per-metric min/mean/max table.  Rank 0's registry
also carries the roll-up as ``cluster/*`` gauges so the Prometheus /
TensorBoard exporters see the cluster view alongside the local one.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


def encode_metrics(compact: Dict[str, float]) -> str:
    """Beat-line payload: compact JSON with NO whitespace (the TCP beat
    protocol is whitespace-split) and values rounded upstream."""
    return json.dumps(compact, separators=(",", ":"), sort_keys=True)


def decode_metrics(payload: str) -> Optional[Dict[str, float]]:
    try:
        d = json.loads(payload)
    except ValueError:
        return None
    return d if isinstance(d, dict) else None


class CrossRankAggregator:
    """Rank-0 state: latest (seq, metrics) per rank + liveness marks."""

    def __init__(self, world_size: int, jsonl_path: Optional[str] = None,
                 registry=None, straggler_factor: float = 1.5):
        self.world_size = int(world_size)
        self.jsonl_path = os.path.abspath(jsonl_path) if jsonl_path else None
        self.registry = registry
        self.straggler_factor = float(straggler_factor)
        self._flagged_stragglers: set = set()
        self.exports = 0
        self._lock = threading.Lock()
        self._latest: Dict[int, Dict[str, float]] = {}
        self._seq: Dict[int, int] = {}
        self._dead: Dict[int, str] = {}
        self._bye: set = set()
        self._dirty = False
        if self.jsonl_path:
            d = os.path.dirname(self.jsonl_path)
            if d:
                os.makedirs(d, exist_ok=True)

    # -- feeding -----------------------------------------------------------
    def update(self, rank: int, seq: int, metrics: Optional[Dict[str, float]]) -> None:
        """Feed one rank's beat payload.  Only a strictly newer seq (or
        a first sighting) dirties the aggregator — the supervisor
        re-feeds the channel's latest table every poll cycle, and an
        unchanged beat must not grow the export stream."""
        if metrics is None:
            return
        with self._lock:
            if rank not in self._seq or seq > self._seq[rank]:
                self._seq[int(rank)] = int(seq)
                self._latest[int(rank)] = dict(metrics)
                self._dirty = True

    def mark_dead(self, rank: int, reason: str = "") -> None:
        with self._lock:
            if rank not in self._dead:
                self._dead[int(rank)] = reason
                self._dirty = True

    def mark_bye(self, rank: int) -> None:
        with self._lock:
            self._bye.add(int(rank))
            self._dirty = True

    @property
    def dirty(self) -> bool:
        return self._dirty

    # -- aggregation -------------------------------------------------------
    def aggregate(self) -> Dict[str, Any]:
        with self._lock:
            latest = {r: dict(m) for r, m in self._latest.items()}
            dead = dict(self._dead)
            bye = set(self._bye)
            seqs = dict(self._seq)
        alive = sorted(r for r in latest if r not in dead and r not in bye)
        names: Dict[str, List[float]] = {}
        # aggregate over LIVE ranks only — a dead rank's frozen counters
        # would drag every mean toward its moment of death
        for r in alive:
            for name, v in latest[r].items():
                names.setdefault(name, []).append(float(v))
        table = {
            name: {
                "min": min(vs), "mean": sum(vs) / len(vs), "max": max(vs),
                "n": len(vs),
            }
            for name, vs in sorted(names.items())
        }
        # runtime anomaly watch (regression.py): rank step wall vs the
        # cluster median, flagged in the SAME stream that detects death
        from deepspeed_tpu.telemetry.regression import find_stragglers

        stragglers = find_stragglers(
            latest, alive, factor=self.straggler_factor
        )
        return {
            "ts": time.time(),
            "world_size": self.world_size,
            "alive": alive,
            "stragglers": stragglers,
            "dead": [
                {"rank": r, "reason": reason, "last_seq": seqs.get(r),
                 "last_metrics": latest.get(r)}
                for r, reason in sorted(dead.items())
            ],
            "departed": sorted(bye),
            "metrics": table,
        }

    def export_line(self, force: bool = False) -> Optional[Dict[str, Any]]:
        """Append one aggregate record to the JSONL stream (and mirror
        it into ``cluster/*`` gauges) when anything changed since the
        last export.  Returns the record, or None when clean."""
        if not self._dirty and not force:
            return None
        agg = self.aggregate()
        self._dirty = False
        if self.registry is not None and self.registry.enabled:
            self.registry.gauge("cluster/alive_ranks").set(len(agg["alive"]))
            self.registry.gauge("cluster/dead_ranks").set(len(agg["dead"]))
            flagged = {s["rank"] for s in agg["stragglers"]}
            # rank count, not (rank, metric) pairs — consistent with the
            # sibling alive/dead rank gauges
            self.registry.gauge("cluster/stragglers").set(len(flagged))
            for s in agg["stragglers"]:
                self.registry.gauge(
                    "cluster/straggler_factor", rank=s["rank"]
                ).set(s["factor"])
            # a recovered rank must stop reading as a straggler: zero
            # the per-rank gauge the moment it drops off the list
            for rank in self._flagged_stragglers - flagged:
                self.registry.gauge("cluster/straggler_factor", rank=rank).set(0.0)
            self._flagged_stragglers = flagged
            for name, row in agg["metrics"].items():
                # qualified names may carry labels ({...}); keep them in
                # the gauge name verbatim — the cluster view is keyed by
                # what the ranks sent
                self.registry.gauge(f"cluster/{name}/mean").set(row["mean"])
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(agg) + "\n")
        self.exports += 1
        return agg
