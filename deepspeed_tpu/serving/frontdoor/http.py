"""Stdlib HTTP front-door over a serving engine (docs/serving.md
§Front-door).

One :class:`FrontDoor` wraps one :class:`~deepspeed_tpu.serving.engine.
ServingEngine` behind a ``ThreadingHTTPServer``:

* ``POST /v1/generate`` — submit a token-id prompt.  ``"stream": true``
  answers with a chunked (HTTP/1.1 ``Transfer-Encoding: chunked``)
  JSON-lines body: one ``{"tokens": [...]}`` delta per poll that found
  new tokens, then a final ``{"done": true, ...}`` line.  Without
  ``stream`` the handler blocks until the request retires and returns
  one JSON object.
* ``GET /healthz`` — liveness + drain/degrade state (503 while
  draining, so a balancer stops sending).
* ``GET /statsz`` — the engine's full stats tree, JSON.

Overload answers carry machine-readable backpressure: a queue-full or
tenant-throttled submit is HTTP 429, overload-shed and draining are
HTTP 503, and every one of them surfaces the scheduler's
``retry_after`` both as a ``Retry-After`` header (integer seconds,
per RFC 9110) and exactly in the JSON error body.

Client deadlines map onto scheduler deadlines: ``"deadline_seconds"``
in the body bounds the request's queue wait exactly like
``ServingEngine.submit(deadline_seconds=...)`` — an expired request
answers 503 with ``"finish_reason": "expired"``.

Graceful drain composes with the PR 10 watchdog: SIGTERM (via
``engine.install_watchdog()``) makes the pump thread's next
``engine.step()`` run the drain — admission stops (new submits answer
503 + Retry-After), in-flight requests keep decoding and stream out,
the journal commits, and only then does the process exit 43
(:func:`FrontDoor._pump` converts the engine's ``SystemExit`` into
``os._exit`` after the last active stream flushes).

Fault sites ``frontdoor.accept`` (request admission) and
``frontdoor.stream`` (every streamed chunk) feed the chaos matrix —
a ``sigkill`` plan at ``frontdoor.stream`` is the kill -9 mid-stream
proof (``tools/frontdoor_chaos.py``).
"""
from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving.scheduler import (
    ServingDraining,
    ServingOverloaded,
    ServingQueueFull,
)
from deepspeed_tpu.serving.frontdoor.tenants import TenantThrottled
from deepspeed_tpu.serving.frontdoor.transport import _json_safe
from deepspeed_tpu.utils.logging import logger


def _retry_after_header(retry_after: Optional[float]) -> Optional[str]:
    """RFC 9110 Retry-After is integer delta-seconds; round up so the
    client never retries early."""
    if retry_after is None:
        return None
    return str(max(0, int(math.ceil(float(retry_after)))))


def _status_for(exc: ServingQueueFull) -> int:
    """The satellite bugfix: the subclass distinction survives to the
    HTTP layer — queue-full and tenant-throttle are the client's fault
    (429 Too Many Requests), overload-shed and draining are the
    server's (503 Service Unavailable)."""
    if isinstance(exc, (ServingOverloaded, ServingDraining)):
        return 503
    return 429


class FrontDoor:
    """The HTTP surface over one engine.  ``start()`` binds the server
    and (by default) a pump thread that turns ``engine.step()``;
    ``serve_forever()`` instead runs the pump in the calling thread —
    the standalone-server mode, where the watchdog's drain
    ``SystemExit(43)`` must unwind the main thread."""

    def __init__(self, engine, config=None, host: Optional[str] = None,
                 port: Optional[int] = None):
        cfg = config if config is not None else getattr(
            engine.config, "frontdoor", None)
        self.engine = engine
        self.host = host if host is not None else (
            cfg.host if cfg is not None else "127.0.0.1")
        self._port = port if port is not None else (
            cfg.port if cfg is not None else 0)
        self.stream_poll_seconds = (
            cfg.stream_poll_seconds if cfg is not None else 0.01)
        self.max_body_bytes = (
            cfg.max_body_bytes if cfg is not None else 1 << 20)
        # ONE lock serializes every engine touch: the pump thread holds
        # it per step, handler threads per submit/poll — the engine
        # itself is not thread-safe
        self.lock = threading.RLock()
        self._streams = 0  # active chunked responses (drain barrier)
        self._streams_cv = threading.Condition()
        self._stop = threading.Event()
        # set once the engine's drain has committed the journal and the
        # process is about to exit: any still-unfinished request was
        # queued (or spilled), will replay after restart, and its
        # stream must be CUT, not waited on
        self._drain_exiting = threading.Event()
        self._server: Optional[ThreadingHTTPServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self._pump_thread: Optional[threading.Thread] = None
        self.requests_served = 0

    # -- lifecycle --------------------------------------------------------

    @property
    def port(self) -> int:
        return (self._server.server_address[1]
                if self._server is not None else self._port)

    def start(self, pump: bool = True) -> "FrontDoor":
        """Bind + serve in background threads; returns self (the bound
        ephemeral port is ``self.port``)."""
        self._bind()
        if pump:
            self._pump_thread = threading.Thread(
                target=self._pump, name="frontdoor-pump", daemon=True)
            self._pump_thread.start()
        return self

    def serve_forever(self) -> None:
        """Standalone-server mode: bind, serve HTTP in background
        threads, and run the pump in THIS thread so the watchdog's
        drain ``SystemExit(43)`` unwinds normally."""
        self._bind()
        self._pump()

    def _bind(self) -> None:
        if self._server is not None:
            return
        fd = self

        class Handler(_Handler):
            frontdoor = fd

        self._server = ThreadingHTTPServer((self.host, self._port), Handler)
        self._server.daemon_threads = True
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, name="frontdoor-http",
            daemon=True)
        self._server_thread.start()
        logger.info(f"frontdoor: serving on {self.host}:{self.port}")

    def close(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=10)
            self._pump_thread = None

    # -- the pump ---------------------------------------------------------

    def _pump(self) -> None:
        """Turn the engine until stopped.  A drain signal surfaces as
        ``SystemExit`` out of ``engine.step()`` (journal already
        committed) — wait for active streams to flush their final
        chunk, then exit the PROCESS with the watchdog's code: exit 43
        only after journal commit AND stream-out."""
        try:
            while not self._stop.is_set():
                with self.lock:
                    busy = self.engine.step()
                if not busy:
                    time.sleep(self.stream_poll_seconds)
        except SystemExit as e:
            code = 0 if e.code is None else int(e.code)
            self._drain_exiting.set()
            self._await_streams(timeout=30.0)
            logger.info(f"frontdoor: drained; exiting {code}")
            os._exit(code)

    def _await_streams(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        with self._streams_cv:
            while self._streams > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    logger.warning(
                        f"frontdoor: {self._streams} stream(s) still "
                        "active at drain-exit deadline")
                    return
                self._streams_cv.wait(left)

    def _stream_enter(self) -> None:
        with self._streams_cv:
            self._streams += 1

    def _stream_exit(self) -> None:
        with self._streams_cv:
            self._streams -= 1
            self._streams_cv.notify_all()

    # -- engine access (all under self.lock) ------------------------------

    def submit(self, body: Dict[str, Any]) -> int:
        prompt = body.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise ValueError("'prompt' must be a non-empty list of token ids")
        kw: Dict[str, Any] = {}
        for key in ("max_new_tokens", "eos_token_id", "top_k", "seed",
                    "priority"):
            if body.get(key) is not None:
                kw[key] = int(body[key])
        for key in ("deadline_seconds", "temperature"):
            if body.get(key) is not None:
                kw[key] = float(body[key])
        if body.get("deadline_ms") is not None:
            kw["deadline_seconds"] = float(body["deadline_ms"]) / 1000.0
        if body.get("do_sample") is not None:
            kw["do_sample"] = bool(body["do_sample"])
        for key in ("client_key", "session_id", "tenant"):
            if body.get(key) is not None:
                kw[key] = str(body[key])
        with self.lock:
            return self.engine.submit(np.asarray(prompt, np.int32), **kw)

    def poll(self, rid: int) -> Optional[Dict[str, Any]]:
        """Tokens generated so far + finish state — the stream chunk
        source (the `partial` RPC op's twin)."""
        with self.lock:
            r = self.engine.result(rid)
            if r is None:
                return None
            return {
                "generated": [int(t) for t in getattr(r, "generated", [])],
                "finished": r.finish_time is not None,
                "finish_reason": r.finish_reason,
            }

    def retire(self, rid: int) -> None:
        """Drop a fully-answered request from the finished map (the
        front-door owns the engine; nothing else pops results)."""
        with self.lock:
            self.engine.scheduler._finished.pop(rid, None)

    def health(self) -> Dict[str, Any]:
        with self.lock:
            eng = self.engine
            wd = eng._watchdog
            return {
                "ok": True,
                "draining": bool(wd is not None and wd.draining),
                "queue_depth": int(eng.scheduler.queue_depth),
                "degrade_level": int(eng.scheduler.ladder.level),
            }

    def stats(self) -> Dict[str, Any]:
        with self.lock:
            return _json_safe(self.engine.stats())


class _Handler(BaseHTTPRequestHandler):
    frontdoor: FrontDoor  # bound by FrontDoor._bind's subclass
    protocol_version = "HTTP/1.1"

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        logger.debug(f"frontdoor: {self.address_string()} {format % args}")

    # -- plumbing ---------------------------------------------------------

    def _send_json(self, status: int, obj: Dict[str, Any],
                   retry_after: Optional[float] = None) -> None:
        payload = json.dumps(obj).encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        ra = _retry_after_header(retry_after)
        if ra is not None:
            self.send_header("Retry-After", ra)
        self.end_headers()
        self.wfile.write(payload)

    def _send_error_for(self, exc: BaseException) -> None:
        if isinstance(exc, ServingQueueFull):
            ra = getattr(exc, "retry_after", None)
            self._send_json(
                _status_for(exc),
                {"error": str(exc), "type": type(exc).__name__,
                 "retry_after": ra},
                retry_after=ra,
            )
        elif isinstance(exc, ValueError):
            self._send_json(400, {"error": str(exc), "type": "ValueError"})
        else:
            self._send_json(
                500, {"error": str(exc), "type": type(exc).__name__})

    def _read_body(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length > self.frontdoor.max_body_bytes:
            raise ValueError(
                f"request body {length} bytes exceeds cap "
                f"{self.frontdoor.max_body_bytes}")
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"request body is not JSON: {e}") from e
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        return body

    # -- routes -----------------------------------------------------------

    def do_GET(self):  # noqa: N802 — stdlib dispatch name
        try:
            if self.path == "/healthz":
                h = self.frontdoor.health()
                self._send_json(503 if h["draining"] else 200, h)
            elif self.path == "/statsz":
                self._send_json(200, self.frontdoor.stats())
            else:
                self._send_json(404, {"error": f"no route {self.path}"})
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — must answer something
            try:
                self._send_error_for(e)
            except OSError:
                pass

    def do_POST(self):  # noqa: N802 — stdlib dispatch name
        if self.path != "/v1/generate":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        try:
            faults.check("frontdoor.accept")
            faults.check_latency("frontdoor.accept")
            body = self._read_body()
            rid = self.frontdoor.submit(body)
        except BrokenPipeError:
            return
        except Exception as e:  # noqa: BLE001 — becomes the HTTP error
            try:
                self._send_error_for(e)
            except OSError:
                pass
            return
        if body.get("stream"):
            self._stream_response(rid, body)
        else:
            self._block_response(rid)
        self.frontdoor.requests_served += 1

    # -- response modes ---------------------------------------------------

    def _block_response(self, rid: int) -> None:
        poll = self.frontdoor.stream_poll_seconds
        while True:
            r = self.frontdoor.poll(rid)
            if r is None:
                self._send_json(
                    500, {"error": f"request {rid} vanished", "request_id": rid})
                return
            if r["finished"]:
                break
            time.sleep(poll)
        self.frontdoor.retire(rid)
        status = 200 if r["finish_reason"] in ("eos", "length") else 503
        self._send_json(status, {
            "request_id": rid,
            "tokens": r["generated"],
            "finish_reason": r["finish_reason"],
            "n_tokens": len(r["generated"]),
        })

    def _write_chunk(self, obj: Dict[str, Any]) -> None:
        # every streamed chunk is a fault site: a sigkill plan here IS
        # the kill -9 mid-stream proof (tools/frontdoor_chaos.py)
        faults.check("frontdoor.stream")
        faults.check_latency("frontdoor.stream")
        data = (json.dumps(obj) + "\n").encode("utf-8")
        self.wfile.write(f"{len(data):x}\r\n".encode("ascii"))
        self.wfile.write(data + b"\r\n")
        self.wfile.flush()

    def _stream_response(self, rid: int, body: Dict[str, Any]) -> None:
        self.frontdoor._stream_enter()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/jsonlines")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._write_chunk({"request_id": rid})
            sent = 0
            poll = self.frontdoor.stream_poll_seconds
            while True:
                r = self.frontdoor.poll(rid)
                if r is None:
                    # vanished mid-stream (recovery raced us): the
                    # missing terminating chunk tells the client
                    return
                if len(r["generated"]) > sent:
                    self._write_chunk({"tokens": r["generated"][sent:]})
                    sent = len(r["generated"])
                if r["finished"]:
                    break
                if self.frontdoor._drain_exiting.is_set():
                    # drain committed with this request unfinished — it
                    # was queued (never held a slot) or spilled, and
                    # will replay from the journal after restart.  Cut
                    # the stream so the client retries its client_key.
                    return
                time.sleep(poll)
            self.frontdoor.retire(rid)
            self._write_chunk({
                "done": True,
                "finish_reason": r["finish_reason"],
                "n_tokens": sent,
            })
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; generation retires on its own
        finally:
            self.frontdoor._stream_exit()


__all__ = ["FrontDoor"]
