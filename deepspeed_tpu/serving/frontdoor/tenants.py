"""Multi-tenant fairness, rate limiting, and billing-grade accounting
(docs/serving.md §Front-door).

The north star is many tenants sharing one fleet where the quiet
tenant never pays for the noisy one.  Four mechanisms, all keyed by the
request's ``tenant`` label:

* **token-bucket rate limits** — each tenant refills at
  ``refill_tokens_per_second`` up to ``burst_tokens``; a submit costs
  ``prompt_len + max_new_tokens`` (the reserved capacity, not the
  realized one — realized usage is billed at retire).  An empty bucket
  raises :class:`TenantThrottled` (a ``ServingQueueFull`` subclass, so
  the ``retry_after`` hint survives the RPC codec and becomes an HTTP
  429).  Fault site ``tenant.refill`` perturbs the refill path.
* **weighted-fair queueing** — ahead of the priority tiers: start-time
  fair queueing tags every submit with a per-tenant virtual start time
  advanced by ``cost / weight``; the scheduler pops the tenant with the
  lowest outstanding tag, then priority-then-FIFO *within* that tenant.
  A tenant flooding the queue advances its own virtual clock far past
  the quiet tenant's, so the quiet tenant's next request still pops
  first.
* **SLO classes** — ``gold``/``silver``/``bronze`` map onto the
  existing priority tiers (0/1/2) and therefore onto the PR 10
  degradation ladder: bronze is shed first at rung 3, gold bypasses the
  estimated-TTFT admission test.
* **quotas + accounting** — per-tenant caps on paged-KV pages and
  pinned prefixes (enforced in ``kvcache/``), and per-tenant counters
  (admitted / rejected / throttled / billed tokens) whose journal twin
  (:func:`journal_tenant_totals`) reconciles exactly across a
  front-door crash + ``recover()``: admission is journaled with a
  ``tn`` key before the ack, realized tokens are journaled in the
  retire record, and replays bypass the bucket (no double-charge).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.serving.scheduler import ServingQueueFull
from deepspeed_tpu.utils.logging import logger

DEFAULT_TENANT = "default"

#: SLO class → priority tier (0 high / 1 normal / 2 low).  The tier is
#: what the scheduler's admission test + degradation ladder act on, so
#: the class mapping IS the ladder mapping (docs/serving.md §Front-door).
SLO_CLASSES: Dict[str, int] = {"gold": 0, "silver": 1, "bronze": 2}


class TenantThrottled(ServingQueueFull):
    """Per-tenant rate limit exceeded.  Carries ``retry_after`` — the
    seconds until the bucket holds the request's cost again — and
    round-trips the RPC codec as itself (HTTP 429 + Retry-After)."""


class TokenBucket:
    """Classic token bucket with exact accounting: ``refilled`` and
    ``consumed`` are monotone totals the race harness checks against
    ``tokens`` (``burst + refilled - consumed == tokens`` always, no
    lost updates).  NOT internally locked — the registry serializes
    access (one lock, instrumentable by ds_race)."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.refilled = 0.0
        self.consumed = 0.0
        self._updated: Optional[float] = None

    def refill(self, now: float) -> None:
        """Fault site ``tenant.refill``: an injected failure aborts the
        whole operation BEFORE any state moves, so accounting never
        tears."""
        faults.check("tenant.refill")
        faults.check_race("race.tenant.refill")
        if self._updated is None:
            self._updated = now
            return
        dt = max(now - self._updated, 0.0)
        self._updated = now
        if dt <= 0.0 or self.rate <= 0.0:
            return
        add = min(dt * self.rate, self.burst - self.tokens)
        if add > 0.0:
            self.tokens += add
            self.refilled += add

    def take(self, cost: float, now: float) -> Optional[float]:
        """Consume ``cost`` tokens; returns None on success or the
        seconds until the bucket could cover the cost (the throttle's
        ``retry_after``)."""
        self.refill(now)
        if self.tokens >= cost:
            self.tokens -= cost
            self.consumed += cost
            return None
        if self.rate <= 0.0:
            return 60.0  # bucket can never refill; arbitrary long hint
        return max((cost - self.tokens) / self.rate, 1e-3)


class TenantState:
    """One tenant's live state: spec knobs, bucket, WFQ virtual clock
    and the accounting counters ``stats()`` / the bench read."""

    def __init__(self, name: str, spec: Dict[str, Any]):
        self.name = name
        self.weight = max(float(spec.get("weight", 1.0)), 1e-6)
        self.slo_class = str(spec.get("slo_class", "silver"))
        self.kv_pages_max = int(spec.get("kv_pages_max", 0))
        self.pinned_prefixes_max = int(spec.get("pinned_prefixes_max", 0))
        self.bucket = TokenBucket(
            rate=float(spec.get("refill_tokens_per_second", 0.0)),
            burst=float(spec.get("burst_tokens", 0.0)),
        )
        self.last_tag = 0.0  # WFQ virtual start time of the latest submit
        self.counters: Dict[str, float] = {
            "submitted": 0, "admitted": 0, "throttled": 0, "rejected": 0,
            "shed": 0, "expired": 0, "cancelled": 0, "finished": 0,
            "replayed": 0, "billed_tokens": 0, "quota_defers": 0,
        }

    @property
    def priority(self) -> int:
        return SLO_CLASSES.get(self.slo_class, 1)


class TenantRegistry:
    """The tenant table the engine, scheduler and paged pool share.

    One lock covers the buckets and the WFQ clocks — deliberately
    coarse (host-side dict math, nanoseconds) and exposed as ``_lock``
    so the ds_race harness can instrument it."""

    def __init__(self, config=None):
        self._lock = threading.Lock()
        self._states: Dict[str, TenantState] = {}
        self._vtime = 0.0  # global WFQ virtual time (advances on pop)
        self._defaults: Dict[str, Any] = {}
        self._overrides: Dict[str, Dict[str, Any]] = {}
        self.rate_limit_enabled = True
        if config is not None:
            self._defaults = {
                "refill_tokens_per_second": config.refill_tokens_per_second,
                "burst_tokens": config.burst_tokens,
                "weight": config.weight,
                "slo_class": config.slo_class,
                "kv_pages_max": config.kv_pages_max,
                "pinned_prefixes_max": config.pinned_prefixes_max,
            }
            self._overrides = {
                name: dict(spec) for name, spec in config.overrides.items()
            }

    # -- state table -------------------------------------------------------
    def state(self, tenant: Optional[str]) -> TenantState:
        name = tenant or DEFAULT_TENANT
        st = self._states.get(name)
        if st is None:
            spec = dict(self._defaults)
            spec.update(self._overrides.get(name, {}))
            st = TenantState(name, spec)
            self._states[name] = st
        return st

    def names(self):
        return sorted(self._states)

    # -- admission ---------------------------------------------------------
    def admit(self, tenant: Optional[str], cost: float, now: float) -> None:
        """Charge the tenant's bucket for a submit; raises
        :class:`TenantThrottled` (with the refill-time ``retry_after``)
        when the bucket cannot cover it.  A zero-rate zero-burst spec
        means 'unlimited' (rate limiting off for that tenant)."""
        with self._lock:
            st = self.state(tenant)
            st.counters["submitted"] += 1
            if not self.rate_limit_enabled or (
                st.bucket.rate <= 0.0 and st.bucket.burst <= 0.0
            ):
                return
            retry = st.bucket.take(float(cost), now)
            if retry is None:
                return
            st.counters["throttled"] += 1
        raise TenantThrottled(
            f"tenant {st.name!r} rate limit: cost {cost:g} exceeds bucket "
            f"({st.bucket.tokens:.1f} of {st.bucket.burst:g} tokens, refill "
            f"{st.bucket.rate:g}/s); retry after ~{retry:.2f}s",
            retry_after=retry,
        )

    def priority_for(self, tenant: Optional[str], explicit: Optional[int]) -> int:
        """The request's priority tier: an explicit caller choice wins,
        otherwise the tenant's SLO class decides."""
        if explicit is not None:
            return int(explicit)
        with self._lock:
            return self.state(tenant).priority

    # -- weighted-fair queueing -------------------------------------------
    def tag(self, tenant: Optional[str], cost: float) -> float:
        """Start-time fair queueing: the submit's virtual start time is
        ``max(global vtime, tenant's last tag)``; the tenant's clock
        then advances by ``cost / weight``."""
        with self._lock:
            st = self.state(tenant)
            start = max(self._vtime, st.last_tag)
            st.last_tag = start + float(cost) / st.weight
            return start

    def pick(self, queue) -> int:
        """The scheduler's pop policy with tenants armed: choose the
        tenant with the LOWEST outstanding virtual tag (fairness ahead
        of the tiers), then priority-then-FIFO within that tenant.
        Returns the queue index to pop."""
        with self._lock:
            tags: Dict[str, float] = {}
            for r in queue:
                t = r.tenant or DEFAULT_TENANT
                tag = r.wfq_tag
                if t not in tags or tag < tags[t]:
                    tags[t] = tag
            winner = min(tags, key=lambda t: (tags[t], t))
            best_i, best = 0, None
            for i, r in enumerate(queue):
                if (r.tenant or DEFAULT_TENANT) != winner:
                    continue
                if best is None or r.priority < best.priority:
                    best_i, best = i, r
                    if r.priority == 0:
                        break
            self._vtime = max(self._vtime, best.wfq_tag)
            return best_i

    # -- accounting --------------------------------------------------------
    def note(self, kind: str, tenant: Optional[str], n: float = 1) -> None:
        with self._lock:
            st = self.state(tenant)
            if kind in st.counters:
                st.counters[kind] += n

    def bill(self, tenant: Optional[str], tokens: int) -> None:
        """Realized usage at retire — the journal's ``n`` twin, so the
        in-memory ledger and :func:`journal_tenant_totals` agree."""
        with self._lock:
            st = self.state(tenant)
            st.counters["finished"] += 1
            st.counters["billed_tokens"] += int(tokens)

    # -- kv quotas ---------------------------------------------------------
    def kv_pages_max(self, tenant: Optional[str]) -> int:
        with self._lock:
            return self.state(tenant).kv_pages_max

    def pinned_prefixes_max(self, tenant: Optional[str]) -> int:
        with self._lock:
            return self.state(tenant).pinned_prefixes_max

    def note_quota_defer(self, tenant: Optional[str]) -> None:
        self.note("quota_defers", tenant)

    # -- introspection -----------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for name, st in self._states.items():
                out[name] = dict(st.counters)
                out[name].update({
                    "weight": st.weight,
                    "slo_class": st.slo_class,
                    "priority": st.priority,
                    "bucket_tokens": st.bucket.tokens,
                    "bucket_burst": st.bucket.burst,
                    "bucket_rate": st.bucket.rate,
                })
            return out


# ---------------------------------------------------------------------------
# journal reconciliation
# ---------------------------------------------------------------------------

def journal_tenant_totals(journal_dir: str) -> Dict[str, Dict[str, int]]:
    """Replay the request journal into per-tenant totals — the durable
    twin of :meth:`TenantRegistry.snapshot`, and the reconciliation
    oracle for the crash tests: ``admitted`` counts distinct journaled
    submits (latest-wins by id, so a recover()'s re-journal does not
    double-count) and ``billed_tokens`` sums the retire records'
    realized token counts (at most one retire per id — no double-bill
    across a crash)."""
    from deepspeed_tpu.serving import journal as _journal

    submits: Dict[int, Optional[str]] = {}
    billed: Dict[int, int] = {}
    rejected: Dict[int, Optional[str]] = {}
    for rec in _journal.read_records(journal_dir):
        t = rec.get("t")
        rid = int(rec.get("id", -1))
        if t == "submit":
            submits[rid] = rec.get("tn")
        elif t == "retire":
            if rec.get("reason") != "cancelled":
                billed[rid] = int(rec.get("n", 0))
        elif t == "reject":
            rejected[rid] = submits.get(rid)
    out: Dict[str, Dict[str, int]] = {}

    def row(tenant: Optional[str]) -> Dict[str, int]:
        name = tenant or DEFAULT_TENANT
        return out.setdefault(
            name, {"admitted": 0, "billed_tokens": 0, "retired": 0,
                   "rejected": 0})

    for rid, tenant in submits.items():
        row(tenant)["admitted"] += 1
    for rid, n in billed.items():
        r = row(submits.get(rid))
        r["billed_tokens"] += n
        r["retired"] += 1
    for rid, tenant in rejected.items():
        row(tenant)["rejected"] += 1
    return out


__all__ = [
    "DEFAULT_TENANT", "SLO_CLASSES", "TenantThrottled", "TokenBucket",
    "TenantState", "TenantRegistry", "journal_tenant_totals",
]
