"""serving/frontdoor/ — multi-tenant front-door (docs/serving.md
§Front-door).

Three layers ahead of the serving engine:

- ``transport.py`` — the transport-agnostic RPC replica boundary: one
  wire codec (op dispatch + exception registry + crc-framed binary
  frames) shared by an in-process transport and socket / child-process
  stream transports, so the fleet router, supervisor and autoscaler
  drive local and remote replicas through one duck surface.
- ``tenants.py`` — the tenant dimension: token-bucket admission rates,
  weighted-fair queueing ahead of priority tiers, SLO classes mapped
  onto scheduler priorities, paged-KV page / pinned-prefix quotas, and
  tenant-attributed accounting that reconciles exactly against the
  request journal across a crash.
- ``http.py`` — the stdlib HTTP surface: chunked streaming token
  responses, request deadlines mapped to scheduler deadlines,
  ``Retry-After``-bearing 429/503 answers, and SIGTERM graceful drain
  composed with the serving watchdog (exit 43 after journal commit).
"""
from deepspeed_tpu.serving.frontdoor.tenants import (
    DEFAULT_TENANT,
    SLO_CLASSES,
    TenantRegistry,
    TenantThrottled,
    journal_tenant_totals,
)
from deepspeed_tpu.serving.frontdoor.transport import (
    InProcTransport,
    LoopbackTransport,
    ProcessTransport,
    SocketTransport,
    StreamTransport,
    TransportFrameError,
    TransportReplica,
    dispatch,
    raise_wire,
    read_frame,
    serve_socket,
    serve_stdio,
    serve_stream,
    wrap_replica,
    write_frame,
)
from deepspeed_tpu.serving.frontdoor.http import FrontDoor

__all__ = [
    "DEFAULT_TENANT",
    "SLO_CLASSES",
    "TenantRegistry",
    "TenantThrottled",
    "journal_tenant_totals",
    "InProcTransport",
    "LoopbackTransport",
    "ProcessTransport",
    "SocketTransport",
    "StreamTransport",
    "TransportFrameError",
    "TransportReplica",
    "dispatch",
    "raise_wire",
    "read_frame",
    "serve_socket",
    "serve_stdio",
    "serve_stream",
    "wrap_replica",
    "write_frame",
    "FrontDoor",
]
