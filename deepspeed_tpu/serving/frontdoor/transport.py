"""Transport-agnostic RPC boundary for fleet replicas (docs/serving.md
§Front-door).

PRs 14–17 route against a duck-typed replica surface; the only wire
implementation lived in ``tools/fleet_chaos.py`` as an ad-hoc JSONL
pipe.  This module promotes that protocol to a first-class boundary:

* **one codec** — :func:`dispatch` maps op dicts onto the replica
  surface and :func:`encode_error` / :func:`raise_wire` round-trip the
  serving exception taxonomy (``ServingQueueFull`` / ``Overloaded`` /
  ``Draining`` reconstruct as their EXACT class with ``retry_after``
  intact — previously any process boundary collapsed them and the
  client lost the backoff hint);
* **two transports** — :class:`InProcTransport` (direct dispatch, no
  serialization fidelity loss for same-process fleets) and
  :class:`StreamTransport` (length-prefixed, crc-framed JSON over any
  byte stream: a socket, or a child process's stdio pipes via
  :class:`ProcessTransport`);
* **one replica** — :class:`TransportReplica` implements the full
  fleet surface over either transport, so ``FleetRouter``,
  ``ReplicaSupervisor`` and ``FleetAutoscaler`` work unchanged.

Framing (the socket codec): ``b"DSRP" + len:u32be + crc32:u32be +
payload`` where payload is UTF-8 JSON.  The frame reader treats ANY
defect — short header, bad magic, oversized length, short payload, crc
mismatch, non-JSON bytes — as :class:`TransportFrameError`; the
transport maps that (and EOF) to ``ReplicaDeadError`` and marks itself
dead, so a torn frame takes the breaker + supervisor path and never
hangs the router.  Fault site ``transport.frame`` perturbs the framer
(fail / latency / stall) for the chaos matrix.
"""
from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.faults import InjectedFault
from deepspeed_tpu.serving.fleet.replica import ReplicaDeadError
from deepspeed_tpu.serving.frontdoor.tenants import TenantThrottled
from deepspeed_tpu.serving.scheduler import (
    ServingDraining,
    ServingOverloaded,
    ServingQueueFull,
)
from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------------
# codec: exceptions
# ---------------------------------------------------------------------------

#: exception classes that reconstruct as THEMSELVES across the wire
#: (everything else degrades to RuntimeError with the original type
#: name in the message).  The serving triple carries ``retry_after`` —
#: the client's backoff hint — through ``__init__(msg, retry_after=)``.
WIRE_EXCEPTIONS: Dict[str, type] = {
    "ServingQueueFull": ServingQueueFull,
    "ServingOverloaded": ServingOverloaded,
    "ServingDraining": ServingDraining,
    "ReplicaDeadError": ReplicaDeadError,
    "TenantThrottled": TenantThrottled,
    "InjectedFault": InjectedFault,
    "ValueError": ValueError,
    "KeyError": KeyError,
}


def encode_error(exc: BaseException) -> Dict[str, Any]:
    """Serve-side half of the exception codec."""
    return {
        "err": str(exc),
        "type": type(exc).__name__,
        "retry_after": getattr(exc, "retry_after", None),
    }


def raise_wire(resp: Dict[str, Any]) -> None:
    """Client-side half: reconstruct the exact exception class when it
    is part of the wire taxonomy, preserving ``retry_after``."""
    name = resp.get("type", "RuntimeError")
    cls = WIRE_EXCEPTIONS.get(name)
    if cls is None:
        raise RuntimeError(f"{name}: {resp['err']}")
    if issubclass(cls, ServingQueueFull):
        raise cls(resp["err"], retry_after=resp.get("retry_after"))
    raise cls(resp["err"])


# ---------------------------------------------------------------------------
# codec: op dispatch (shared by the in-process transport and the
# stream-serve loop — the "one codec" contract)
# ---------------------------------------------------------------------------

def dispatch(rep, cmd: Dict[str, Any]) -> Dict[str, Any]:
    """Map one op dict onto the replica surface; returns a JSON-plain
    ``{"ok": ...}`` or ``{"err": ..., "type": ..., "retry_after": ...}``
    response.  ``rep`` is anything with the LocalReplica surface (the
    worker side wraps its engine in a LocalReplica so migration fault
    sites and dead-replica semantics come along for free)."""
    op = cmd.get("op")
    try:
        if op == "submit":
            rid = rep.submit(
                np.asarray(cmd["prompt"], np.int32),
                client_key=cmd.get("client_key"),
                **cmd.get("kw", {}),
            )
            return {"ok": int(rid)}
        if op == "step":
            return {"ok": bool(rep.step())}
        if op == "has_work":
            return {"ok": bool(rep.has_work())}
        if op == "pop":
            return {"ok": {
                str(rid): {
                    "tokens": [int(t) for t in r.tokens()],
                    "finish_reason": r.finish_reason,
                    "first_token_time": r.first_token_time,
                    "submit_time": r.submit_time,
                    "retry_after": r.retry_after,
                }
                for rid, r in rep.pop_results().items()
            }}
        if op == "cancel":
            return {"ok": bool(rep.cancel(int(cmd["id"])))}
        if op == "result":
            r = rep.result(int(cmd["id"]))
            if r is None:
                return {"ok": None}
            finished = r.finish_time is not None
            return {"ok": {
                "first_token": r.first_token_time is not None,
                "finished": finished,
                "finish_time": r.finish_time,
                "first_token_time": r.first_token_time,
                "submit_time": r.submit_time,
                "finish_reason": r.finish_reason,
                "retry_after": getattr(r, "retry_after", None),
                # the full token view only once retired: the router may
                # surface a deduped finished request's result directly
                "tokens": ([int(t) for t in r.tokens()]
                           if finished else None),
            }}
        if op == "partial":
            # streaming pull: tokens generated SO FAR for an in-flight
            # request (the HTTP front-door's chunk source)
            r = rep.result(int(cmd["id"]))
            return {"ok": None if r is None else {
                "generated": [int(t) for t in getattr(r, "generated", [])],
                "finished": r.finish_time is not None,
                "finish_reason": r.finish_reason,
            }}
        if op == "ck":
            rid = rep.client_request_id(str(cmd["key"]))
            return {"ok": None if rid is None else int(rid)}
        if op == "recover":
            return {"ok": [int(r) for r in rep.engine.recover()]}
        if op == "affinity":
            return {"ok": float(rep.kv_affinity(
                np.asarray(cmd["prompt"], np.int32),
                session_id=cmd.get("session_id"),
            ))}
        if op == "export":
            return {"ok": rep.export_sessions(cmd["dir"])}
        if op == "import":
            return {"ok": rep.import_sessions(cmd["dir"])}
        if op == "sweep":
            return {"ok": int(rep.sweep_sessions(
                float(cmd.get("now", time.monotonic()))))}
        if op == "kvstats":
            kv = getattr(rep, "kv_stats", None)
            if kv is not None:
                return {"ok": kv()}
            pool = getattr(getattr(rep, "engine", None), "pool", None)
            return {"ok": pool.stats()
                    if pool is not None and hasattr(pool, "sessions") else {}}
        if op == "health":
            est = rep.estimate_ttft(int(cmd.get("len", 8)))
            return {"ok": {
                "depth": int(rep.queue_depth()),
                "level": int(rep.degrade_level()),
                "draining": bool(rep.draining()),
                "est": est if est is None else float(est),
            }}
        if op == "stats":
            return {"ok": _json_safe(rep.stats())}
        if op == "exit":
            return {"ok": True}
        return {"err": f"unknown op {op!r}", "type": "ValueError",
                "retry_after": None}
    except Exception as e:  # noqa: BLE001 — becomes the wire error
        return encode_error(e)


def _json_safe(obj):
    """Best-effort scrub of numpy scalars out of a stats tree."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return [_json_safe(v) for v in obj.tolist()]
    return obj


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

MAGIC = b"DSRP"
_HEADER = struct.Struct(">4sII")  # magic, payload len, crc32
MAX_FRAME_BYTES = 64 * 1024 * 1024


class TransportFrameError(RuntimeError):
    """A frame failed to parse: short header, bad magic, oversized or
    short payload, crc mismatch, or non-JSON bytes.  The transport maps
    this to ``ReplicaDeadError`` — a torn frame means the peer (or the
    pipe between) can no longer be trusted."""


def write_frame(wfile, obj: Any) -> None:
    """Encode + frame one message.  Fault site ``transport.frame``."""
    faults.check("transport.frame")
    faults.check_latency("transport.frame")
    faults.check_stall("transport.frame")
    payload = json.dumps(obj).encode("utf-8")
    import zlib

    wfile.write(_HEADER.pack(MAGIC, len(payload), zlib.crc32(payload)))
    wfile.write(payload)
    wfile.flush()


def read_frame(rfile) -> Any:
    """Read one framed message; ``EOFError`` on a clean EOF at a frame
    boundary, :class:`TransportFrameError` on any torn/garbage frame."""
    header = rfile.read(_HEADER.size)
    if not header:
        raise EOFError("transport: EOF")
    if len(header) < _HEADER.size:
        raise TransportFrameError(
            f"torn frame header ({len(header)}/{_HEADER.size} bytes)")
    magic, length, crc = _HEADER.unpack(header)
    if magic != MAGIC:
        raise TransportFrameError(f"bad frame magic {magic!r}")
    if length > MAX_FRAME_BYTES:
        raise TransportFrameError(f"frame length {length} exceeds cap")
    payload = rfile.read(length)
    if len(payload) < length:
        raise TransportFrameError(
            f"torn frame payload ({len(payload)}/{length} bytes)")
    import zlib

    if zlib.crc32(payload) != crc:
        raise TransportFrameError("frame crc mismatch")
    try:
        return json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise TransportFrameError(f"frame payload not JSON: {e}") from e


# ---------------------------------------------------------------------------
# transports
# ---------------------------------------------------------------------------

class InProcTransport:
    """Direct dispatch against an in-process replica — the codec's
    identity path.  ``kill``/``restart`` forward to the backing
    replica, so chaos tests keep their exact semantics."""

    def __init__(self, replica):
        self.local_replica = replica

    def alive(self) -> bool:
        return self.local_replica.alive()

    def call(self, cmd: Dict[str, Any]) -> Any:
        resp = dispatch(self.local_replica, cmd)
        if "err" in resp:
            raise_wire(resp)
        return resp["ok"]

    def kill(self, reason: str = "killed") -> None:
        self.local_replica.kill(reason)

    def restart(self) -> List[int]:
        return self.local_replica.restart()

    def close(self) -> None:
        pass

    @property
    def kills(self) -> int:
        return self.local_replica.kills

    @property
    def first_rc(self):
        return None


class StreamTransport:
    """The framed codec over any (readable, writable) binary stream
    pair.  EOF and torn frames mark the transport dead and raise
    ``ReplicaDeadError`` — there is no recovery short of ``restart()``
    (which subclasses that own the peer implement)."""

    def __init__(self, rfile, wfile, name: str = "stream",
                 local_replica=None):
        self._rfile = rfile
        self._wfile = wfile
        self.name = name
        self._dead = False
        self.kills = 0
        self.first_rc: Optional[int] = None
        self._lock = threading.Lock()
        # set when the peer is an in-process serve thread (tests): lets
        # TransportReplica expose ``.engine`` for white-box assertions
        self.local_replica = local_replica

    def alive(self) -> bool:
        return not self._dead

    def _mark_dead(self, why: str) -> None:
        if not self._dead:
            self._dead = True
            self.kills += 1
            if self.first_rc is None:
                self.first_rc = self._peer_rc()
        self._close_files()
        raise ReplicaDeadError(f"replica {self.name}: {why}")

    def _peer_rc(self) -> Optional[int]:
        return None

    def _close_files(self) -> None:
        for f in (self._rfile, self._wfile):
            try:
                if f is not None:
                    f.close()
            except OSError:
                pass

    def call(self, cmd: Dict[str, Any]) -> Any:
        with self._lock:
            if self._dead:
                raise ReplicaDeadError(f"replica {self.name} transport is dead")
            try:
                write_frame(self._wfile, cmd)
                resp = read_frame(self._rfile)
            except (EOFError, TransportFrameError, BrokenPipeError,
                    OSError, ValueError) as e:
                self._mark_dead(f"{type(e).__name__}: {e}")
        if "err" in resp:
            raise_wire(resp)
        return resp["ok"]

    def kill(self, reason: str = "killed") -> None:
        """Sever the stream (tests); process transports override with a
        real SIGKILL."""
        self._dead = True
        self.kills += 1
        self._close_files()
        logger.warning(f"fleet: transport {self.name} killed ({reason})")

    def restart(self) -> List[int]:
        raise ReplicaDeadError(
            f"replica {self.name}: stream transport cannot respawn its peer")

    def close(self) -> None:
        if self._dead:
            return
        try:
            with self._lock:
                write_frame(self._wfile, {"op": "exit"})
                read_frame(self._rfile)
        except (EOFError, TransportFrameError, OSError, ValueError,
                ReplicaDeadError):
            pass
        self._dead = True
        self._close_files()


class SocketTransport(StreamTransport):
    """:class:`StreamTransport` over a connected socket."""

    def __init__(self, sock: socket.socket, name: str = "socket",
                 local_replica=None):
        self._sock = sock
        super().__init__(sock.makefile("rb"), sock.makefile("wb"),
                         name=name, local_replica=local_replica)

    @classmethod
    def connect(cls, host: str, port: int, name: str = "socket",
                timeout: Optional[float] = None) -> "SocketTransport":
        sock = socket.create_connection((host, port), timeout=timeout)
        return cls(sock, name=name)

    def _close_files(self) -> None:
        super()._close_files()
        try:
            self._sock.close()
        except OSError:
            pass


class ProcessTransport(StreamTransport):
    """The framed codec over a child process's stdio pipes.  The child
    runs :func:`serve_stdio` (see ``tools/fleet_chaos.py --role
    worker``).  ``restart()`` respawns over the same journal directory
    (sans fault plan) and replays via the ``recover`` op — the
    parent-side half of the lossless-restart contract."""

    def __init__(self, name: str, argv: List[str],
                 fault_plan: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None):
        self.name = name
        self._argv = list(argv)
        self._base_env = dict(os.environ if env is None else env)
        self.proc: Optional[subprocess.Popen] = None
        super().__init__(None, None, name=name)
        self._spawn(fault_plan)

    def _spawn(self, fault_plan: Optional[str] = None) -> None:
        env = dict(self._base_env)
        env.pop("DS_FAULT_PLAN", None)
        if fault_plan is not None:
            env["DS_FAULT_PLAN"] = fault_plan
        self.proc = subprocess.Popen(
            self._argv, env=env, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        self._rfile = self.proc.stdout
        self._wfile = self.proc.stdin
        self._dead = False

    def alive(self) -> bool:
        return (not self._dead and self.proc is not None
                and self.proc.poll() is None)

    def _peer_rc(self) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            return self.proc.poll()

    def call(self, cmd: Dict[str, Any]) -> Any:
        if self.proc is None or self.proc.poll() is not None:
            if not self._dead:
                with self._lock:
                    if not self._dead:
                        self._mark_dead(f"process exited rc={self.proc.poll()}"
                                        if self.proc is not None
                                        else "never spawned")
            raise ReplicaDeadError(f"replica {self.name} process is gone")
        return super().call(cmd)

    def kill(self, reason: str = "killed") -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()
        if self.first_rc is None and self.proc is not None:
            self.first_rc = self.proc.poll()
        super().kill(reason)

    def restart(self) -> List[int]:
        if self.proc is not None and self.first_rc is None:
            self.first_rc = self.proc.poll()
        self._spawn()  # same argv / journal dir, no fault plan
        return self.call({"op": "recover"})

    def close(self) -> None:
        super().close()
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()


# ---------------------------------------------------------------------------
# serve loops
# ---------------------------------------------------------------------------

def serve_stream(rep, rfile, wfile) -> None:
    """Serve one framed op stream against a replica until ``exit``, a
    clean EOF, or a torn frame (the server closes; the client's next
    read EOFs into ``ReplicaDeadError``)."""
    while True:
        try:
            cmd = read_frame(rfile)
        except EOFError:
            return
        except TransportFrameError as e:
            logger.warning(f"transport: dropping connection on {e}")
            return
        resp = dispatch(rep, cmd)
        try:
            write_frame(wfile, resp)
        except (BrokenPipeError, OSError):
            return
        if cmd.get("op") == "exit":
            return


def serve_socket(rep, sock: socket.socket) -> None:
    with sock:
        serve_stream(rep, sock.makefile("rb"), sock.makefile("wb"))


def serve_stdio(rep) -> None:
    """Child-process entry: claim fd 0/1 as the private framed channel
    BEFORE anything logs — fd 1 is re-pointed at stderr so framework
    prints cannot corrupt the framing (the PR 14 discipline)."""
    rfile = os.fdopen(os.dup(0), "rb")
    wfile = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    serve_stream(rep, rfile, wfile)


class LoopbackTransport(SocketTransport):
    """A REAL socketpair + serve thread over an in-process replica —
    the full framed codec without a child process (the both-transports
    test rig).  ``kill``/``restart`` compose the stream semantics with
    the backing replica's, so the supervisor's kill → restart → replay
    cycle behaves exactly as it does over a child process."""

    def __init__(self, rep, name: Optional[str] = None):
        self._rep = rep
        self._serve_thread: Optional[threading.Thread] = None
        sock = self._start_serve(name or rep.name)
        super().__init__(sock, name=name or rep.name, local_replica=rep)

    def _start_serve(self, name: str) -> socket.socket:
        a, b = socket.socketpair()
        self._serve_thread = threading.Thread(
            target=serve_socket, args=(self._rep, b), daemon=True,
            name=f"serve-{name}")
        self._serve_thread.start()
        return a

    def kill(self, reason: str = "killed") -> None:
        # kill the replica FIRST (drop the engine — only journal-durable
        # state survives), then sever the stream
        if self._rep.alive():
            self._rep.kill(reason)
        super().kill(reason)

    def restart(self) -> List[int]:
        with self._lock:
            self._close_files()
            replayed = self._rep.restart()
            sock = self._start_serve(self.name)
            self._sock = sock
            self._rfile = sock.makefile("rb")
            self._wfile = sock.makefile("wb")
            self._dead = False
            return replayed

    def close(self) -> None:
        super().close()
        if self._serve_thread is not None:
            # the closed socketpair EOFs the serve loop; reap it
            self._serve_thread.join(timeout=5)
            self._serve_thread = None


def loopback_transport(rep, name: Optional[str] = None) -> LoopbackTransport:
    return LoopbackTransport(rep, name=name)


# ---------------------------------------------------------------------------
# the replica over a transport
# ---------------------------------------------------------------------------

class _WireResult:
    """Client-side view of a retired request (the fields the router and
    the fleet tests consume)."""

    def __init__(self, d: Dict[str, Any]):
        self._tokens = d["tokens"]
        self.finish_reason = d["finish_reason"]
        self.first_token_time = d["first_token_time"]
        self.submit_time = d["submit_time"]
        self.retry_after = d.get("retry_after")
        # ``result`` op views carry the liveness gates the router's
        # client_key dedup path reads; pop records are retired by
        # construction, so default them finished
        self.finish_time = d.get("finish_time", d["submit_time"])
        self.first_token = bool(d.get("first_token",
                                      d["first_token_time"] is not None))
        self.finished = bool(d.get("finished", True))

    def tokens(self):
        return self._tokens


class TransportReplica:
    """The full fleet replica surface over a :class:`Transport` — the
    router, supervisor and autoscaler cannot tell it from a
    :class:`LocalReplica`.  Dead-transport reads return the same
    neutral values LocalReplica returns for a dead engine; submit/step
    raise ``ReplicaDeadError`` (safe-retry signal)."""

    def __init__(self, name: str, transport):
        self.name = str(name)
        self.transport = transport

    # -- white-box access (in-process transports only) --------------------
    @property
    def engine(self):
        rep = getattr(self.transport, "local_replica", None)
        return None if rep is None else rep.engine

    @property
    def kills(self) -> int:
        return self.transport.kills

    @property
    def first_rc(self):
        return self.transport.first_rc

    # -- liveness ---------------------------------------------------------
    def alive(self) -> bool:
        return self.transport.alive()

    def kill(self, reason: str = "killed") -> None:
        self.transport.kill(reason)

    def restart(self) -> List[int]:
        return self.transport.restart()

    def close(self) -> None:
        self.transport.close()

    # -- request surface --------------------------------------------------
    def submit(self, prompt, client_key=None, **kw) -> int:
        return self.transport.call({
            "op": "submit", "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
            "client_key": client_key, "kw": kw,
        })

    def cancel(self, request_id: int) -> bool:
        if not self.alive():
            return False
        try:
            return bool(self.transport.call({"op": "cancel",
                                             "id": int(request_id)}))
        except ReplicaDeadError:
            return False

    def step(self) -> bool:
        return bool(self.transport.call({"op": "step"}))

    def has_work(self) -> bool:
        if not self.alive():
            return False
        return bool(self.transport.call({"op": "has_work"}))

    def pop_results(self) -> Dict[int, Any]:
        if not self.alive():
            return {}
        return {int(rid): _WireResult(d)
                for rid, d in self.transport.call({"op": "pop"}).items()}

    def result(self, request_id: int) -> Optional[Any]:
        if not self.alive():
            return None
        d = self.transport.call({"op": "result", "id": int(request_id)})
        return None if d is None else _WireResult(d)

    def partial_result(self, request_id: int) -> Optional[Dict[str, Any]]:
        if not self.alive():
            return None
        return self.transport.call({"op": "partial", "id": int(request_id)})

    def first_token_seen(self, request_id: int) -> bool:
        r = self.result(request_id)
        return bool(r and r.first_token)

    def client_request_id(self, client_key: str) -> Optional[int]:
        if not self.alive():
            return None
        return self.transport.call({"op": "ck", "key": str(client_key)})

    # -- load / health feeds ----------------------------------------------
    def estimate_ttft(self, prompt_len: int) -> Optional[float]:
        if not self.alive():
            return None
        return self.transport.call({"op": "health",
                                    "len": int(prompt_len)})["est"]

    def kv_affinity(self, prompt, session_id: Optional[str] = None) -> float:
        if not self.alive():
            return 0.0
        return float(self.transport.call({
            "op": "affinity",
            "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
            "session_id": session_id,
        }))

    def queue_depth(self) -> int:
        if not self.alive():
            return 0
        return int(self.transport.call({"op": "health"})["depth"])

    def degrade_level(self) -> int:
        if not self.alive():
            return 0
        return int(self.transport.call({"op": "health"})["level"])

    def draining(self) -> bool:
        if not self.alive():
            return False
        return bool(self.transport.call({"op": "health"})["draining"])

    def stats(self) -> Dict[str, Any]:
        if not self.alive():
            return {"dead": True}
        return self.transport.call({"op": "stats"})

    # -- live migration (docs/serving.md §Elastic fleet) ------------------
    def export_sessions(self, dest_dir: str) -> List[str]:
        return self.transport.call({"op": "export", "dir": dest_dir})

    def import_sessions(self, src_dir: str) -> Dict[str, int]:
        return self.transport.call({"op": "import", "dir": src_dir})

    def sweep_sessions(self, now: float) -> int:
        if not self.alive():
            return 0
        return int(self.transport.call({"op": "sweep", "now": float(now)}))

    def kv_stats(self) -> Dict[str, Any]:
        if not self.alive():
            return {}
        return self.transport.call({"op": "kvstats"})


def wrap_replica(rep, transport: str = "inproc"):
    """Wrap a LocalReplica behind the named transport (``inproc`` |
    ``socket``) — the rig the fleet suites use to prove the router /
    supervisor / autoscaler run unchanged over both."""
    if transport == "inproc":
        return TransportReplica(rep.name, InProcTransport(rep))
    if transport == "socket":
        return TransportReplica(rep.name, loopback_transport(rep))
    raise ValueError(f"unknown transport {transport!r}")


__all__ = [
    "WIRE_EXCEPTIONS", "encode_error", "raise_wire", "dispatch",
    "TransportFrameError", "write_frame", "read_frame", "MAGIC",
    "MAX_FRAME_BYTES", "InProcTransport", "StreamTransport",
    "SocketTransport", "ProcessTransport", "serve_stream", "serve_socket",
    "serve_stdio", "loopback_transport", "TransportReplica", "wrap_replica",
]
