"""serving/ — continuous-batching serving engine (docs/serving.md).

The traffic-shaped rebuild of the reference's inference layer: a
fixed-shape slot-pool KV cache (``pool.py``), a token-granularity
admission/retirement scheduler with chunked prefill (``scheduler.py``),
and a ``submit()/step()/drain()`` engine that serves any churning
request stream against exactly one compiled decode executable
(``engine.py``).

    eng = deepspeed_tpu.init_inference(model="gpt2-xl", ...)
    srv = ServingEngine(eng, num_slots=8, prefill_chunk=128)
    rid = srv.submit(prompt_tokens, max_new_tokens=64)
    while srv.step():
        pass
    print(srv.result(rid).tokens())
"""
from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.pool import SlotKVPool, SlotPoolError
from deepspeed_tpu.serving.scheduler import (
    ContinuousScheduler,
    Request,
    ServingQueueFull,
)

__all__ = [
    "ServingEngine",
    "SlotKVPool",
    "SlotPoolError",
    "ContinuousScheduler",
    "Request",
    "ServingQueueFull",
]
