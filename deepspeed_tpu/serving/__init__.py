"""serving/ — continuous-batching serving engine (docs/serving.md).

The traffic-shaped rebuild of the reference's inference layer: a
fixed-shape slot-pool KV cache (``pool.py``), a token-granularity
admission/retirement scheduler with chunked prefill, priority tiers and
a load-shedding admission controller (``scheduler.py``), a write-ahead
request journal for crash recovery (``journal.py``), a SIGTERM graceful
drain watchdog (``watchdog.py``), and a ``submit()/step()/drain()``
engine that serves any churning request stream against exactly one
compiled decode executable (``engine.py``).

    eng = deepspeed_tpu.init_inference(model="gpt2-xl", ...)
    srv = ServingEngine(eng, num_slots=8, prefill_chunk=128,
                        journal_dir="/ckpt/serving-journal")
    srv.install_watchdog()          # SIGTERM -> drain -> exit 43
    srv.recover()                   # replay a crashed engine's journal
    rid = srv.submit(prompt_tokens, max_new_tokens=64)
    while srv.step():
        pass
    print(srv.result(rid).tokens())
"""
from deepspeed_tpu.serving.engine import ServingEngine
from deepspeed_tpu.serving.fleet import (
    FleetOverloaded,
    FleetRouter,
    LocalReplica,
    ReplicaDeadError,
    ReplicaSupervisor,
)
from deepspeed_tpu.serving.frontdoor import (
    FrontDoor,
    TenantRegistry,
    TenantThrottled,
    TransportFrameError,
    TransportReplica,
    journal_tenant_totals,
    wrap_replica,
)
from deepspeed_tpu.serving.journal import JournalError, RequestJournal
from deepspeed_tpu.serving.kvcache import PagedKVPool
from deepspeed_tpu.serving.pool import SlotKVPool, SlotPoolError
from deepspeed_tpu.serving.scheduler import (
    PRIORITY_HIGH,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    ContinuousScheduler,
    DegradationLadder,
    Request,
    ServingDraining,
    ServingOverloaded,
    ServingQueueFull,
)
from deepspeed_tpu.serving.watchdog import ServingWatchdog

__all__ = [
    "ServingEngine",
    "FleetRouter",
    "FleetOverloaded",
    "LocalReplica",
    "ReplicaDeadError",
    "ReplicaSupervisor",
    "SlotKVPool",
    "SlotPoolError",
    "PagedKVPool",
    "ContinuousScheduler",
    "DegradationLadder",
    "Request",
    "RequestJournal",
    "JournalError",
    "ServingQueueFull",
    "ServingOverloaded",
    "ServingDraining",
    "ServingWatchdog",
    "FrontDoor",
    "TenantRegistry",
    "TenantThrottled",
    "TransportFrameError",
    "TransportReplica",
    "journal_tenant_totals",
    "wrap_replica",
    "PRIORITY_HIGH",
    "PRIORITY_NORMAL",
    "PRIORITY_LOW",
]
