"""Paged KV subsystem: page pool + allocator, shared-prefix dedup,
copy-on-write pages, and durable session KV (docs/serving.md §Paged KV
& prefix caching)."""
from deepspeed_tpu.serving.kvcache.pages import GARBAGE_PAGE, PagedKVPool
from deepspeed_tpu.serving.kvcache.prefix import PrefixEntry, PrefixIndex
from deepspeed_tpu.serving.kvcache.sessions import Session, SessionStore

__all__ = [
    "GARBAGE_PAGE",
    "PagedKVPool",
    "PrefixEntry",
    "PrefixIndex",
    "Session",
    "SessionStore",
]
