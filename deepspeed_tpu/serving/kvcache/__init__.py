"""Paged KV subsystem: page pool + allocator, shared-prefix dedup,
copy-on-write pages, durable session KV, and hierarchical HBM → host →
disk page tiering (docs/serving.md §Paged KV & prefix caching, §KV
tiering)."""
from deepspeed_tpu.serving.kvcache.pages import GARBAGE_PAGE, PagedKVPool
from deepspeed_tpu.serving.kvcache.prefix import PrefixEntry, PrefixIndex
from deepspeed_tpu.serving.kvcache.sessions import Session, SessionStore
from deepspeed_tpu.serving.kvcache.tiers import PageTierManager, TierEntry

__all__ = [
    "GARBAGE_PAGE",
    "PagedKVPool",
    "PageTierManager",
    "PrefixEntry",
    "PrefixIndex",
    "Session",
    "SessionStore",
    "TierEntry",
]
